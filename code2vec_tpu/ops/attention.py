"""Masked single-query attention over the bag of path-contexts.

This is the core of code2vec: a single trainable query vector scores every
context, invalid (padding) contexts get -inf via an additive log-mask, and
the code vector is the attention-weighted sum. Exact math from the
reference (tensorflow_model.py:253-262 / keras_attention_layer.py:52-63):

    w      = tanh(ctx @ W) @ a            # (B, M)
    w     += log(mask)                    # -inf on invalid contexts
    attn   = softmax(w, axis=contexts)
    codev  = sum(attn * tanh(ctx @ W), axis=contexts)

Kept as a standalone op so the context axis can be sharded: with contexts
split over a mesh axis the softmax combines per-shard (max, sum-exp)
partials with collectives — the degenerate single-query form of ring
attention (SURVEY.md §5 long-context plan). `axis_name=None` is the
single-shard path used under plain jit/GSPMD.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def masked_single_query_attention(
    transformed: jax.Array,       # (B, M_local, D) already tanh(ctx @ W)
    attention_param: jax.Array,   # (D,)
    context_valid_mask: jax.Array,  # (B, M_local) float {0,1}
    axis_name: Optional[str] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Returns (code_vectors (B, D), attention_weights (B, M_local)).

    Softmax runs in float32 regardless of the compute dtype. When
    `axis_name` names a mesh axis over which the context dimension is
    sharded, the max/sum-exp/weighted-sum reductions are combined across
    shards with pmax/psum so the result equals the unsharded computation.
    """
    scores = jnp.einsum(
        "bmd,d->bm", transformed, attention_param.astype(transformed.dtype),
        preferred_element_type=jnp.float32)           # (B, M)
    # Additive log-mask (reference: tensorflow_model.py:256-258). Where the
    # mask is 0 this is -inf; jnp.where keeps the gradient clean.
    neg_inf = jnp.asarray(-jnp.inf, dtype=scores.dtype)
    scores = jnp.where(context_valid_mask > 0, scores, neg_inf)

    # The max shift is numerical stabilization only; its gradient cancels
    # exactly in softmax, so stop_gradient (also: pmax has no AD rule).
    local_max = jax.lax.stop_gradient(jnp.max(scores, axis=1, keepdims=True))
    if axis_name is not None:
        local_max = jax.lax.pmax(local_max, axis_name)
    # Guard all-invalid rows (padded eval examples): exp(-inf - -inf) = nan,
    # so pin the max to 0 there; the row's weights become 0/sum=0 -> handled
    # by the caller's example_valid mask.
    safe_max = jnp.where(jnp.isfinite(local_max), local_max, 0.0)
    unnorm = jnp.exp(scores - safe_max)                      # (B, M)
    denom = jnp.sum(unnorm, axis=1, keepdims=True)           # (B, 1)
    if axis_name is not None:
        denom = jax.lax.psum(denom, axis_name)
    attention = unnorm / jnp.maximum(denom, 1e-30)           # (B, M)

    code_vectors = jnp.einsum(
        "bm,bmd->bd", attention.astype(transformed.dtype), transformed,
        preferred_element_type=jnp.float32)                  # (B, D)
    if axis_name is not None:
        code_vectors = jax.lax.psum(code_vectors, axis_name)
    return code_vectors, attention
