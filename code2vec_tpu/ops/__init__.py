from code2vec_tpu.ops.attention import masked_single_query_attention  # noqa: F401
