"""Blockwise top-k over the target-name classifier without materializing
the full logit row.

The code2vec prediction head is a (B, V) matmul against a ~246K-row
target table followed by top-k; at batch 1024 the logits alone are
~1 GB/batch of HBM traffic written once and read twice (top-k + CE) —
BENCH_ROOFLINE.md shows the hot ops are bandwidth-bound, so never
materializing that row is a direct lever. These kernels stream the
target table in row blocks, compute each block's (B, block) logit slice,
and fold it into a running `lax.top_k` merge (plus an optional running
logsumexp for the eval CE), so peak live logits are (B, block) instead
of (B, V).

Exactness: `lax.top_k` breaks ties toward the lower index. The merge
concatenates [running(k), block] with blocks visited in ascending-index
order, so among equal values the running entries (strictly lower global
indices, themselves tie-ordered ascending) occupy earlier positions —
position order equals global index order, and the merged result is
IDENTICAL (indices and values, bitwise) to `lax.top_k` over the full
logits. The one documented exception: rows whose finite-entry count is
below k may pick different -inf-valued indices (the init sentinel is
value -inf, index 0); callers clamp k to the real vocab size, so this
never happens in practice. Pinned in tests/test_quant.py.

The table blocks may be quantized with per-row symmetric scales
(ops/quant.py): int8 or fp8 blocks cast straight into the compute
dtype, int4-packed blocks (`int4_dim`) are nibble-unpacked AFTER the
block slice, and in every case the dequant is fused after the block
matmul (accumulation in the compute dtype, scales applied to the f32
block logits) — the table moves through HBM at one byte (int8/fp8) or
half a byte (int4) per weight.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp


class BlockTopKOutputs(NamedTuple):
    values: jax.Array   # (B, k) f32, sorted descending
    indices: jax.Array  # (B, k) i32 global target-vocab ids
    lse: jax.Array      # (B,) f32 logsumexp over all live logits


def _merge_top_k(vals: jax.Array, idx: jax.Array, block_vals: jax.Array,
                 block_idx: jax.Array, k: int) -> Tuple[jax.Array, jax.Array]:
    """Fold one block's (B, block) logits into the running (B, k) top-k.
    Concatenation order [running, block] is what makes ties resolve to
    the globally-lowest index (see module docstring)."""
    cat_v = jnp.concatenate([vals, block_vals], axis=1)
    cat_i = jnp.concatenate([idx, block_idx], axis=1)
    top_v, pos = jax.lax.top_k(cat_v, k)
    return top_v, jnp.take_along_axis(cat_i, pos, axis=1)


def _fold_lse(run_max: jax.Array, run_sum: jax.Array,
              block_logits: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """One streaming-logsumexp step: rescale the running sum to the new
    max and add the block's sum-exp. -inf (masked) entries contribute 0;
    the isfinite guard keeps the first block's empty running term
    (max=-inf) from producing exp(-inf - -inf) = nan."""
    block_max = jnp.max(block_logits, axis=-1)
    new_max = jnp.maximum(run_max, block_max)
    safe_new = jnp.where(jnp.isfinite(new_max), new_max, 0.0)
    rescale = jnp.where(jnp.isfinite(run_max),
                        jnp.exp(run_max - safe_new), 0.0)
    run_sum = (run_sum * rescale
               + jnp.sum(jnp.exp(block_logits - safe_new[:, None]), axis=-1))
    return new_max, run_sum


def blockwise_top_k_from_logits(logits: jax.Array, k: int,
                                block_cols: int
                                ) -> Tuple[jax.Array, jax.Array]:
    """Top-k of precomputed (B, V) logits streamed in column blocks.

    Parity-test surface for the merge loop (the production paths below
    never hold full logits); returns exactly what
    `jax.lax.top_k(logits, k)` returns, per the tie argument in the
    module docstring.
    """
    b, v = logits.shape
    k = min(k, v)
    block_cols = max(1, min(int(block_cols), v))
    vals = jnp.full((b, k), -jnp.inf, logits.dtype)
    idx = jnp.zeros((b, k), jnp.int32)
    for start in range(0, v, block_cols):
        stop = min(start + block_cols, v)
        ids = jnp.arange(start, stop, dtype=jnp.int32)
        vals, idx = _merge_top_k(
            vals, idx, logits[:, start:stop],
            jnp.broadcast_to(ids[None, :], (b, stop - start)), k)
    return vals, idx


def blockwise_matmul_top_k(
    code_vectors: jax.Array,          # (B, D) f32
    target_table: jax.Array,          # (V, D) f32 — or int8 with `scales`
    k: int,
    block_rows: int,
    *,
    scales: Optional[jax.Array] = None,   # (V, 1) f32 per-row dequant
    valid_rows: Optional[int] = None,     # ids >= this are padding (-inf)
    compute_dtype: jnp.dtype = jnp.float32,
    int4_dim: Optional[int] = None,       # table is int4-packed uint8
    #                                       (V, ceil(int4_dim/2))
) -> BlockTopKOutputs:
    """Streaming `top_k(code_vectors @ target_table.T, k)` + logsumexp.

    The (B, V) logit row is never materialized: a `fori_loop` slides a
    (block_rows, D) window over the table, computes the block's logits
    in `compute_dtype` (f32 accumulation), applies the fused per-row
    dequant when `scales` is given, and merges into the running top-k
    and running logsumexp. The last window is clamped to the table end
    and its already-visited prefix masked to -inf, so any (V, block)
    combination is exact — no table padding, no row read twice live.

    Per-element logit values are the same einsum contraction the full
    path runs (blocking the non-contracted axis does not change each
    output element's reduction over D), which is what makes the indices
    match the full path bitwise (pinned in tests/test_quant.py and
    re-verified on the accuracy-bench eval set by
    experiments/quant_bench.py).
    """
    b = code_vectors.shape[0]
    v = target_table.shape[0]
    k = min(k, v if valid_rows is None else valid_rows)
    block = max(1, min(int(block_rows), v))
    n_blocks = -(-v // block)
    cv = code_vectors.astype(compute_dtype)

    init = (jnp.full((b, k), -jnp.inf, jnp.float32),
            jnp.zeros((b, k), jnp.int32),
            jnp.full((b,), -jnp.inf, jnp.float32),
            jnp.zeros((b,), jnp.float32))

    def body(i, carry):
        vals, idx, run_max, run_sum = carry
        start = jnp.minimum(i * block, v - block)
        tbl = jax.lax.dynamic_slice_in_dim(target_table, start, block, axis=0)
        if int4_dim is not None:
            # packed bytes through HBM; nibbles unpacked on the
            # block-sized slice only (ops/quant.py)
            from code2vec_tpu.ops.quant import unpack_int4
            tbl = unpack_int4(tbl, int4_dim)
        ids = start + jnp.arange(block, dtype=jnp.int32)
        logits = jnp.einsum("bd,vd->bv", cv, tbl.astype(compute_dtype),
                            preferred_element_type=jnp.float32)
        if scales is not None:
            s = jax.lax.dynamic_slice_in_dim(scales, start, block, axis=0)
            logits = logits * s[:, 0][None, :]
        # Clamped-last-block overlap + padded classifier rows -> -inf
        # (never selected: k is clamped to the real vocab, and exp(-inf)
        # contributes 0 to the lse).
        live = ids >= i * block
        if valid_rows is not None:
            live &= ids < valid_rows
        logits = jnp.where(live[None, :], logits, -jnp.inf)
        vals, idx = _merge_top_k(
            vals, idx, logits, jnp.broadcast_to(ids[None, :], logits.shape), k)
        # The CE denominator gets the full eval path's nonfinite guard
        # (safe_logits = where(isfinite, logits, -1e30) in
        # training/step.py): a NaN/Inf logit from blown-up weights must
        # not poison the reported eval loss. Top-k above merges the RAW
        # logits — parity with `lax.top_k` over the full (unclamped)
        # logits is preserved; dead (-inf-masked) entries stay -inf and
        # keep contributing 0 to the lse.
        lse_in = jnp.where(live[None, :] & ~jnp.isfinite(logits),
                           -1e30, logits)
        run_max, run_sum = _fold_lse(run_max, run_sum, lse_in)
        return vals, idx, run_max, run_sum

    vals, idx, run_max, run_sum = jax.lax.fori_loop(0, n_blocks, body, init)
    lse = jnp.where(jnp.isfinite(run_max),
                    jnp.log(jnp.maximum(run_sum, 1e-30)) + run_max, run_max)
    return BlockTopKOutputs(vals, idx, lse)


def gathered_label_logits(code_vectors: jax.Array, target_table: jax.Array,
                          labels: jax.Array, *,
                          scales: Optional[jax.Array] = None,
                          compute_dtype: jnp.dtype = jnp.float32,
                          int4_dim: Optional[int] = None) -> jax.Array:
    """(B,) logit of each row's own label: a B-row gather + dot instead
    of a column of the full logit matrix. Same per-element contraction
    as the blockwise/full matmul, so CE = lse - label_logit matches the
    full path's cross-entropy — including its nonfinite guard: a
    NaN/Inf label logit is substituted with -1e30 exactly as the full
    path's safe_logits would have at that column."""
    rows = jnp.take(target_table, labels, axis=0)          # (B, D)
    if int4_dim is not None:
        from code2vec_tpu.ops.quant import unpack_int4
        rows = unpack_int4(rows, int4_dim)
    logits = jnp.einsum("bd,bd->b", code_vectors.astype(compute_dtype),
                        rows.astype(compute_dtype),
                        preferred_element_type=jnp.float32)
    if scales is not None:
        logits = logits * jnp.take(scales[:, 0], labels, axis=0)
    return jnp.where(jnp.isfinite(logits), logits, -1e30)
