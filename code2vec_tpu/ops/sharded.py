"""Tensor-parallel collective kernels used inside shard_map.

The reference computes everything whole on one device (embedding tables
tensorflow_model.py:204-219; full-vocab logits :225). At pod scale the
three tables (~385M params, BASELINE.md) are row-sharded over the `model`
mesh axis; these kernels implement the sharded compute with explicit XLA
collectives:

- `tp_embedding_lookup`: masked local gather + psum (the vocab-parallel
  embedding pattern — each shard gathers rows it owns, others contribute
  zeros).
- `tp_softmax_ce`: cross-entropy over row-sharded logits via
  pmax/psum-logsumexp, without ever materializing the full (B, V) logits
  on one device.
- `tp_top_k`: local top-k + all_gather + re-top-k, returning global ids.

All functions assume they run inside shard_map with `axis_name` bound.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp


def _shard_offset(num_rows_local: int, axis_name: str) -> jax.Array:
    return jax.lax.axis_index(axis_name) * num_rows_local


def tp_embedding_lookup(table_shard: jax.Array, ids: jax.Array,
                        axis_name: str) -> jax.Array:
    """Gather rows of a row-sharded table by global ids: (..., dim) f32.

    Each shard translates global ids to local ones, gathers in-range rows,
    zeroes the rest, and a psum over `axis_name` reconstructs the full
    lookup (out-of-range shards contribute 0).
    """
    rows_local = table_shard.shape[0]
    offset = _shard_offset(rows_local, axis_name)
    local_ids = ids - offset
    in_range = (local_ids >= 0) & (local_ids < rows_local)
    safe_ids = jnp.clip(local_ids, 0, rows_local - 1)
    gathered = jnp.take(table_shard, safe_ids, axis=0)
    gathered = jnp.where(in_range[..., None], gathered, 0.0)
    return jax.lax.psum(gathered, axis_name)


def tp_logits(code_vectors: jax.Array, target_table_shard: jax.Array,
              compute_dtype=jnp.bfloat16) -> jax.Array:
    """Local logits slice (B, V_local) for a row-sharded classifier."""
    return jnp.einsum(
        "bd,vd->bv", code_vectors.astype(compute_dtype),
        target_table_shard.astype(compute_dtype),
        preferred_element_type=jnp.float32)


def tp_softmax_ce(local_logits: jax.Array, labels: jax.Array,
                  axis_name: str) -> jax.Array:
    """Sparse softmax cross-entropy over row-sharded logits: (B,) f32.

    Numerics identical to an unsharded logsumexp: global max via pmax,
    global sum-exp and the label's logit via psum (the label row lives on
    exactly one shard).
    """
    v_local = local_logits.shape[-1]
    offset = _shard_offset(v_local, axis_name)
    # Max shift is stabilization only — its gradient cancels exactly in
    # logsumexp (d/dm [log Σexp(x-m) + m] = 0), and pmax has no AD rule.
    local_max = jax.lax.stop_gradient(jnp.max(local_logits, axis=-1))  # (B,)
    global_max = jax.lax.pmax(local_max, axis_name)
    sumexp = jnp.sum(jnp.exp(local_logits - global_max[:, None]), axis=-1)
    global_sumexp = jax.lax.psum(sumexp, axis_name)               # (B,)

    local_labels = labels - offset
    in_range = (local_labels >= 0) & (local_labels < v_local)
    safe = jnp.clip(local_labels, 0, v_local - 1)
    label_logit_local = jnp.take_along_axis(
        local_logits, safe[:, None], axis=-1)[:, 0]
    label_logit = jax.lax.psum(
        jnp.where(in_range, label_logit_local, 0.0), axis_name)   # (B,)

    return jnp.log(global_sumexp) + global_max - label_logit


def tp_log_softmax_at_topk(local_logits, axis_name: str):
    """Global (max, logsumexp) pair for normalizing scores of sharded
    logits; returned per example so callers can normalize any slice."""
    local_max = jax.lax.stop_gradient(jnp.max(local_logits, axis=-1))
    global_max = jax.lax.pmax(local_max, axis_name)
    sumexp = jnp.sum(jnp.exp(local_logits - global_max[:, None]), axis=-1)
    global_sumexp = jax.lax.psum(sumexp, axis_name)
    return global_max, jnp.log(global_sumexp) + global_max


def tp_top_k(local_logits: jax.Array, k: int,
             axis_name: str) -> Tuple[jax.Array, jax.Array]:
    """Top-k over row-sharded logits -> (values (B, k), global ids (B, k)).

    Communication is O(B * k * tp) instead of all-gathering the full
    (B, V) logits (1 GB/batch at the reference's 261K-target vocab,
    batch 1024 — SURVEY.md §7 'hard parts').
    """
    v_local = local_logits.shape[-1]
    offset = _shard_offset(v_local, axis_name)
    k_local = min(k, v_local)
    values, idx = jax.lax.top_k(local_logits, k_local)           # (B, k_local)
    global_idx = idx + offset
    all_values = jax.lax.all_gather(values, axis_name, axis=1)    # (B, tp, k_local)
    all_idx = jax.lax.all_gather(global_idx, axis_name, axis=1)
    b = all_values.shape[0]
    flat_vals = all_values.reshape(b, -1)
    flat_idx = all_idx.reshape(b, -1)
    top_vals, pos = jax.lax.top_k(flat_vals, k)                   # (B, k)
    top_idx = jnp.take_along_axis(flat_idx, pos, axis=1)
    return top_vals, top_idx
