"""code2vec_tpu: a TPU-native (JAX/XLA/Flax/pjit) framework for learning
distributed representations of code from bags of AST path-contexts.

Capability parity target: km-Poonacha/code2vec (see /root/repo/SURVEY.md).
The architecture is TPU-first — host-side integer data pipeline, a single
Flax model (instead of the reference's dual TF1/Keras backends,
reference: code2vec.py:7-13), pjit/shard_map sharding over a
``jax.sharding.Mesh`` for data/model/context parallelism, Optax Adam,
Orbax checkpoints — not a translation of the reference's TF graphs.
"""

__version__ = "0.1.0"

import os as _os

if not _os.environ.get("C2V_HOST_WORKER"):
    import jax as _jax

    # Sharding-invariant PRNG: the sharded kernels assume a dropout pattern
    # that is bit-identical whether the batch lives on one device or a mesh
    # (newer jax makes this the only behavior; jax < 0.5 defaults the flag
    # off, which would make GSPMD runs diverge from single-device parity).
    try:
        _jax.config.update("jax_threefry_partitionable", True)
    except Exception:  # flag retired (always-on) in newer jax
        pass
# C2V_HOST_WORKER marks spawned multiprocessing children of the offline
# data pipeline (data/preprocess.py _worker_pool): pure host-side
# split/lookup/pack code that must not pay a jax import (seconds + 100s
# of MB per worker). Such workers never touch jax, so skipping the
# flag-pinning import above is safe.

from code2vec_tpu.config import Config  # noqa: F401
