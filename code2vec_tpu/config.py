"""Configuration for the TPU-native code2vec framework.

Mirrors the knob surface of the reference ``Config`` class
(reference: config.py:46-70 for defaults, config.py:10-44 for CLI flags,
config.py:143-230 for derived path conventions) as a frozen-free dataclass,
and adds TPU-specific knobs (mesh shape, compute dtype, packed-data paths)
that have no reference equivalent (the reference is single-device,
reference: SURVEY.md §2.3).
"""

from __future__ import annotations

import dataclasses
import logging
import math
import os
import sys
from typing import Optional, Tuple


_LOGGER_NAME = "code2vec_tpu"


@dataclasses.dataclass
class Config:
    # -- training schedule (reference: config.py:46-57) --
    num_train_epochs: int = 20
    save_every_epochs: int = 1
    # Checkpoint-and-stop on SIGTERM (preempted TPU workers get a grace
    # window; training/loop.py PreemptionWatcher). No reference analog —
    # the reference loses the epoch in progress on preemption.
    save_on_preemption: bool = True
    # Host-memory watchdog: when process peak RSS crosses this many GB
    # the trainer checkpoints and stops via the same path as SIGTERM
    # (clean resumable stop instead of a kernel OOM kill mid-epoch).
    # 0 disables. No reference analog.
    rss_limit_gb: float = 0.0
    # Non-finite-loss sentinel policy (training/loop.py): "halt"
    # checkpoints via the preemption save path and exits nonzero the
    # first time a log-window average loss is NaN/Inf; "warn" logs and
    # keeps going. No reference analog — a diverged reference run just
    # prints NaN losses forever.
    on_nonfinite_loss: str = "halt"
    # Seconds before a hung serving-side path extraction is killed
    # (serving/extractor_bridge.py). The offline preprocess pipeline has
    # its own kill-timer (data/preprocess.py); this covers the
    # interactive/serving bridge, where one wedged extractor child would
    # otherwise hang the predict request forever. 0 disables.
    extractor_timeout_s: float = 120.0
    # Retries (beyond the first attempt) when the serving-side extractor
    # subprocess fails to launch or crashes (nonzero exit / no output),
    # with bounded exponential backoff between attempts. Distinct from
    # the timeout above: a HUNG child is killed and NOT retried (the
    # next one would likely hang the same way and double the stall);
    # a crashed child usually hit a transient (OOM, fork pressure).
    # 0 disables retries.
    extractor_retries: int = 2
    # Defer the checkpoint commit (Orbax flush wait + cross-host commit
    # barrier + manifest + atomic rename) to a background commit thread
    # (training/checkpoint.py AsyncCommitter) with bounded in-flight
    # depth, so the step loop's save stall shrinks to staging + array
    # dispatch. Crash-atomicity is unchanged: the manifest still lands
    # only after the flush + barrier, and the trainer drains the
    # pipeline before exiting (incl. on preemption). No reference
    # analog.
    async_checkpointing: bool = False
    # Seconds each cross-host checkpoint commit barrier waits for every
    # host before declaring the save failed (a peer died or hung
    # mid-protocol). Generous by default: the barrier only fires after
    # each host's own Orbax flush, so it usually completes in
    # milliseconds; stragglers flushing multi-GB shards to cold storage
    # are the long tail it must tolerate.
    save_barrier_timeout_s: float = 600.0
    # Resume the input pipeline from the checkpoint's data cursor
    # (manifest v3 `data_cursor`): a run resumed from a mid-epoch
    # (preemption) artifact skips the global rows the interrupted epoch
    # already consumed — remapped exactly onto the current host count —
    # so the pass neither skips nor double-reads rows. False re-runs the
    # interrupted epoch from its start (the pre-v3 behavior). Only the
    # packed (.c2vb) pipeline supports the cursor; the streaming text
    # reader always restarts the epoch. No reference analog.
    cursor_resume: bool = True
    train_batch_size: int = 1024
    test_batch_size: int = 1024
    top_k_words_considered_during_prediction: int = 10
    num_batches_to_log_progress: int = 100
    num_train_batches_to_evaluate: int = 1800
    reader_num_workers: int = 6
    shuffle_buffer_size: int = 10000
    csv_buffer_size: int = 100 * 1024 * 1024
    max_to_keep: int = 10

    # -- model hyper-params (reference: config.py:59-70) --
    max_contexts: int = 200
    max_token_vocab_size: int = 1301136
    max_target_vocab_size: int = 261245
    max_path_vocab_size: int = 911417
    # Reference semantics (config.py:64-66): token/path embedding sizes
    # default to DEFAULT_EMBEDDINGS_SIZE; set either explicitly to
    # override just that table. Resolved in __post_init__.
    default_embeddings_size: int = 128
    token_embeddings_size: Optional[int] = None
    path_embeddings_size: Optional[int] = None
    dropout_keep_rate: float = 0.75
    separate_oov_and_pad: bool = False

    # -- CLI-filled run mode (reference: config.py:72-87) --
    predict: bool = False
    # Run the batched prediction HTTP server (serving/server.py) on the
    # loaded/trained model. No reference analog.
    serve: bool = False
    model_save_path: Optional[str] = None
    model_load_path: Optional[str] = None
    train_data_path_prefix: Optional[str] = None
    test_data_path: str = ""
    release: bool = False
    export_code_vectors: bool = False
    save_w2v: Optional[str] = None
    save_t2v: Optional[str] = None
    verbose_mode: int = 1
    logs_path: Optional[str] = None
    use_tensorboard: bool = False

    # -- TPU-native knobs (no reference equivalent) --
    # Mesh axis sizes: data parallel, tensor/model parallel (row-sharded
    # embedding tables + target softmax), context/sequence parallel
    # (shards the MAX_CONTEXTS axis; SURVEY.md §5 long-context plan).
    dp: int = 1
    tp: int = 1
    cp: int = 1
    # Computation dtype for matmuls (params stay float32). bfloat16 maps
    # onto the MXU natively; accumulation is forced to float32.
    compute_dtype: str = "bfloat16"
    # Adam hyper-params (reference uses tf.compat.v1.train.AdamOptimizer()
    # defaults, tensorflow_model.py:231).
    learning_rate: float = 0.001
    adam_beta1: float = 0.9
    adam_beta2: float = 0.999
    adam_eps: float = 1e-8
    # Use the hand-written shard_map tensor-parallel kernels instead of
    # relying purely on GSPMD sharding propagation (only matters if tp>1).
    use_manual_tp_kernels: bool = True
    # Touched-rows (lazy) Adam for the token/path embedding tables
    # (training/sparse_adam.py) instead of a dense update over all ~285M
    # of their parameters. Default OFF on single-chip after measurement:
    # XLA's fused scatter+Adam already runs at the HBM roofline
    # (~670 GB/s on a v5e chip), while the sparse path's sort/permute/
    # segment/scatter chain is bound per *index-array row* (~70-120M
    # rows/s) regardless of how few unique rows a batch touches — it
    # measured slower at java14m scale on both uniform and Zipf(1.07) id
    # distributions. Where it genuinely wins is the manual
    # tensor-parallel path at pod scale: the sparse (ids, grad-rows)
    # all-gather exchanged per step is ~5x smaller than a dense psum of
    # the two table-shaped gradients. Semantics are lazy Adam (TF's
    # LazyAdam; the reference's tf.train.AdamOptimizer
    # (tensorflow_model.py:231) decays moments and updates vars densely
    # even for sparse grads, matching our dense default's cost model).
    use_sparse_embedding_update: bool = False
    # Storage dtype for Adam's first moment (optax mu_dtype). bfloat16
    # halves its HBM traffic in the memory-bound update (+~5% step
    # throughput at java14m scale) with negligible effect on convergence;
    # set "float32" for bit-strict Adam.
    adam_mu_dtype: str = "bfloat16"
    # Storage dtype for Adam's second moment (nu). bfloat16 shaves
    # ~3 GB of HBM traffic per flagship step (+10% examples/sec,
    # BENCH_ROOFLINE.md) and was validated end-to-end: the accuracy
    # harness converges to the same test F1 as with f32 nu. nu sets the
    # per-parameter step size through a sqrt, so its rounding is more
    # consequential than mu's — set "float32" (with adam_mu_dtype
    # "float32") for bit-strict optax.adam. The sparse touched-rows path
    # keeps its nu in f32 regardless (training/sparse_adam.py).
    adam_nu_dtype: str = "bfloat16"
    # PRNG implementation for the per-step dropout key. The TPU hardware
    # generator ("rbg") produces the ~78M dropout bits per flagship step
    # far faster than the default threefry (+~5% step throughput);
    # parameter initialization always uses threefry for reproducibility.
    dropout_prng_impl: str = "rbg"
    # Prefer the packed int32 binary sidecar (.c2vb) when present.
    use_packed_data: bool = True
    # Train from a corpus MANIFEST (data/packed.py ShardedCorpus): a
    # JSON file listing N .c2vb shards — the incumbent pack plus any
    # continuous-training delta shards — presented as one logical row
    # space with the same epoch-keyed global shuffle as a single pack
    # (the PR-6 cursor laws hold verbatim across shard counts). Built
    # and grown with the `corpus` subcommand / pipeline ingest stage.
    # Overrides --data's packed file for training when set.
    train_corpus_manifest: Optional[str] = None
    # Host worker processes for the offline data compile: the on-demand
    # .c2v -> .c2vb pack at training startup (model_facade) and the
    # fused raw-corpus compiler (data/preprocess.py compile_corpus).
    # Output is byte-identical at any worker count; 0 = in-process
    # serial. No reference analog (the reference preprocesses in awk +
    # single-process Python).
    preprocess_workers: int = 0
    # Number of batches the host pipeline keeps in flight ahead of device.
    prefetch_batches: int = 4
    # Double-buffer device transfers (utils/prefetch.py): issue the
    # device_put for batch N+1 before handing batch N to the step loop,
    # so the N+1 transfer overlaps step N's dispatch instead of
    # serializing after it. One extra batch of device memory; the
    # train_input_bound_fraction gauge reads whether it pays off.
    prefetch_double_buffer: bool = False
    # When set, a jax.profiler trace of train batches 10-20 is written
    # here (viewable in TensorBoard / Perfetto).
    profile_dir: Optional[str] = None

    # -- observability (code2vec_tpu/obs; no reference equivalent) --
    # Prometheus text-format snapshot, rewritten atomically at every log
    # boundary (node-exporter textfile-collector style). None disables.
    metrics_file: Optional[str] = None
    # Localhost HTTP port serving the same snapshot at /metrics for a
    # direct Prometheus scrape. 0 disables.
    metrics_port: int = 0
    # JSON heartbeat file {step, epoch, last_loss, wall_time, ...},
    # rewritten atomically each log window so an external watchdog can
    # detect a hung trainer by staleness alone. None disables.
    heartbeat_file: Optional[str] = None
    # Chrome trace-event JSON of host-side spans (data wait / dispatch /
    # loss sync / checkpoint / eval), written when training ends —
    # loadable in Perfetto, complementing the device-side --profile_dir
    # trace. None disables span buffering entirely.
    trace_export: Optional[str] = None
    # -- serving (code2vec_tpu/serving; no reference equivalent — the
    # reference "serves" through a one-file interactive REPL) --
    # HTTP bind for the prediction server (`serve` subcommand /
    # --serve). Port 0 picks a free port (logged + returned by
    # PredictionServer.start); localhost by default — fronting proxies
    # own external exposure/TLS.
    serve_port: int = 8800
    serve_host: str = "127.0.0.1"
    # Rows per coalesced device batch: the dynamic batcher dispatches
    # when this many method rows are pending (or the delay below
    # expires). Also the padded row count of every compiled predict
    # shape — smaller than test_batch_size because serving favors
    # latency over peak throughput.
    serve_batch_size: int = 64
    # Max milliseconds a request waits for batch-mates before the
    # batcher dispatches anyway: the latency price of coalescing on an
    # idle server (a busy server fills batches and never waits).
    serve_max_delay_ms: float = 10.0
    # Continuous batching (serving/batcher.py ContinuousBatcher): admit
    # newly-arrived rows into the next device step of an already-forming
    # slot instead of collect-then-dispatch — a row arriving while a
    # step is on device rides the NEXT step rather than opening a fresh
    # delay window — and parse extractor output straight into the
    # slot's padded (rows, contexts) buffer (zero-copy request path).
    # An idle server behaves exactly like the classic batcher.
    serve_continuous: bool = False
    # Device steps the continuous batcher may keep in flight at once
    # (worker threads; step N+1 launches as soon as step N's dispatch
    # returns). Only read with --serve_continuous.
    serve_inflight_steps: int = 2
    # Padded-context-count buckets for the predict path (comma list;
    # max_contexts is always appended, entries >= max_contexts or not
    # divisible by cp are dropped): every predict batch pads its context
    # axis up to the smallest bucket that holds its deepest valid
    # context, so the number of pjit compilations the serving path can
    # trigger is bounded by len(buckets) instead of one per request
    # shape.
    serve_buckets: str = "32,64,128"
    # LRU prediction-cache capacity (entries), keyed by normalized
    # method-body hash (serving/cache.py). 0 disables.
    serve_cache_entries: int = 4096
    # Warm extractor worker processes kept resident by the serving pool
    # (serving/extractor_pool.py).
    extractor_pool_size: int = 2
    # Seconds the SIGTERM drain waits for in-flight requests before
    # giving up (mirrors the trainer's preemption grace pattern).
    serve_drain_timeout_s: float = 30.0
    # -- serving resilience (serving/admission.py, serving/breaker.py,
    # serving/supervisor.py, serving/swap.py; README "Operating the
    # server") --
    # Default end-to-end deadline per request, in milliseconds. Clients
    # override per request via the `X-Deadline-Ms` header; both are
    # clamped by serve_deadline_max_ms. The deadline propagates through
    # the whole pipeline (extractor timeout, batcher coalescing, device
    # wait); expiry is an honest 504 that never occupies a device slot.
    # 0 = no default deadline (the max still applies when set).
    serve_deadline_ms: float = 2000.0
    # Hard ceiling on any request's deadline — a client cannot pin a
    # pipeline slot forever by asking for an hour.
    serve_deadline_max_ms: float = 30000.0
    # Admission bound: maximum requests admitted into the cache-miss
    # pipeline at once. Beyond it (or when the estimated queue wait
    # exceeds a request's remaining budget) requests are SHED with
    # 503 + Retry-After instead of queueing unboundedly
    # (serving_requests_shed_total{reason=...}).
    serve_queue_depth: int = 64
    # Weighted-fair multi-tenancy (serving/tenancy.py; README
    # "Multi-tenancy"): "name=weight,..." declares the tenants sharing
    # this server and their relative admission shares (the `X-Tenant`
    # request header names the tenant; absent = "default"; tenants not
    # listed here collapse into one "other" bucket). Each
    # recently-active tenant owns weight/sum(active weights) of
    # serve_queue_depth; an over-share tenant sheds as 503
    # shed_reason=tenant_quota while in-share tenants keep their full
    # deadline budget. Empty (the default) disables the tenancy layer
    # entirely — responses are byte-identical to a tenancy-free build.
    serve_tenants: str = ""
    # Admission-share weight for tenants NOT named in serve_tenants
    # (the "default" tenant and the collapsed "other" bucket).
    serve_tenant_default_weight: float = 1.0
    # Per-tenant rate quota (deterministic token bucket, qps): either
    # one bare number applied to every tenant, or "name=qps,..." per
    # tenant. 0 / unset = uncapped. An over-quota request sheds as
    # tenant_quota with Retry-After derived from that tenant's own
    # bucket refill time. Only read when serve_tenants is set.
    serve_tenant_qps: str = ""
    # Circuit breakers (extractor pool + device step): rolling failure
    # window length, the failure ratio that opens the breaker once
    # min_requests samples exist, and the open->half-open probe
    # cooldown. An open breaker fails requests fast (503); cache hits
    # still serve.
    serve_breaker_window_s: float = 10.0
    serve_breaker_failure_ratio: float = 0.5
    serve_breaker_min_requests: int = 4
    serve_breaker_cooldown_s: float = 5.0
    # Supervised multi-replica serving (`serve --replicas N`,
    # serving/supervisor.py): a parent supervisor forks N single-model
    # replicas sharing the listen port (SO_REUSEPORT; falls back to
    # per-replica ports behind the supervisor's round-robin proxy),
    # restarts crashed/hung replicas with exponential backoff, and
    # fans SIGTERM out as a coordinated drain.
    serve_replicas: int = 1
    # Restarts the supervisor grants EACH replica before escalating to
    # supervisor exit (a replica that cannot stay up is a deploy
    # problem, not a restart-loop problem).
    serve_max_restarts: int = 5
    # Seconds between serving heartbeat rewrites (--heartbeat_file).
    # The supervisor treats a heartbeat older than ~3 intervals as a
    # HUNG replica and restarts it.
    serve_heartbeat_interval_s: float = 5.0
    # -- serving telemetry (obs/reqtrace.py, obs/flight.py,
    # serving/telemetry.py; README "Telemetry") --
    # Honor `?debug=trace` on /predict//embed//neighbors: the response
    # gains a `trace` field with the request's full span tree. OFF by
    # default — the tree exposes internals (worker pids, batch
    # composition, cache behavior) that do not belong on a public
    # endpoint; enable on debug/staging replicas only.
    serve_debug_trace: bool = False
    # Directory for flight-recorder dumps (incident-triggered and
    # POST /admin/dump). None = next to --heartbeat_file when set,
    # else incident auto-dumps are disabled (/admin/dump still writes,
    # into the system temp dir).
    serve_flight_dir: Optional[str] = None
    # Terminal request records the flight recorder retains (the black
    # box ring; anomaly events ring separately at 256).
    serve_flight_records: int = 512
    # Flight dumps retained per dump directory: past the cap the
    # OLDEST flight-*.json files are deleted after each new dump, so a
    # long-running supervisor run dir cannot grow without bound.
    # 0 = unbounded.
    serve_flight_max_dumps: int = 64
    # Supervisor telemetry listener (merged GET /metrics + GET /fleet —
    # the documented scrape address under --replicas, fixing the
    # SO_REUSEPORT one-replica-scrape gap). None = public port + 1;
    # 0 = pick a free port (logged + in the supervisor heartbeat's
    # telemetry_port).
    serve_telemetry_port: Optional[int] = None
    # -- serving fleet (code2vec_tpu/serving/fleet; README "Fleet") --
    # Run the fleet control plane + router (`fleet` subcommand): N
    # host supervisors per model group behind one health-gated router,
    # telemetry-driven per-host replica scaling, canary-first
    # coordinated hot-swap.
    fleet: bool = False
    # Hosts launched per model group. The default LocalHostLauncher
    # runs them as local processes (dev/test/single-machine); remote
    # substrates plug in through fleet/control.py's HostLauncher seam.
    fleet_hosts: int = 2
    # Router public port. None = serve_port (the fleet takes over the
    # serving stack's public address); 0 picks a free port.
    fleet_port: Optional[int] = None
    # Multi-model fleet: comma list of name=artifact_dir groups, each
    # getting fleet_hosts hosts; the router keys on the X-Model
    # request header. Empty = one "default" group from --artifact.
    fleet_models: str = ""
    # Seconds between control-plane polls of each host's /fleet +
    # /metrics (also the scaling decision cadence).
    fleet_poll_interval_s: float = 1.0
    # Per-host replica-count bounds for telemetry-driven scaling (and
    # the sanity bounds for manual POST /admin/scale overrides).
    fleet_scale_min: int = 1
    fleet_scale_max: int = 4
    # Scale-up triggers, evaluated over the window since the previous
    # poll tick: shed rate above this fraction...
    fleet_scale_up_shed_rate: float = 0.05
    # ...or total-phase p95 above this many milliseconds (0 disables
    # the p95 trigger; shed rate alone then drives scale-up). Default
    # MEASURED, not guessed (the PR-13 "defaults off pending a
    # threshold" follow-on): `serving_bench.py p95` records the healthy
    # 4-client cache-off total-phase p95 (~48 ms on the dev harness)
    # and ships 10x rounded up — unambiguous sustained distress the
    # shed-rate trigger cannot see (slow-but-not-yet-shedding), still
    # a quarter of the default 2000 ms deadline so scale-up fires
    # before requests expire (experiments/results/serving_p95.json).
    fleet_scale_up_p95_ms: float = 500.0
    # Hysteresis: consecutive over-threshold ticks required to scale
    # up, consecutive zero-request ticks required to scale down, and a
    # cooldown after every action so a noisy signal cannot flap the
    # replica count.
    fleet_scale_up_ticks: int = 2
    fleet_scale_down_ticks: int = 10
    fleet_scale_cooldown_s: float = 15.0
    # Seconds the coordinated-swap driver waits for ONE host's
    # replicas to converge on the new fingerprint before declaring the
    # rollout failed (halt at the canary; rollback past it).
    fleet_swap_timeout_s: float = 120.0
    # Restarts the control plane grants each host before escalating to
    # fleet exit (the supervisor's deploy-problem philosophy, one
    # level up).
    fleet_max_host_restarts: int = 5
    # -- edge tier (code2vec_tpu/serving/fleet/edge.py; README
    # "Edge") --
    # Public router processes. 1 (default) = the classic embedded
    # router on the fleet port. N >= 2 = N stateless router AGENTS on
    # consecutive ports (fleet_port..fleet_port+N-1; 0 = all auto),
    # each polling the control plane's private control listener for
    # the shared fleet view — any router serves any request (put them
    # behind one DNS name / L4 VIP), and the control plane respawns a
    # dead one with the host backoff/escalation policy.
    fleet_routers: int = 1
    # Control-listener address (HOST:PORT) a router agent polls; set
    # by the control plane on the re-exec command line, not by
    # operators.
    fleet_control: str = ""
    # Consistent-hash cache affinity (--fleet_no_affinity to disable):
    # routers hash each request's normalized source onto a ring of the
    # fully-healthy hosts and try that host first, so repeat traffic
    # lands on the replica whose LRU cache already holds the entry;
    # unhealthy/draining hosts leave the ring and selection falls back
    # to weighted sampling. Response bytes are unaffected (the cache
    # keys on fingerprint + normalized source per host).
    fleet_cache_affinity: bool = True
    # Remote HostLauncher wrapper template (empty = local processes):
    # e.g. "ssh {address}" or "docker exec {address}" — {address} is
    # each host's address from fleet_addresses. Contract: the fleet
    # run dir on a shared filesystem (heartbeats readable) and
    # reported ports reachable at the host's address.
    fleet_launcher: str = ""
    # Comma list of addresses hosts are placed on (round-robin) and
    # reached at; empty = serve_host for every host.
    fleet_addresses: str = ""
    # -- telemetry history + SLO engine (obs/tsdb.py + obs/slo.py;
    # README "SLO & history") --
    # Window of poll-tick history the control plane keeps (memory +
    # on-disk segment ring under <run dir>/tsdb/), and the ring's
    # byte cap (oldest segments evicted first).
    fleet_tsdb_retention_s: float = 3600.0
    fleet_tsdb_max_mb: float = 64.0
    # Availability objective: target fraction of non-5xx/non-shed
    # requests (0 disables the objective).
    fleet_slo_availability: float = 0.999
    # Latency objective: target fraction of requests completing under
    # the threshold (either at 0 disables the objective).
    fleet_slo_latency_ms: float = 500.0
    fleet_slo_latency_target: float = 0.95
    # Error-budget period the slo_error_budget_remaining gauge is
    # computed over (default 30 days), and a uniform scale applied to
    # EVERY burn window — production keeps 1.0; tests/benches shrink
    # it so a page fires in seconds through the real window pairing.
    fleet_slo_period_s: float = 2592000.0
    fleet_slo_window_scale: float = 1.0
    # `fleet trace` collector inputs: the trace id to stitch, and
    # either a run dir to walk locally or --fleet_control to ask a
    # live control plane via GET /trace?id=.
    fleet_trace_id: str = ""
    fleet_trace_dir: str = ""
    # Rows per streamed target-table block in the blockwise top-k
    # prediction head (ops/topk.py): the eval/predict steps fold the
    # ~246K-name classifier through a running top-k merge + logsumexp
    # instead of materializing the (B, target_vocab) logit row (~1 GB
    # of HBM traffic per flagship eval batch, written once and read
    # twice). Indices/values are exactly the full path's (pinned in
    # tests/test_quant.py). Engages only when the target vocab exceeds
    # one block and the table is unsharded over `model` (tp == 1);
    # 0 forces the classic full-logits path.
    topk_block_size: int = 4096

    # -- release artifacts (code2vec_tpu/release; no reference
    # equivalent — the reference's --release only strips optimizer
    # state from a checkpoint) --
    # `export` subcommand output: write a self-contained quantized
    # inference artifact (int8 tables + per-row scales, vocabs, AOT
    # serve lowerings) here. Requires --load.
    export_artifact_path: Optional[str] = None
    # `serve`/eval input: run from a release artifact instead of a
    # checkpoint (serving/server.py gets a release/runtime.py model).
    serve_artifact: Optional[str] = None
    # Quantize the three embedding tables to per-row symmetric int8 in
    # the exported artifact (ops/quant.py). False exports fp32 tables
    # (same layout, 4x the bytes) — the control arm of BENCH_QUANT.md.
    release_quantize: bool = True
    # Quantization scheme of the exported tables (release/artifact.py):
    # int8 (1 byte/weight, the default), fp8_e4m3 / fp8_e5m2 (1
    # byte/weight with a relative error profile), int4 (two weights per
    # byte — another ~2x below int8), or float32 (= --no_quantize).
    # Per-scheme accuracy deltas vs same-run fp32 in BENCH_QUANT.md.
    release_scheme: str = "int8"
    # Approximate-MIPS prediction head (retrieval/mips.py): when > 0,
    # serve/predict top-k over the ~246K-name classifier searches only
    # the rows of the `serve_mips_nprobe` nearest coarse-quantizer
    # lists instead of streaming the whole table (blockwise exact path
    # stays the default at 0, and remains the accuracy-eval path
    # regardless). Top-1 agreement vs exact is measured per nprobe in
    # BENCH_QUANT.md; the tuned value documented there keeps agreement
    # >= 0.99.
    serve_mips_nprobe: int = 0
    # Coarse-quantizer size of the MIPS head; 0 = sqrt(real vocab) auto.
    serve_mips_nlist: int = 0
    # Batch-shape-aware exact/MIPS head dispatch (release/runtime.py):
    # device batches with at most this many LIVE rows route to the MIPS
    # head, bulk shapes to the exact blockwise head — the PR-14 residue
    # (MIPS wins 10-56x single-row, loses at bulk) resolved per batch
    # instead of per server. -1 = adopt the crossover the export
    # calibration recorded in the artifact meta (mips_crossover), or
    # legacy all-MIPS when the artifact carries none; 0 = exact-only,
    # bit-for-bit identical to serving with nprobe 0; > 0 = explicit
    # crossover row count. Requires serve_mips_nprobe > 0 to take
    # effect (there is no MIPS head to dispatch to otherwise).
    serve_mips_crossover: int = -1
    # Overlap the gradient all-reduce with the optimizer apply
    # (parallel/overlap.py): the train step splits into backward (no
    # cross-host reduce) + per-bucket all-reduce+Adam jits dispatched
    # back to back, so bucket i's apply overlaps bucket i+1's reduce
    # and the host never blocks on one monolithic step chain. Dense
    # optimizer only; data-parallel GSPMD meshes, or manual-kernel
    # tp/cp meshes (--manual_tp_kernels — the manual forward runs per
    # shard and the bucket reducers psum each leaf over exactly the
    # axes it is replicated on). Measured at 2 hosts in
    # BENCH_ROOFLINE.md "Roofline levers" and BENCH_INPUT.md.
    overlap_grad_allreduce: bool = False
    # Target bytes per gradient bucket, in MB (leaves bigger than one
    # bucket get their own).
    overlap_bucket_mb: float = 32.0
    # True in-backward bucket completion (parallel/overlap.py): split
    # the backward itself by bucket so bucket i's all-reduce + Adam
    # apply dispatches while bucket i+1's backward is still running,
    # instead of overlapping only the post-backward reduce chain.
    # Costs one extra forward per bucket beyond the first (no
    # cross-bucket activation reuse at the jit seam) — the
    # input-bench A/B (BENCH_INPUT.md) records whether the overlap
    # buys more than the recompute. Requires overlap_grad_allreduce.
    overlap_in_backward: bool = False
    # Also AOT-export (jax.export) the bucketed serve functions into
    # the artifact, one per (serve_batch_size, context bucket) shape,
    # so a serving replica cold-starts from deserialized lowerings
    # instead of retracing each bucket. Artifacts embed the lowering
    # platform; a replica on a different backend falls back to jit.
    release_aot: bool = True
    # -- retrieval (code2vec_tpu/retrieval; no reference equivalent —
    # the reference only dumps code vectors as text via
    # --export_code_vectors) --
    # `embed` subcommand output: write the corpus's code vectors into a
    # sharded vector store here (retrieval/store.py). The corpus is
    # --test's packed .c2vb; the model is --load or --artifact.
    embed_out: Optional[str] = None
    # Vector-store payload dtype: float16 halves the store (and the
    # index's HBM footprint) at ~1e-3 cosine error; float32 is exact.
    embed_dtype: str = "float32"
    # Rows per committed store shard — the embed job's resume
    # granularity (a killed job re-embeds at most this many rows).
    embed_shard_rows: int = 65536
    # --export_code_vectors compat: write the reference's `.vectors`
    # text layout (one space-joined vector per line) instead of the
    # sharded store format.
    vectors_text: bool = False
    # `export-embeddings` subcommand output dir: token + target
    # embedding tables in word2vec text format (the reference's
    # --save_w2v/--save_t2v pair as one artifact).
    embeddings_out: Optional[str] = None
    # `index-build` subcommand input/output: the vector store to index
    # and the index artifact dir to write (retrieval/index.py).
    index_vectors: Optional[str] = None
    index_out: Optional[str] = None
    # IVF coarse-quantizer size; 0 = sqrt(rows) auto. Small corpora
    # (or nlist <= 1) fall back to the brute-force exact backend.
    index_nlist: int = 0
    # Inverted lists probed per query (recall/latency knob; clients
    # override per request via the JSON body's `nprobe`). The default
    # is recorded into the index artifact at build time.
    index_nprobe: int = 8
    # Jitted Lloyd iterations for the coarse quantizer.
    index_kmeans_iters: int = 10
    # Similarity metric baked into the index: cosine (vectors
    # normalized at build, distance = 1 - score) or raw dot.
    index_metric: str = "cosine"
    # `serve` input: mount a built index so the server answers
    # POST /neighbors (retrieval/api.py). The index's recorded
    # embedding fingerprint must match the serving model's.
    retrieval_index: Optional[str] = None
    # Default neighbors returned per method by /neighbors (JSON body
    # `k` overrides per request).
    retrieval_topk: int = 10
    # What a model hot-swap does when the new weights' fingerprint
    # diverges from the mounted index's: "refuse" rejects the swap
    # (the index is part of the serving contract), "detach" commits
    # the swap and detaches the index (reason in /healthz; /neighbors
    # answers 503 until a matching index is mounted). Either way,
    # neighbors are NEVER computed across embedding spaces.
    retrieval_swap_policy: str = "refuse"

    # -- continuous-training pipeline (code2vec_tpu/pipeline; README
    # "Continuous training"; no reference equivalent — the reference's
    # model is one-shot) --
    # Run the crash-safe pipeline supervisor (`pipeline` subcommand):
    # ingest delta -> fine-tune -> export -> shadow-eval -> canary
    # promote -> retrieval refresh, journaled per stage.
    pipeline: bool = False
    # `corpus` subcommand: manifest tooling for the sharded training
    # corpus (--train_corpus_manifest) — list shards, create a
    # manifest, append a delta shard, validate shard headers and vocab
    # fingerprints. Never builds a model.
    corpus: bool = False
    # Comma-separated .c2vb shard paths to build a new manifest from
    # (`corpus --corpus_create`). Shard order defines global row ids.
    corpus_create: Optional[str] = None
    # One .c2vb delta shard to append to the manifest (`corpus
    # --corpus_add`); refused on vocab-fingerprint mismatch.
    corpus_add: Optional[str] = None
    # Re-read every listed shard's header and meta and fail on any
    # drift (rows changed, mixed vocab); plain `corpus` only prints
    # the manifest.
    corpus_validate: bool = False
    # Pipeline state root: journaled manifest, per-stage work dirs,
    # candidate checkpoint/artifact. One dir = one run; a killed run
    # rerun with the SAME inputs resumes from the last committed stage.
    pipeline_dir: Optional[str] = None
    # New raw extractor output to ingest as a delta shard against the
    # FROZEN incumbent vocab (OOV rate exported through obs — the
    # "vocabulary aging out" signal).
    pipeline_raw: Optional[str] = None
    # The incumbent release artifact the fleet serves today: the
    # shadow-eval baseline and the implicit rollback identity.
    pipeline_incumbent: Optional[str] = None
    # Recorded live-traffic sample (what serving replicas write at
    # --serve_traffic_sample) replayed through incumbent AND candidate
    # at shadow-eval. None = gate on the accuracy harness alone.
    pipeline_traffic: Optional[str] = None
    # Max traffic lines replayed (deterministically sampled by seed,
    # so a rerun of a killed shadow-eval replays the same slice).
    pipeline_shadow_samples: int = 256
    # Epochs the fine-tune stage trains on the delta shard, resumed
    # from the latest committed checkpoint via the elastic-restore
    # path (any host count / mesh shape the child runs on).
    pipeline_finetune_epochs: int = 1
    # Quality-gate regression bars: largest tolerated drop (candidate
    # minus incumbent) per metric, and the smallest tolerated top-k
    # agreement over the replayed traffic. Any tripped bar REFUSES
    # promotion (terminal; incumbent keeps serving).
    pipeline_gate_top1_drop: float = 0.01
    pipeline_gate_topk_drop: float = 0.01
    pipeline_gate_f1_drop: float = 0.01
    pipeline_gate_min_agreement: float = 0.98
    # Fleet router admin address (host:port) the promote stage drives
    # the canary-first coordinated swap through. Empty = the pipeline
    # stops after shadow-eval with a gated candidate on disk.
    pipeline_fleet: str = ""
    # Fleet model group to promote into (the router's X-Model key).
    pipeline_model: str = "default"
    # Budget for one fleet rollout (promote or index remount) to reach
    # a terminal state before the stage fails.
    pipeline_promote_timeout_s: float = 600.0
    # After promotion: re-embed the delta shard with the candidate,
    # build a fresh ANN index behind its fingerprint, and remount it
    # fleet-wide through the reload fan-out (each replica mounts the
    # index atomically with its model flip; the refuse/detach policy
    # guards every transition).
    pipeline_refresh_retrieval: bool = False
    # -- live-traffic sampling (serving/traffic.py) --
    # Record every Nth cache-miss request's EXTRACTED lines into this
    # bounded ring file — the shadow-eval replay corpus. None = off.
    serve_traffic_sample_file: Optional[str] = None
    serve_traffic_sample_every: int = 10
    serve_traffic_sample_cap: int = 4096

    # Knob names the user set EXPLICITLY on the command line (filled by
    # cli.config_from_args). Lets a consumer distinguish "holds the
    # dataclass default because nobody asked" from "the operator typed
    # exactly the default value": ReleaseModel only adopts an artifact's
    # AOT-exported serve_batch_size when the flag was never given.
    explicit_knobs: Tuple[str, ...] = ()

    # Full-content sha256 of every checkpoint file (including the
    # multi-GB Orbax shards, chunked + hashed on a thread pool) recorded
    # into the manifest AFTER the atomic commit, so it stays off the
    # save critical path; resume verifies the hashes when present
    # (training/checkpoint.py). Default off: the manifest's
    # existence+size probe already rejects truncation, and Orbax
    # checksums its own payloads — this adds bit-rot/corruption
    # detection for long-lived artifacts.
    checkpoint_hash_content: bool = False
    # Random seed for params/dropout.
    seed: int = 42

    # -- filled at runtime (reference: config.py:130-132) --
    num_train_examples: int = 0
    num_test_examples: int = 0

    def __post_init__(self):
        # reference config.py:64-66: per-table sizes fall back to
        # DEFAULT_EMBEDDINGS_SIZE unless set explicitly.
        if self.token_embeddings_size is None:
            self.token_embeddings_size = self.default_embeddings_size
        if self.path_embeddings_size is None:
            self.path_embeddings_size = self.default_embeddings_size

    # ---------------------------------------------------------------- derived

    @property
    def context_vector_size(self) -> int:
        # concat of source-token, path and target-token embeddings
        # (reference: config.py:143-147).
        return self.path_embeddings_size + 2 * self.token_embeddings_size

    @property
    def code_vector_size(self) -> int:
        return self.context_vector_size

    @property
    def target_embeddings_size(self) -> int:
        return self.code_vector_size

    @property
    def is_training(self) -> bool:
        return bool(self.train_data_path_prefix)

    @property
    def is_loading(self) -> bool:
        return bool(self.model_load_path)

    @property
    def is_saving(self) -> bool:
        return bool(self.model_save_path)

    @property
    def is_testing(self) -> bool:
        return bool(self.test_data_path)

    @property
    def train_steps_per_epoch(self) -> int:
        # reference: config.py:165-167
        if not self.train_batch_size:
            return 0
        return math.ceil(self.num_train_examples / self.train_batch_size)

    @property
    def test_steps(self) -> int:
        if not self.test_batch_size:
            return 0
        return math.ceil(self.num_test_examples / self.test_batch_size)

    @property
    def train_data_path(self) -> Optional[str]:
        # reference: config.py:179-183 — `<prefix>.train.c2v`
        if not self.is_training:
            return None
        return f"{self.train_data_path_prefix}.train.c2v"

    @property
    def word_freq_dict_path(self) -> Optional[str]:
        # reference: config.py:185-189 — `<prefix>.dict.c2v`
        if not self.is_training:
            return None
        return f"{self.train_data_path_prefix}.dict.c2v"

    def data_path(self, is_evaluating: bool = False) -> Optional[str]:
        return self.test_data_path if is_evaluating else self.train_data_path

    def batch_size(self, is_evaluating: bool = False) -> int:
        return self.test_batch_size if is_evaluating else self.train_batch_size

    @staticmethod
    def get_vocabularies_path_from_model_path(model_file_path: str) -> str:
        # Our model artifacts are directories carrying their own
        # `dictionaries.bin`; the reference instead stores it as a sibling
        # of the checkpoint file (reference: config.py:191-194). Accept both
        # so reference-layout model dirs remain loadable.
        inside = os.path.join(model_file_path, "dictionaries.bin")
        if os.path.isfile(inside):
            return inside
        return os.path.join(os.path.dirname(model_file_path), "dictionaries.bin")

    @property
    def model_load_dir(self) -> str:
        return os.path.dirname(self.model_load_path or "")

    @property
    def tensorboard_dir(self) -> str:
        # reference: keras_model.py:158-163 roots the TensorBoard callback
        # next to the model artifacts.
        base = self.model_save_path or self.model_load_path or "code2vec"
        return base + "_tb"

    @property
    def mesh_size(self) -> int:
        return self.dp * self.tp * self.cp

    # ---------------------------------------------------------------- checks

    def verify(self) -> None:
        # reference: config.py:232-239, plus mesh-shape checks.
        if (not self.is_training and not self.is_loading
                and not self.serve_artifact and not self.index_out
                and not self.corpus
                and not (self.fleet and self.fleet_models)
                and not (self.fleet and self.fleet_trace_id)):
            raise ValueError(
                "Must train or load a model (or serve a release "
                "artifact via --artifact; `index-build` and `corpus` "
                "alone need no model; `fleet` may carry its models in "
                "--fleet_models; `fleet --fleet_trace_id` only "
                "stitches trace files).")
        if self.is_loading and not os.path.isdir(self.model_load_dir):
            raise ValueError(
                f"Model load dir `{self.model_load_dir}` does not exist.")
        if self.dp < 1 or self.tp < 1 or self.cp < 1:
            raise ValueError("Mesh axis sizes dp/tp/cp must be >= 1.")
        if self.max_contexts % self.cp != 0:
            raise ValueError(
                f"max_contexts ({self.max_contexts}) must be divisible by the "
                f"context-parallel degree cp ({self.cp}).")
        if self.compute_dtype not in ("bfloat16", "float32"):
            raise ValueError("compute_dtype must be bfloat16 or float32.")
        if self.adam_mu_dtype not in ("bfloat16", "float32"):
            raise ValueError("adam_mu_dtype must be bfloat16 or float32.")
        if self.adam_nu_dtype not in ("bfloat16", "float32"):
            raise ValueError("adam_nu_dtype must be bfloat16 or float32.")
        if self.dropout_prng_impl not in ("rbg", "threefry2x32",
                                          "unsafe_rbg"):
            raise ValueError(
                "dropout_prng_impl must be rbg, threefry2x32 or unsafe_rbg.")
        if self.rss_limit_gb < 0:
            raise ValueError("rss_limit_gb must be >= 0 (0 disables).")
        if self.on_nonfinite_loss not in ("halt", "warn"):
            raise ValueError("on_nonfinite_loss must be halt or warn.")
        if self.extractor_timeout_s < 0:
            raise ValueError(
                "extractor_timeout_s must be >= 0 (0 disables).")
        if self.extractor_retries < 0:
            raise ValueError(
                "extractor_retries must be >= 0 (0 disables retries).")
        if self.save_barrier_timeout_s <= 0:
            raise ValueError(
                "save_barrier_timeout_s must be > 0 (a barrier that "
                "never times out turns a dead peer into a pod hang).")
        if not (0 <= self.metrics_port <= 65535):
            raise ValueError(
                "metrics_port must be in [0, 65535] (0 disables).")
        if self.preprocess_workers < 0:
            raise ValueError(
                "preprocess_workers must be >= 0 (0 = in-process serial).")
        if not (0 <= self.serve_port <= 65535):
            raise ValueError(
                "serve_port must be in [0, 65535] (0 picks a free port).")
        if self.serve_batch_size < 1:
            raise ValueError("serve_batch_size must be >= 1.")
        if self.serve_max_delay_ms < 0:
            raise ValueError(
                "serve_max_delay_ms must be >= 0 (0 = dispatch "
                "immediately, no coalescing).")
        if self.serve_cache_entries < 0:
            raise ValueError(
                "serve_cache_entries must be >= 0 (0 disables the "
                "prediction cache).")
        if self.extractor_pool_size < 1:
            raise ValueError("extractor_pool_size must be >= 1.")
        try:
            from code2vec_tpu.serving.batcher import parse_buckets
            parse_buckets(self.serve_buckets, self.max_contexts, cp=self.cp)
        except ValueError:
            raise ValueError(
                f"serve_buckets must be a comma-separated list of ints "
                f"(got {self.serve_buckets!r}).")
        if self.serve_drain_timeout_s <= 0:
            raise ValueError(
                "serve_drain_timeout_s must be > 0 (a drain that never "
                "times out can outlive the SIGTERM grace window).")
        if self.serve_deadline_ms < 0:
            raise ValueError(
                "serve_deadline_ms must be >= 0 (0 = no default "
                "deadline).")
        if self.serve_deadline_max_ms < 0:
            raise ValueError(
                "serve_deadline_max_ms must be >= 0 (0 = no ceiling).")
        if (self.serve_deadline_ms > 0 and self.serve_deadline_max_ms > 0
                and self.serve_deadline_ms > self.serve_deadline_max_ms):
            raise ValueError(
                "serve_deadline_ms must not exceed serve_deadline_max_ms "
                "(the default deadline would be clamped below itself).")
        if self.serve_queue_depth < 1:
            raise ValueError(
                "serve_queue_depth must be >= 1 (the admission gate "
                "needs room for at least one request).")
        try:
            from code2vec_tpu.serving.tenancy import (
                parse_tenant_qps, parse_tenant_weights,
            )
            parse_tenant_weights(self.serve_tenants)
            parse_tenant_qps(self.serve_tenant_qps)
        except ValueError as e:
            # a typo'd tenant spec must fail at startup, not skew
            # production fairness silently
            raise ValueError(str(e))
        if self.serve_tenant_default_weight <= 0:
            raise ValueError(
                "serve_tenant_default_weight must be > 0 (it is the "
                "admission share of every unconfigured tenant).")
        if self.serve_breaker_window_s <= 0:
            raise ValueError("serve_breaker_window_s must be > 0.")
        if not (0 < self.serve_breaker_failure_ratio <= 1):
            raise ValueError(
                "serve_breaker_failure_ratio must be in (0, 1].")
        if self.serve_breaker_min_requests < 1:
            raise ValueError("serve_breaker_min_requests must be >= 1.")
        if self.serve_breaker_cooldown_s <= 0:
            raise ValueError(
                "serve_breaker_cooldown_s must be > 0 (an open breaker "
                "must eventually probe for recovery).")
        if self.serve_replicas < 1:
            raise ValueError("serve_replicas (--replicas) must be >= 1.")
        if self.serve_replicas > 1 and not self.serve:
            raise ValueError(
                "--replicas applies to the serve subcommand only "
                "(supervised multi-replica serving).")
        if self.serve_max_restarts < 0:
            raise ValueError(
                "serve_max_restarts must be >= 0 (0 = never restart, "
                "escalate on first replica death).")
        if self.serve_heartbeat_interval_s <= 0:
            raise ValueError("serve_heartbeat_interval_s must be > 0.")
        if self.serve_flight_records < 1:
            raise ValueError(
                "serve_flight_records must be >= 1 (the flight "
                "recorder ring needs at least one slot).")
        if self.serve_flight_max_dumps < 0:
            raise ValueError(
                "serve_flight_max_dumps must be >= 0 (0 = unbounded, "
                "no retention sweep).")
        if self.fleet and not self.serve:
            raise ValueError(
                "fleet knobs apply to the `fleet` subcommand (which "
                "implies serving).")
        if self.fleet_hosts < 1:
            raise ValueError("fleet_hosts must be >= 1.")
        if self.fleet_port is not None and not (
                0 <= self.fleet_port <= 65535):
            raise ValueError(
                "fleet_port must be in [0, 65535] (0 picks a free "
                "port; unset defaults to serve_port).")
        if self.fleet_models:
            try:
                from code2vec_tpu.serving.fleet.control import (
                    parse_fleet_models,
                )
                parse_fleet_models(self.fleet_models)
            except ValueError as e:
                raise ValueError(str(e))
        if self.fleet_poll_interval_s <= 0:
            raise ValueError("fleet_poll_interval must be > 0.")
        if self.fleet_scale_min < 1:
            raise ValueError("fleet_scale_min must be >= 1.")
        if self.fleet_scale_max < self.fleet_scale_min:
            raise ValueError(
                "fleet_scale_max must be >= fleet_scale_min.")
        if not (0 <= self.fleet_scale_up_shed_rate <= 1):
            raise ValueError(
                "fleet_scale_up_shed_rate must be in [0, 1].")
        if self.fleet_scale_up_p95_ms < 0:
            raise ValueError(
                "fleet_scale_up_p95_ms must be >= 0 (0 disables the "
                "p95 scale-up trigger).")
        if self.fleet_scale_up_ticks < 1 or self.fleet_scale_down_ticks < 1:
            raise ValueError(
                "fleet_scale_up_ticks and fleet_scale_down_ticks must "
                "be >= 1 (they are the hysteresis).")
        if self.fleet_scale_cooldown_s < 0:
            raise ValueError("fleet_scale_cooldown must be >= 0.")
        if self.fleet_swap_timeout_s <= 0:
            raise ValueError(
                "fleet_swap_timeout must be > 0 (a rollout that never "
                "times out wedges the swap driver on a dead host).")
        if self.fleet_max_host_restarts < 0:
            raise ValueError(
                "fleet_max_host_restarts must be >= 0 (0 = escalate "
                "on first host death).")
        if self.fleet_routers < 1:
            raise ValueError(
                "fleet_routers must be >= 1 (1 = the embedded router; "
                "N >= 2 = the edge router tier).")
        if self.fleet_control and (
                ":" not in self.fleet_control
                or not self.fleet_control.rsplit(":", 1)[1].isdigit()):
            raise ValueError(
                "fleet_control must be HOST:PORT (it is set by the "
                "control plane on router re-exec commands).")
        if self.fleet_tsdb_retention_s <= 0:
            raise ValueError(
                "fleet_tsdb_retention must be > 0 (the history window "
                "the SLO engine and /query read from).")
        if self.fleet_tsdb_max_mb <= 0:
            raise ValueError(
                "fleet_tsdb_max_mb must be > 0 (the on-disk segment "
                "ring's byte cap).")
        if not (0 <= self.fleet_slo_availability < 1):
            raise ValueError(
                "fleet_slo_availability must be in [0, 1) "
                "(0 disables the objective; 1 allows no errors ever "
                "and pages forever).")
        if not (0 <= self.fleet_slo_latency_target < 1):
            raise ValueError(
                "fleet_slo_latency_target must be in [0, 1) "
                "(0 disables the objective).")
        if self.fleet_slo_latency_ms < 0:
            raise ValueError("fleet_slo_latency_ms must be >= 0.")
        if self.fleet_slo_period_s <= 0:
            raise ValueError("fleet_slo_period must be > 0.")
        if self.fleet_slo_window_scale <= 0:
            raise ValueError(
                "fleet_slo_window_scale must be > 0 (1.0 = the "
                "standard SRE windows; smaller = faster drills).")
        if self.fleet_launcher and "{address}" in self.fleet_launcher \
                and not self.fleet_addresses:
            raise ValueError(
                "fleet_launcher template uses {address} but "
                "fleet_addresses is empty — list the machines hosts "
                "should land on (comma-separated).")
        if self.serve_telemetry_port is not None and not (
                0 <= self.serve_telemetry_port <= 65535):
            raise ValueError(
                "serve_telemetry_port must be in [0, 65535] "
                "(0 picks a free port; unset defaults to the public "
                "port + 1).")
        if self.topk_block_size < 0:
            raise ValueError(
                "topk_block_size must be >= 0 (0 forces the full-logits "
                "top-k path).")
        if self.pipeline:
            if not self.pipeline_dir:
                raise ValueError(
                    "pipeline requires --pipeline_dir DIR (the "
                    "journaled state root a killed run resumes from).")
            if self.serve or self.predict or self.is_training:
                raise ValueError(
                    "the `pipeline` subcommand is a standalone "
                    "supervisor: it re-execs train/export/embed "
                    "children itself and cannot be combined with "
                    "--serve/--predict/--data.")
            if (self.export_artifact_path or self.embed_out
                    or self.index_out or self.embeddings_out
                    or self.fleet):
                raise ValueError(
                    "pipeline cannot be combined with the one-shot "
                    "export/embed/index-build/export-embeddings jobs "
                    "or `fleet`: it drives those itself as stages.")
            if not self.is_loading:
                raise ValueError(
                    "pipeline requires --load CKPT: the incumbent "
                    "checkpoint is the fine-tune starting point and "
                    "the frozen-vocab source.")
            if not self.pipeline_raw:
                raise ValueError(
                    "pipeline requires --pipeline_raw FILE (the new "
                    "raw extractor output to ingest as a delta "
                    "shard).")
            if not self.pipeline_incumbent:
                raise ValueError(
                    "pipeline requires --pipeline_incumbent DIR (the "
                    "release artifact the fleet serves today — "
                    "shadow-eval's baseline).")
            if not self.is_testing:
                raise ValueError(
                    "pipeline requires --test FILE: the accuracy "
                    "harness shadow-eval scores both models on.")
            if self.serve_artifact:
                raise ValueError(
                    "pipeline takes the incumbent artifact via "
                    "--pipeline_incumbent, not --artifact (which "
                    "conflicts with the --load'ed checkpoint).")
        if self.pipeline_shadow_samples < 0:
            raise ValueError(
                "pipeline_shadow_samples must be >= 0 (0 = gate on "
                "the accuracy harness alone).")
        if self.pipeline_finetune_epochs < 1:
            raise ValueError("pipeline_finetune_epochs must be >= 1.")
        for bar in ("pipeline_gate_top1_drop", "pipeline_gate_topk_drop",
                    "pipeline_gate_f1_drop"):
            if getattr(self, bar) < 0:
                raise ValueError(f"{bar} must be >= 0 (the largest "
                                 f"tolerated drop).")
        if not (0 <= self.pipeline_gate_min_agreement <= 1):
            raise ValueError(
                "pipeline_gate_min_agreement must be in [0, 1].")
        if self.pipeline_promote_timeout_s <= 0:
            raise ValueError(
                "pipeline_promote_timeout must be > 0 (a rollout poll "
                "that never times out wedges the pipeline on a dead "
                "fleet).")
        if self.serve_traffic_sample_file and not self.serve:
            raise ValueError(
                "--serve_traffic_sample applies to the serve "
                "subcommand (it records the serving extract path).")
        if self.serve_traffic_sample_every < 1:
            raise ValueError(
                "serve_traffic_sample_every must be >= 1 (1 = sample "
                "every request).")
        if self.serve_traffic_sample_cap < 1:
            raise ValueError(
                "serve_traffic_sample_cap must be >= 1.")
        if self.release_scheme not in ("int8", "fp8_e4m3", "fp8_e5m2",
                                       "int4", "float32"):
            raise ValueError(
                "release_scheme must be one of int8, fp8_e4m3, "
                "fp8_e5m2, int4, float32.")
        if self.serve_mips_nprobe < 0:
            raise ValueError(
                "serve_mips_nprobe must be >= 0 (0 = exact blockwise "
                "top-k, the default).")
        if self.serve_mips_nlist < 0:
            raise ValueError(
                "serve_mips_nlist must be >= 0 (0 = sqrt(vocab) auto).")
        if self.serve_mips_nprobe > 0:
            if not (self.serve or self.predict
                    or self.export_artifact_path):
                raise ValueError(
                    "serve_mips_nprobe applies to serve/--predict (the "
                    "prediction head) and export (which calibrates and "
                    "records the exact/MIPS crossover in the artifact "
                    "meta); eval/embed always use the exact blockwise "
                    "path, so the knob would be a silent no-op here.")
            if self.is_testing:
                raise ValueError(
                    "--serve_mips_nprobe cannot be combined with "
                    "--test: accuracy evaluation always scores the "
                    "exact blockwise head. Measure MIPS agreement and "
                    "speedup with experiments/quant_bench.py "
                    "(BENCH_QUANT.md) instead.")
        if self.serve_mips_crossover < -1:
            raise ValueError(
                "serve_mips_crossover must be >= -1 (-1 = adopt the "
                "artifact's calibrated crossover, 0 = exact-only, "
                "> 0 = explicit crossover row count).")
        if self.serve_mips_crossover > 0 and self.serve_mips_nprobe == 0:
            raise ValueError(
                "serve_mips_crossover > 0 requires serve_mips_nprobe "
                "> 0: there is no MIPS head to dispatch small batches "
                "to without an IVF probe budget.")
        if self.serve_inflight_steps < 1:
            raise ValueError(
                "serve_inflight_steps must be >= 1 (device steps the "
                "continuous batcher may keep in flight).")
        if self.overlap_bucket_mb <= 0:
            raise ValueError("overlap_bucket_mb must be > 0.")
        if self.overlap_grad_allreduce and self.use_sparse_embedding_update:
            raise ValueError(
                "overlap_grad_allreduce is incompatible with "
                "--sparse_embedding_update: the sparse path already "
                "exchanges (ids, rows) lists instead of table-shaped "
                "gradients.")
        if (self.overlap_grad_allreduce and (self.tp > 1 or self.cp > 1)
                and not self.use_manual_tp_kernels):
            raise ValueError(
                "overlap_grad_allreduce on a tp/cp-sharded mesh requires "
                "--manual_tp_kernels: the split backward runs the forward "
                "per shard, which only the manual-kernel path does under "
                "tp/cp sharding (GSPMD tp/cp keeps the stock fused step).")
        if self.train_corpus_manifest and not self.use_packed_data:
            raise ValueError(
                "--train_corpus_manifest requires packed data: the "
                "manifest lists .c2vb shards (drop --no_packed_data).")
        if self.overlap_in_backward and not self.overlap_grad_allreduce:
            raise ValueError(
                "overlap_in_backward requires overlap_grad_allreduce: "
                "in-backward completion is a scheduling mode of the "
                "bucketed overlap step.")
        if self.export_artifact_path and not self.is_loading:
            raise ValueError(
                "export (--artifact_out) requires --load: the artifact "
                "is built from a trained checkpoint.")
        if self.export_artifact_path and self.is_training:
            raise ValueError(
                "export (--artifact_out) cannot be combined with training "
                "(--data): main() exports the --load'ed checkpoint and "
                "exits, so the training run would be silently skipped. "
                "Train first, then `export --load CKPT --artifact_out "
                "DIR`.")
        if self.export_artifact_path and (self.serve or self.predict
                                          or self.is_testing):
            raise ValueError(
                "export (--artifact_out) is a one-shot job and cannot be "
                "combined with serve/--predict/--test in the same run; "
                "run those against the exported artifact (--artifact) or "
                "the checkpoint (--load) separately.")
        if self.serve_artifact and self.is_loading:
            raise ValueError(
                "--artifact and --load are mutually exclusive: a release "
                "artifact carries its own tables and vocabularies.")
        if self.serve_artifact and (self.save_w2v or self.save_t2v):
            raise ValueError(
                "--artifact cannot be combined with --save_w2v/--save_t2v: "
                "the vector writers read the fp32 checkpoint tables and "
                "the artifact branch in main() would silently skip them; "
                "run them against --load.")
        if self.serve_artifact and self.is_training:
            raise ValueError(
                "--artifact is inference-only (serve/--predict/--test) "
                "and cannot be combined with training (--data): a "
                "release artifact has no optimizer state to train.")
        if self.embed_dtype not in ("float32", "float16"):
            raise ValueError("embed_dtype must be float32 or float16.")
        if self.embed_shard_rows < 1:
            raise ValueError(
                "embed_shard_rows must be >= 1 (it is the embed job's "
                "resume granularity).")
        if self.embed_out and not self.is_testing:
            raise ValueError(
                "embed (--embed_out) needs a corpus: pass --test FILE "
                "(its packed .c2vb is the embed input).")
        if self.embed_out and not (self.is_loading or self.serve_artifact):
            raise ValueError(
                "embed (--embed_out) needs a model: --load CKPT or "
                "--artifact DIR (an untrained model's vectors index "
                "noise).")
        if self.embed_out and self.is_training:
            raise ValueError(
                "embed (--embed_out) is a one-shot job and cannot be "
                "combined with training (--data); train first, then "
                "embed the corpus.")
        if self.embed_out and (self.serve or self.predict):
            raise ValueError(
                "embed (--embed_out) is a one-shot job and cannot be "
                "combined with serve/--predict: main() runs the embed "
                "job and exits, so the server/REPL would be silently "
                "skipped. Run them as separate invocations.")
        if self.index_out and (self.is_training or self.serve
                               or self.predict or self.is_testing
                               or self.embed_out or self.embeddings_out):
            raise ValueError(
                "index-build (--index_out) is a standalone job and "
                "cannot be combined with training/serve/--predict/"
                "--test/--embed_out/--embeddings_out: main() builds "
                "the index and exits, silently skipping the rest. Run "
                "them as separate invocations.")
        if self.embeddings_out and (self.is_training or self.serve
                                    or self.predict or self.is_testing
                                    or self.embed_out):
            raise ValueError(
                "export-embeddings (--embeddings_out) is a one-shot "
                "job and cannot be combined with training/serve/"
                "--predict/--test/--embed_out: main() writes the "
                "tables and exits, silently skipping the rest. Run "
                "them as separate invocations.")
        if self.index_out and not self.index_vectors:
            raise ValueError(
                "index-build (--index_out) requires --vectors DIR (the "
                "store the `embed` subcommand wrote).")
        if self.index_vectors and not self.index_out:
            raise ValueError(
                "--vectors is only consumed by index-build; pass "
                "--index_out DIR for the artifact to write.")
        if self.index_nlist < 0:
            raise ValueError(
                "index_nlist must be >= 0 (0 = sqrt(rows) auto).")
        if self.index_nprobe < 1:
            raise ValueError("index_nprobe must be >= 1.")
        if self.index_kmeans_iters < 1:
            raise ValueError("index_kmeans_iters must be >= 1.")
        if self.index_metric not in ("cosine", "dot"):
            raise ValueError("index_metric must be cosine or dot.")
        if self.retrieval_index and not self.serve:
            raise ValueError(
                "--retrieval_index applies to the serve subcommand "
                "only (it mounts the /neighbors index).")
        if self.retrieval_topk < 1:
            raise ValueError("retrieval_topk must be >= 1.")
        if self.retrieval_swap_policy not in ("refuse", "detach"):
            raise ValueError(
                "retrieval_swap_policy must be refuse or detach.")
        if self.embeddings_out and not self.is_loading:
            raise ValueError(
                "export-embeddings (--embeddings_out) requires --load: "
                "the tables come from a trained checkpoint.")
        if self.embeddings_out and self.serve_artifact:
            raise ValueError(
                "export-embeddings (--embeddings_out) reads the fp32 "
                "checkpoint tables; a release artifact's are quantized "
                "— run it against --load.")

    # ---------------------------------------------------------------- logging

    def get_logger(self) -> logging.Logger:
        logger = logging.getLogger(_LOGGER_NAME)
        if not logger.handlers:
            logger.setLevel(logging.INFO)
            logger.propagate = False
            formatter = logging.Formatter("%(asctime)s %(levelname)-8s %(message)s")
            if self.verbose_mode >= 1:
                ch = logging.StreamHandler(sys.stdout)
                ch.setFormatter(formatter)
                logger.addHandler(ch)
            if self.logs_path:
                fh = logging.FileHandler(self.logs_path)
                fh.setFormatter(formatter)
                logger.addHandler(fh)
        return logger

    def log(self, msg: str) -> None:
        self.get_logger().info(msg)

    def items(self):
        return dataclasses.asdict(self).items()
