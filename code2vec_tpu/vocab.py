"""Vocabularies: word<->index maps for tokens, AST paths and target names.

Reproduces the reference semantics exactly (they determine filtering and
padding behavior, hence accuracy parity):

- special words come first; the default scheme joins PAD and OOV into a
  single ``<PAD_OR_OOV>`` at index 0 (reference: vocabularies.py:22-35,
  Code2VecVocabs._get_special_words_by_vocab_type vocabularies.py:204-209 —
  with ``separate_oov_and_pad`` the target vocab gets only ``<OOV>`` while
  token/path vocabs get ``<PAD>``/``<OOV>``).
- construction from a frequency dict keeps the top-N words by count
  (reference: vocabularies.py:99-106).
- the on-disk model-sidecar format ``dictionaries.bin`` stores the three
  vocabs WITHOUT special words, in token/target/path order (reference:
  vocabularies.py:57-97, 211-218) — we keep that format bit-compatible so
  models can be audited against reference tooling.
- the training-time source is the ``.dict.c2v`` pickle written by
  preprocessing: token/path/target freq dicts + train example count
  (reference: preprocess.py:12-20, vocabularies.py:220-230).
"""

from __future__ import annotations

import enum
import os
import pickle
from typing import Dict, Iterable, List, NamedTuple, Optional

PAD_OR_OOV = "<PAD_OR_OOV>"
PAD = "<PAD>"
OOV = "<OOV>"


class VocabType(enum.Enum):
    Token = 1
    Target = 2
    Path = 3


class SpecialWords(NamedTuple):
    """Resolved special-word scheme for one vocab."""
    pad: str
    oov: str

    @property
    def unique(self) -> List[str]:
        # preserves order, dedups joined PAD/OOV (reference: common.py:199-201)
        out: List[str] = []
        for w in (self.pad, self.oov):
            if w not in out:
                out.append(w)
        return out


def special_words_for(vocab_type: VocabType, separate_oov_and_pad: bool) -> SpecialWords:
    # reference: vocabularies.py:204-209
    if not separate_oov_and_pad:
        return SpecialWords(pad=PAD_OR_OOV, oov=PAD_OR_OOV)
    if vocab_type == VocabType.Target:
        # Target rows are never padded, only OOV; PAD aliases OOV here so the
        # reader can treat all vocabs uniformly.
        return SpecialWords(pad=OOV, oov=OOV)
    return SpecialWords(pad=PAD, oov=OOV)


class Vocab:
    """One word<->index vocabulary with its special words at the front."""

    def __init__(self, vocab_type: VocabType, words: Iterable[str],
                 special_words: SpecialWords):
        self.vocab_type = vocab_type
        self.special_words = special_words
        self.word_to_index: Dict[str, int] = {}
        self.index_to_word: Dict[int, str] = {}
        for index, word in enumerate(list(special_words.unique) + list(words)):
            self.word_to_index[word] = index
            self.index_to_word[index] = word
        self.size = len(self.word_to_index)

    # -- indices used all over the data pipeline / model ------------------

    @property
    def pad_index(self) -> int:
        return self.word_to_index[self.special_words.pad]

    @property
    def oov_index(self) -> int:
        return self.word_to_index[self.special_words.oov]

    def lookup_index(self, word: str) -> int:
        return self.word_to_index.get(word, self.oov_index)

    def lookup_word(self, index: int) -> str:
        return self.index_to_word.get(index, self.special_words.oov)

    # -- construction -----------------------------------------------------

    @classmethod
    def create_from_freq_dict(cls, vocab_type: VocabType, word_to_count: Dict[str, int],
                              max_size: int, special_words: SpecialWords) -> "Vocab":
        # Top-N by count; ties broken by dict insertion order, matching the
        # reference's stable sort (reference: vocabularies.py:99-106).
        words = sorted(word_to_count, key=word_to_count.get, reverse=True)[:max_size]
        return cls(vocab_type, words, special_words)

    # -- reference-compatible binary format (dictionaries.bin) ------------

    def save_to_file(self, file) -> None:
        # Stored WITHOUT special words (reference: vocabularies.py:57-66).
        nr_special = len(self.special_words.unique)
        w2i = {w: i for w, i in self.word_to_index.items() if i >= nr_special}
        i2w = {i: w for i, w in self.index_to_word.items() if i >= nr_special}
        pickle.dump(w2i, file)
        pickle.dump(i2w, file)
        pickle.dump(self.size - nr_special, file)

    @classmethod
    def load_from_file(cls, vocab_type: VocabType, file,
                       special_words: SpecialWords) -> "Vocab":
        # reference: vocabularies.py:68-97
        w2i = pickle.load(file)
        i2w = pickle.load(file)
        size_wo_specials = pickle.load(file)
        assert len(i2w) == len(w2i) == size_wo_specials
        specials = special_words.unique
        min_idx = min(i2w.keys())
        if min_idx != len(specials):
            raise ValueError(
                f"Stored vocabulary {vocab_type} has minimum word index {min_idx}, "
                f"expected {len(specials)} (number of special words {specials}). "
                f"Check `separate_oov_and_pad`.")
        vocab = cls(vocab_type, [], special_words)
        vocab.word_to_index = {**w2i, **{w: i for i, w in enumerate(specials)}}
        vocab.index_to_word = {**i2w, **{i: w for i, w in enumerate(specials)}}
        vocab.size = size_wo_specials + len(specials)
        return vocab


class WordFreqDicts(NamedTuple):
    token_to_count: Dict[str, int]
    path_to_count: Dict[str, int]
    target_to_count: Dict[str, int]
    num_train_examples: int


def load_word_freq_dicts(dict_c2v_path: str) -> WordFreqDicts:
    """Load the `.dict.c2v` pickle produced by preprocessing.

    Pickle order: token, path, target freq dicts then train example count
    (reference: preprocess.py:12-20).
    """
    with open(dict_c2v_path, "rb") as f:
        token_to_count = pickle.load(f)
        path_to_count = pickle.load(f)
        target_to_count = pickle.load(f)
        try:
            num_train_examples = pickle.load(f)
        except EOFError:
            num_train_examples = 0
    return WordFreqDicts(token_to_count, path_to_count, target_to_count,
                         num_train_examples)


class Code2VecVocabs:
    """The three vocabularies, created from freq dicts or loaded from a
    saved model's ``dictionaries.bin`` (reference: vocabularies.py:151-230).
    """

    def __init__(self, token_vocab: Vocab, path_vocab: Vocab, target_vocab: Vocab):
        self.token_vocab = token_vocab
        self.path_vocab = path_vocab
        self.target_vocab = target_vocab
        self._already_saved_in_paths = set()

    @classmethod
    def create_from_freq_dicts(cls, freq: WordFreqDicts, *,
                               max_token_vocab_size: int,
                               max_path_vocab_size: int,
                               max_target_vocab_size: int,
                               separate_oov_and_pad: bool = False) -> "Code2VecVocabs":
        token_vocab = Vocab.create_from_freq_dict(
            VocabType.Token, freq.token_to_count, max_token_vocab_size,
            special_words_for(VocabType.Token, separate_oov_and_pad))
        path_vocab = Vocab.create_from_freq_dict(
            VocabType.Path, freq.path_to_count, max_path_vocab_size,
            special_words_for(VocabType.Path, separate_oov_and_pad))
        target_vocab = Vocab.create_from_freq_dict(
            VocabType.Target, freq.target_to_count, max_target_vocab_size,
            special_words_for(VocabType.Target, separate_oov_and_pad))
        return cls(token_vocab, path_vocab, target_vocab)

    @classmethod
    def load_or_create(cls, config) -> "Code2VecVocabs":
        # reference: vocabularies.py:163-173
        assert config.is_training or config.is_loading
        if config.is_loading:
            path = config.get_vocabularies_path_from_model_path(config.model_load_path)
            if not os.path.isfile(path):
                raise ValueError(
                    f"Model dictionaries file is not found in model load dir. "
                    f"Expecting file `{path}`.")
            return cls.load(path, separate_oov_and_pad=config.separate_oov_and_pad)
        freq = load_word_freq_dicts(config.word_freq_dict_path)
        return cls.create_from_freq_dicts(
            freq,
            max_token_vocab_size=config.max_token_vocab_size,
            max_path_vocab_size=config.max_path_vocab_size,
            max_target_vocab_size=config.max_target_vocab_size,
            separate_oov_and_pad=config.separate_oov_and_pad)

    @classmethod
    def load(cls, path: str, separate_oov_and_pad: bool = False) -> "Code2VecVocabs":
        # Stored order is token, target, path (reference: vocabularies.py:175-185).
        with open(path, "rb") as f:
            token_vocab = Vocab.load_from_file(
                VocabType.Token, f,
                special_words_for(VocabType.Token, separate_oov_and_pad))
            target_vocab = Vocab.load_from_file(
                VocabType.Target, f,
                special_words_for(VocabType.Target, separate_oov_and_pad))
            path_vocab = Vocab.load_from_file(
                VocabType.Path, f,
                special_words_for(VocabType.Path, separate_oov_and_pad))
        vocabs = cls(token_vocab, path_vocab, target_vocab)
        vocabs._already_saved_in_paths.add(path)
        return vocabs

    def save(self, path: str) -> None:
        # reference: vocabularies.py:211-218 (token, target, path order).
        if path in self._already_saved_in_paths:
            return
        with open(path, "wb") as f:
            self.token_vocab.save_to_file(f)
            self.target_vocab.save_to_file(f)
            self.path_vocab.save_to_file(f)
        self._already_saved_in_paths.add(path)

    def get(self, vocab_type: VocabType) -> Vocab:
        return {
            VocabType.Token: self.token_vocab,
            VocabType.Target: self.target_vocab,
            VocabType.Path: self.path_vocab,
        }[vocab_type]
