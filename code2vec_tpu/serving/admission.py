"""Admission control + end-to-end request deadlines: overload honesty.

The PR-7 server accepted unbounded work: every request queued behind the
extractor pool and the batcher no matter how deep the backlog, so under
overload *every* client saw unbounded latency and none saw an honest
"try later". This module makes overload a first-class, measurable
outcome instead of an emergent hang:

- **Deadline**: every request carries one, from `--serve_deadline_ms`
  (client-overridable via the `X-Deadline-Ms` header, clamped by
  `--serve_deadline_max_ms`). The deadline object travels the whole
  pipeline: the extractor pool reuses the remaining budget as its
  per-request timeout, the batcher refuses to coalesce a request whose
  remaining budget can't cover the bucket's observed p95 device time,
  and a request that expires mid-pipeline settles as 504 without ever
  occupying a device slot.
- **AdmissionController**: a bounded admission gate in front of the
  cache-miss pipeline. A request is SHED (503 + `Retry-After`) when the
  pipeline already holds `--serve_queue_depth` requests, or when the
  estimated queue wait (depth x EWMA request duration / pipeline
  concurrency) exceeds the request's remaining deadline budget — there
  is no point admitting work that will certainly 504.

Shed vocabulary (one counter family, pinned in tests and alerted on —
README "Operating the server"):

    serving_requests_shed_total{reason=queue_full|deadline|breaker|
                                draining|tenant_quota}

With a tenancy policy (serving/tenancy.py, `--serve_tenants`) the gate
is additionally weighted-fair: each recently-active tenant owns a
share of `max_depth` proportional to its configured weight, a tenant
over its share (or over its token-bucket rate quota) sheds as
`tenant_quota` with a Retry-After derived from ITS OWN state — the
bucket's refill time for a rate shed, its own in-flight drain estimate
for a share shed — never the fleet-wide queue estimate, while
in-share tenants keep their full deadline budget.

`Shed` (503, the request was never worked on — retry elsewhere/later)
is deliberately distinct from `DeadlineExceeded` (504, the request was
admitted but its budget ran out mid-pipeline, counted in
`serving_requests_expired_total{stage=...}`).

Fault point `admission_enqueue` (utils/faults.py) fires on the admit
path so the serving chaos suite can prove an admission-layer fault
surfaces as an honest error, never a hang or a corrupt response.
"""

from __future__ import annotations

import math
import random
import threading
import time
from typing import Optional

from code2vec_tpu import obs
from code2vec_tpu.utils.faults import fault_point

_G_DEPTH = obs.gauge(
    "serving_admission_depth",
    "requests admitted into the cache-miss pipeline and not yet "
    "finished (the admission queue bound applies to this)")


_SHED_HELP = (
    "requests refused with an honest 503 before any pipeline work: "
    "queue_full (admission depth at the bound), deadline (estimated "
    "wait or device time exceeds the request's remaining budget), "
    "breaker (a circuit breaker is open), draining (SIGTERM grace), "
    "tenant_quota (the tenant is over its fair share or rate quota — "
    "serving/tenancy.py)")


def _shed_counter(reason: str):
    return obs.counter("serving_requests_shed_total", _SHED_HELP,
                       reason=reason)


def expired_counter(stage: str):
    return obs.counter(
        "serving_requests_expired_total",
        "admitted requests whose deadline ran out mid-pipeline (504); "
        "stage says how far they got before expiring",
        stage=stage)


def retry_after_seconds(base_s: float, jitter_frac: float = 0.5) -> int:
    """Retry-After header value for a shed: the base estimate plus up
    to `jitter_frac` of it in random jitter, rounded up to integer
    seconds (>= 1). A fleet-wide shed (open breaker, drain, overload)
    otherwise teaches every client the SAME retry instant, and the
    synchronized retry storm hits the recovering server at full
    amplitude — jitter decorrelates the herd."""
    base = max(1.0, float(base_s))
    return max(1, int(math.ceil(
        base * (1.0 + random.random() * max(0.0, jitter_frac)))))


class Shed(Exception):
    """Request refused before any pipeline work — an honest 503. The
    server maps `reason` onto serving_requests_shed_total and
    `retry_after_s` onto the Retry-After header."""

    def __init__(self, reason: str, message: str,
                 retry_after_s: float = 1.0):
        super().__init__(message)
        self.reason = reason
        self.retry_after_s = max(1.0, float(retry_after_s))

    def count(self) -> None:
        _shed_counter(self.reason).inc()


class DeadlineExceeded(Exception):
    """An ADMITTED request's budget ran out mid-pipeline — a 504. Kept
    distinct from Shed: a 503 was never worked on, a 504 was."""


class DeadlineInfeasible(Shed):
    """The batcher's fail-fast refusal: the request has budget left but
    its bucket's observed p95 device time alone exceeds it, so admitting
    it to a device batch would only burn a slot on a guaranteed 504.
    A Shed subclass — the request was not worked on."""

    def __init__(self, message: str, retry_after_s: float = 1.0):
        super().__init__("deadline", message, retry_after_s)


class Deadline:
    """Monotonic per-request budget. `budget_s` <= 0 means unbounded
    (no default configured and no header) — `remaining()` is +inf and
    `expired()` never fires, so one code path serves both."""

    __slots__ = ("t0", "budget_s")

    def __init__(self, budget_s: float):
        self.t0 = time.monotonic()
        self.budget_s = float(budget_s)

    @property
    def bounded(self) -> bool:
        return self.budget_s > 0

    def remaining(self) -> float:
        if not self.bounded:
            return math.inf
        return self.budget_s - (time.monotonic() - self.t0)

    def expired(self) -> bool:
        return self.bounded and self.remaining() <= 0


def deadline_from_request(config, header_ms: Optional[str]) -> Deadline:
    """Resolve one request's deadline: `X-Deadline-Ms` header when
    present (client knows its own SLO), else `--serve_deadline_ms`,
    both clamped by `--serve_deadline_max_ms` so a client cannot pin a
    pipeline slot forever. An unparsable header is treated as absent
    (the server-side default still applies) rather than rejected — a
    malformed hint must not turn a servable request into a 400."""
    budget_ms = float(getattr(config, "serve_deadline_ms", 0.0))
    if header_ms is not None:
        try:
            requested = float(header_ms)
        except (TypeError, ValueError):
            requested = None
        if requested is not None and requested > 0:
            budget_ms = requested
    max_ms = float(getattr(config, "serve_deadline_max_ms", 0.0))
    if max_ms > 0 and budget_ms > 0:
        budget_ms = min(budget_ms, max_ms)
    elif max_ms > 0 and budget_ms <= 0:
        # No default and no header, but a max is configured: the max IS
        # the budget — "unbounded" requests still cannot outlive it.
        budget_ms = max_ms
    return Deadline(budget_ms / 1000.0)


class AdmissionController:
    """Bounded admission gate for the cache-miss pipeline.

    `admit(deadline)` either returns (the caller MUST pair it with
    `finish(duration_s)` in a finally) or raises `Shed`. The queue-wait
    estimate is depth x EWMA(total request duration) / `concurrency`
    (the extractor pool size — the serving bottleneck on the miss
    path); until the first completion seeds the EWMA only the hard
    depth bound sheds, so a cold server never refuses its first
    requests on a bogus estimate.

    With `tenancy` (a serving/tenancy.TenantPolicy) the gate is
    weighted-fair: `admit(deadline, tenant=label)` first charges the
    tenant's token bucket (over-rate ⇒ `tenant_quota` shed whose
    Retry-After is the BUCKET's refill time), then checks the tenant's
    share of `max_depth`. The share bound is weight-proportional over
    the tenants seen inside the policy's active window — a lone tenant
    keeps the whole queue (and behaves bit-identically to the
    tenancy-free gate), while under contention each tenant's in-flight
    depth is capped at floor(max_depth x weight / active weights), so
    the most-over-share tenant is always the first refused and the sum
    of bounds never exceeds the global bound. A share shed's
    Retry-After is the TENANT's own drain estimate (its depth x EWMA /
    concurrency), not the fleet-wide wait.
    """

    def __init__(self, max_depth: int, concurrency: int = 1,
                 ewma_alpha: float = 0.2, tenancy=None):
        self.max_depth = max(1, int(max_depth))
        self.concurrency = max(1, int(concurrency))
        self._alpha = float(ewma_alpha)
        self.tenancy = tenancy
        self._lock = threading.Lock()
        self._depth = 0
        self._ewma_s: Optional[float] = None
        self._tenant_depth: dict = {}
        self._tenant_seen: dict = {}   # label -> last admit-attempt ts

    @property
    def depth(self) -> int:
        return self._depth

    def estimated_wait_s(self) -> Optional[float]:
        """Expected queue wait for a request admitted NOW; None until
        the EWMA has a sample."""
        with self._lock:
            if self._ewma_s is None:
                return None
            return self._depth * self._ewma_s / self.concurrency

    def tenant_depth(self, label: str) -> int:
        with self._lock:
            return self._tenant_depth.get(label, 0)

    def tenant_bound(self, label: str) -> int:
        """This tenant's current in-flight bound (for /healthz and the
        fairness-law tests): its weighted share of `max_depth` over
        the recently-active tenant set."""
        with self._lock:
            return self._tenant_bound_locked(label)

    def _tenant_bound_locked(self, label: str) -> int:
        pol = self.tenancy
        now = pol.clock()
        self._tenant_seen[label] = now
        horizon = now - pol.active_window_s
        for t in [t for t, ts in self._tenant_seen.items()
                  if ts < horizon and not self._tenant_depth.get(t)]:
            del self._tenant_seen[t]
        active = set(self._tenant_seen) | set(self._tenant_depth)
        total = sum(pol.weight(t) for t in active)
        if total <= 0:
            return self.max_depth
        # floor keeps sum(bounds) <= max_depth, so in-share tenants
        # never hit the global queue_full path while every contender
        # respects its share; max(1,...) keeps a tiny-weight tenant
        # servable at all.
        return max(1, int(self.max_depth * pol.weight(label) / total))

    def admit(self, deadline: Optional[Deadline] = None,
              tenant: Optional[str] = None) -> None:
        fault_point("admission_enqueue")
        pol = self.tenancy
        if pol is not None and tenant is not None:
            bucket = pol.bucket(tenant)
            if bucket is not None and not bucket.try_take():
                # the bugfix contract: a rate-quota shed's Retry-After
                # comes from THIS tenant's bucket refill time, never
                # the fleet-wide queue-wait estimate
                raise Shed(
                    "tenant_quota",
                    f"tenant {tenant!r} is over its rate quota",
                    retry_after_s=bucket.retry_after_s())
        with self._lock:
            if pol is not None and tenant is not None:
                bound = self._tenant_bound_locked(tenant)
                held = self._tenant_depth.get(tenant, 0)
                # bound == max_depth means no contention (a lone
                # tenant owns the whole queue): fall through to the
                # global gate so the shed reason — and the behavior —
                # stay exactly the tenancy-free queue_full
                if held >= bound and bound < self.max_depth:
                    # tenant-scoped wait: how long until ITS in-flight
                    # requests drain, not the whole queue's
                    wait = (self._ewma_s or 1.0) * max(held, 1) \
                        / self.concurrency
                    raise Shed(
                        "tenant_quota",
                        f"tenant {tenant!r} is over its fair share "
                        f"({held}/{bound} of {self.max_depth} in "
                        f"flight)",
                        retry_after_s=wait)
            if self._depth >= self.max_depth:
                wait = (self._ewma_s or 1.0) * self.max_depth \
                    / self.concurrency
                raise Shed(
                    "queue_full",
                    f"admission queue full ({self._depth}/"
                    f"{self.max_depth} in flight)",
                    retry_after_s=wait)
            if (deadline is not None and deadline.bounded
                    and self._ewma_s is not None):
                est = self._depth * self._ewma_s / self.concurrency
                if est > deadline.remaining():
                    raise Shed(
                        "deadline",
                        f"estimated queue wait {est * 1e3:.0f}ms exceeds "
                        f"the request's remaining deadline budget "
                        f"{max(deadline.remaining(), 0) * 1e3:.0f}ms",
                        retry_after_s=est)
            self._depth += 1
            if pol is not None and tenant is not None:
                self._tenant_depth[tenant] = \
                    self._tenant_depth.get(tenant, 0) + 1
            _G_DEPTH.set(self._depth)

    def finish(self, duration_s: float,
               tenant: Optional[str] = None) -> None:
        with self._lock:
            self._depth = max(0, self._depth - 1)
            if tenant is not None:
                held = self._tenant_depth.get(tenant, 0) - 1
                if held > 0:
                    self._tenant_depth[tenant] = held
                else:
                    self._tenant_depth.pop(tenant, None)
            _G_DEPTH.set(self._depth)
            if duration_s >= 0:
                if self._ewma_s is None:
                    self._ewma_s = float(duration_s)
                else:
                    self._ewma_s += self._alpha * (duration_s
                                                   - self._ewma_s)
