"""Bridge to an AST path extractor producing raw context lines.

Preference order:
1. the framework's native C++ extractor (`cpp/` build, `c2v-extract`);
2. the reference's shipped Java jar (a data producer, not model runtime —
   SURVEY.md §7 'minimum end-to-end slice').

Reproduces the reference driver semantics (extractor.py:11-38): run with
`--no_hash` so paths come out readable, truncate to MAX_CONTEXTS, re-hash
each path string with Java's String#hashCode (the training data stores
hashed paths), keep hash->string for the attention display.
"""

from __future__ import annotations

import os
import shutil
import subprocess
import time
from typing import Dict, List, Optional, Tuple

from code2vec_tpu import obs
from code2vec_tpu.common import java_string_hashcode

_H_EXTRACT = obs.histogram(
    "extractor_seconds",
    "serving-side path extraction: subprocess spawn to parsed contexts")
_C_CALLS = obs.counter("extractor_calls_total",
                       "serving-side extractions attempted")
_C_TIMEOUTS = obs.counter(
    "extractor_timeouts_total",
    "extractor children killed after config.extractor_timeout_s")
_C_FAILURES = obs.counter(
    "extractor_failures_total",
    "extractions that failed (nonzero exit / empty output), "
    "timeouts excluded")

DEFAULT_JAR_PATH = "JavaExtractor/JPredict/target/JavaExtractor-0.0.1-SNAPSHOT.jar"
NATIVE_EXTRACTOR_ENV = "C2V_NATIVE_EXTRACTOR"


class ExtractionTimeout(ValueError):
    """A hung extractor child was killed after the configured timeout.
    Subclasses ValueError so every existing extraction-failure handler
    (e.g. the interactive REPL's catch-print-continue) treats a timeout
    like any other failed extraction instead of crashing the session."""


def _native_extractor_path() -> str:
    env = os.environ.get(NATIVE_EXTRACTOR_ENV)
    if env:
        return env
    here = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return os.path.join(here, "cpp", "build", "c2v-extract")


class PathExtractor:
    def __init__(self, config, jar_path: str = DEFAULT_JAR_PATH,
                 max_path_length: int = 8, max_path_width: int = 2,
                 timeout: Optional[float] = None):
        self.config = config
        self.jar_path = jar_path
        self.max_path_length = max_path_length
        self.max_path_width = max_path_width
        # The offline preprocess pipeline kills hung extractions after a
        # timeout (data/preprocess.py); the serving bridge needs the same
        # or one wedged child hangs the predict request forever. None
        # defers to config.extractor_timeout_s; <= 0 disables.
        if timeout is None:
            timeout = float(getattr(config, "extractor_timeout_s", 120.0))
        self.timeout = timeout if timeout > 0 else None

    def _build_command(self, path: str) -> List[str]:
        native = _native_extractor_path()
        if os.path.exists(native):
            return [native, "--max_path_length", str(self.max_path_length),
                    "--max_path_width", str(self.max_path_width),
                    "--file", path, "--no_hash"]
        if os.path.exists(self.jar_path) and shutil.which("java"):
            return ["java", "-cp", self.jar_path, "JavaExtractor.App",
                    "--max_path_length", str(self.max_path_length),
                    "--max_path_width", str(self.max_path_width),
                    "--file", path, "--no_hash"]
        raise FileNotFoundError(
            f"No extractor available: native binary `{native}` not built and "
            f"jar `{self.jar_path}` not present (or no java runtime).")

    def extract_paths(self, path: str) -> Tuple[List[str], Dict[str, str]]:
        _C_CALLS.inc()
        t0 = time.perf_counter()
        try:
            return self._extract_paths_inner(path)
        finally:
            dur = time.perf_counter() - t0
            _H_EXTRACT.observe(dur)
            obs.default_tracer().maybe_record("extract_paths", t0, dur)

    def _extract_paths_inner(self, path: str
                             ) -> Tuple[List[str], Dict[str, str]]:
        command = self._build_command(path)
        process = subprocess.Popen(command, stdout=subprocess.PIPE,
                                   stderr=subprocess.PIPE)
        try:
            out, err = process.communicate(timeout=self.timeout)
        except subprocess.TimeoutExpired:
            process.kill()
            out, err = process.communicate()
            _C_TIMEOUTS.inc()
            raise ExtractionTimeout(
                f"path extraction of {path} exceeded {self.timeout:g}s "
                f"and was killed; partial stderr: "
                f"{err.decode(errors='replace').strip()!r}")
        output = out.decode().splitlines()
        if process.returncode != 0:
            # Surface stderr even when the child produced some stdout —
            # a nonzero exit means the extraction is incomplete and the
            # partial output must not be silently served.
            _C_FAILURES.inc()
            raise ValueError(
                f"extractor exited with code {process.returncode} on "
                f"{path} ({len(output)} stdout lines discarded); stderr: "
                f"{err.decode(errors='replace').strip()!r}")
        if len(output) == 0:
            _C_FAILURES.inc()
            raise ValueError(err.decode())
        hash_to_string: Dict[str, str] = {}
        result = []
        max_contexts = self.config.max_contexts
        for line in output:
            parts = line.rstrip().split(" ")
            line_parts = [parts[0]]
            contexts = parts[1:]
            for context in contexts[:max_contexts]:
                w1, p, w2 = context.split(",")
                hashed = str(java_string_hashcode(p))
                hash_to_string[hashed] = p
                line_parts.append(f"{w1},{hashed},{w2}")
            padding = " " * (max_contexts - len(contexts))
            result.append(" ".join(line_parts) + padding)
        return result, hash_to_string
