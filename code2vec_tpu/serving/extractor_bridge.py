"""Bridge to an AST path extractor producing raw context lines.

Preference order:
1. the framework's native C++ extractor (`cpp/` build, `c2v-extract`);
2. the reference's shipped Java jar (a data producer, not model runtime —
   SURVEY.md §7 'minimum end-to-end slice').

Reproduces the reference driver semantics (extractor.py:11-38): run with
`--no_hash` so paths come out readable, truncate to MAX_CONTEXTS, re-hash
each path string with Java's String#hashCode (the training data stores
hashed paths), keep hash->string for the attention display.
"""

from __future__ import annotations

import os
import shutil
import subprocess
import time
from typing import Dict, List, Optional, Tuple

from code2vec_tpu import obs
from code2vec_tpu.common import java_string_hashcode

_H_EXTRACT = obs.histogram(
    "extractor_seconds",
    "serving-side path extraction: subprocess spawn to parsed contexts")
_C_CALLS = obs.counter("extractor_calls_total",
                       "serving-side extractions attempted")
_C_TIMEOUTS = obs.counter(
    "extractor_timeouts_total",
    "extractor children killed after config.extractor_timeout_s")

_FAILURES_HELP = ("extractions that failed (nonzero exit / empty output / "
                  "launch failure), timeouts excluded; retried=yes means "
                  "another attempt followed, retried=no means the failure "
                  "was surfaced to the caller")


def _count_failure(retried: bool) -> None:
    obs.counter("extractor_failures_total", _FAILURES_HELP,
                retried="yes" if retried else "no").inc()

DEFAULT_JAR_PATH = "JavaExtractor/JPredict/target/JavaExtractor-0.0.1-SNAPSHOT.jar"
NATIVE_EXTRACTOR_ENV = "C2V_NATIVE_EXTRACTOR"


class ExtractionTimeout(ValueError):
    """A hung extractor child was killed after the configured timeout.
    Subclasses ValueError so every existing extraction-failure handler
    (e.g. the interactive REPL's catch-print-continue) treats a timeout
    like any other failed extraction instead of crashing the session."""


class ExtractorCrash(ValueError):
    """The extractor child DIED rather than rejecting its input: killed
    by a signal (negative returncode) or a fatal-exit code >= 126
    (137 = SIGKILL/OOM, 134 = SIGABRT, ...). Distinguished from plain
    nonzero diagnostic exits because only crashes are plausibly
    transient (memory pressure, fork storms) and therefore retried;
    a parser that deterministically rejects a file would fail
    identically on every retry and only add latency."""


def _native_extractor_path() -> str:
    env = os.environ.get(NATIVE_EXTRACTOR_ENV)
    if env:
        return env
    here = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return os.path.join(here, "cpp", "build", "c2v-extract")


def postprocess_extractor_output(output: List[str], max_contexts: int
                                 ) -> Tuple[List[str], Dict[str, str]]:
    """Turn raw `--no_hash` extractor lines into model-ready predict
    lines: truncate to `max_contexts`, re-hash each readable path with
    Java's String#hashCode (the training data stores hashed paths), pad
    to a fixed context count, and keep hash->string for the attention
    display. Shared by the one-shot bridge below and the warm worker
    pool (serving/extractor_pool.py) so both produce byte-identical
    predict input (reference driver semantics: extractor.py:11-38)."""
    hash_to_string: Dict[str, str] = {}
    result = []
    for line in output:
        parts = line.rstrip().split(" ")
        line_parts = [parts[0]]
        contexts = parts[1:]
        for context in contexts[:max_contexts]:
            w1, p, w2 = context.split(",")
            hashed = str(java_string_hashcode(p))
            hash_to_string[hashed] = p
            line_parts.append(f"{w1},{hashed},{w2}")
        padding = " " * (max_contexts - len(contexts))
        result.append(" ".join(line_parts) + padding)
    return result, hash_to_string


class PathExtractor:
    # backoff before retry attempt k (1-based) is _RETRY_BACKOFF_BASE_S *
    # 2**(k-1), capped — a crashed child usually hit transient pressure
    # (fork storm, OOM kill), which a short pause outlasts.
    _RETRY_BACKOFF_BASE_S = 0.2
    _RETRY_BACKOFF_CAP_S = 2.0

    def __init__(self, config, jar_path: str = DEFAULT_JAR_PATH,
                 max_path_length: int = 8, max_path_width: int = 2,
                 timeout: Optional[float] = None,
                 retries: Optional[int] = None):
        self.config = config
        self.jar_path = jar_path
        self.max_path_length = max_path_length
        self.max_path_width = max_path_width
        # The offline preprocess pipeline kills hung extractions after a
        # timeout (data/preprocess.py); the serving bridge needs the same
        # or one wedged child hangs the predict request forever. None
        # defers to config.extractor_timeout_s; <= 0 disables.
        if timeout is None:
            timeout = float(getattr(config, "extractor_timeout_s", 120.0))
        self.timeout = timeout if timeout > 0 else None
        # Launch/crash retries (config.extractor_retries). Timeouts are
        # NOT retried: a child that hung once will likely hang again,
        # and the caller already waited a full timeout.
        if retries is None:
            retries = int(getattr(config, "extractor_retries", 2))
        self.retries = max(retries, 0)

    def _build_command(self, path: str) -> List[str]:
        native = _native_extractor_path()
        if os.path.exists(native):
            return [native, "--max_path_length", str(self.max_path_length),
                    "--max_path_width", str(self.max_path_width),
                    "--file", path, "--no_hash"]
        if os.path.exists(self.jar_path) and shutil.which("java"):
            return ["java", "-cp", self.jar_path, "JavaExtractor.App",
                    "--max_path_length", str(self.max_path_length),
                    "--max_path_width", str(self.max_path_width),
                    "--file", path, "--no_hash"]
        raise FileNotFoundError(
            f"No extractor available: native binary `{native}` not built and "
            f"jar `{self.jar_path}` not present (or no java runtime).")

    def extract_paths(self, path: str) -> Tuple[List[str], Dict[str, str]]:
        _C_CALLS.inc()
        t0 = time.perf_counter()
        try:
            return self._extract_with_retries(path)
        finally:
            dur = time.perf_counter() - t0
            _H_EXTRACT.observe(dur)
            obs.default_tracer().maybe_record("extract_paths", t0, dur)

    def _extract_with_retries(self, path: str
                              ) -> Tuple[List[str], Dict[str, str]]:
        """Bounded retry-with-backoff around one extraction. Retried:
        subprocess launch failures (OSError from Popen) and child
        CRASHES (ExtractorCrash: signal-killed / fatal-exit codes).
        Not retried: deterministic rejections (plain nonzero diagnostic
        exits, empty output — identical on every retry), timeouts
        (their own policy, see __init__), and missing-extractor setup
        errors (FileNotFoundError from _build_command — no number of
        retries builds the binary)."""
        for attempt in range(self.retries + 1):
            try:
                return self._extract_paths_inner(path)
            except ExtractionTimeout:
                raise
            except FileNotFoundError:
                raise  # no extractor installed at all — not transient
            except (ExtractorCrash, OSError) as e:
                final = attempt == self.retries
                _count_failure(retried=not final)
                if final:
                    raise
                backoff = min(self._RETRY_BACKOFF_BASE_S * (2 ** attempt),
                              self._RETRY_BACKOFF_CAP_S)
                time.sleep(backoff)
            except ValueError:
                _count_failure(retried=False)
                raise

    def _extract_paths_inner(self, path: str,
                             timeout: Optional[float] = None
                             ) -> Tuple[List[str], Dict[str, str]]:
        # `timeout` overrides the configured hang timeout for this one
        # attempt — the serving pool passes the request's remaining
        # deadline budget when that is the tighter bound.
        effective = self.timeout if timeout is None else timeout
        command = self._build_command(path)
        process = subprocess.Popen(command, stdout=subprocess.PIPE,
                                   stderr=subprocess.PIPE)
        try:
            out, err = process.communicate(timeout=effective)
        except subprocess.TimeoutExpired:
            process.kill()
            out, err = process.communicate()
            _C_TIMEOUTS.inc()
            raise ExtractionTimeout(
                f"path extraction of {path} exceeded {effective:g}s "
                f"and was killed; partial stderr: "
                f"{err.decode(errors='replace').strip()!r}")
        output = out.decode().splitlines()
        if process.returncode != 0:
            # Surface stderr even when the child produced some stdout —
            # a nonzero exit means the extraction is incomplete and the
            # partial output must not be silently served. (Failure
            # counting lives in _extract_with_retries, which also knows
            # whether another attempt follows.) Signal deaths and
            # fatal-exit codes raise the retryable crash subclass.
            crashed = process.returncode < 0 or process.returncode >= 126
            exc_type = ExtractorCrash if crashed else ValueError
            raise exc_type(
                f"extractor {'crashed' if crashed else 'exited'} with "
                f"code {process.returncode} on "
                f"{path} ({len(output)} stdout lines discarded); stderr: "
                f"{err.decode(errors='replace').strip()!r}")
        if len(output) == 0:
            raise ValueError(err.decode())
        return postprocess_extractor_output(output, self.config.max_contexts)
