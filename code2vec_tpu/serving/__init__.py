from code2vec_tpu.serving.extractor_bridge import PathExtractor  # noqa: F401
from code2vec_tpu.serving.interactive import InteractivePredictor  # noqa: F401
