"""Batched prediction HTTP server: the paper's model behind real traffic.

Request path:

    POST /predict  --cache miss-->  extractor pool (warm --server
    workers) --> dynamic batcher (coalesce + context-bucketed padded
    shapes) --> jitted predict step --> JSON response --> LRU cache

Endpoints (JSON unless noted; schema in README "Serving"):

- `POST /predict`  body = raw Java source (or `{"code": "..."}`);
  per-method top-k name predictions + attention paths (+ code vectors
  when the model was created with --export_code_vectors).
- `POST /embed`    same input; code vectors only (forces them on
  regardless of --export_code_vectors — the embedding IS the product).
- `GET  /healthz`  liveness + pool/batcher/cache gauges; `"status":
  "serving"` flips to `"draining"` during SIGTERM grace.
- `GET  /metrics`  Prometheus text format — the same registry/plumbing
  as the trainer's --metrics_port (obs/exporters.py).

Every request is timed into per-phase SLO histograms
(`serving_request_seconds{phase=queue_wait|extract|batch_wait|device|
total}`) through the PR-2 MetricsRegistry, so p50/p99 per phase come
free from any Prometheus scrape.

Shutdown mirrors the trainer's preemption-grace pattern
(training/loop.py PreemptionWatcher): SIGTERM stops intake, in-flight
requests finish (bounded by config.serve_drain_timeout_s), the batcher
flushes, the extractor pool is torn down, and the process exits 0.
"""

from __future__ import annotations

import http.server
import json
import os
import signal
import socketserver
import threading
import time
from typing import Dict, Optional

from code2vec_tpu import obs
from code2vec_tpu.serving.batcher import DynamicBatcher
from code2vec_tpu.serving.cache import PredictionCache, cache_key
from code2vec_tpu.serving.extractor_bridge import ExtractorCrash
from code2vec_tpu.serving.extractor_pool import ExtractorPool
from code2vec_tpu.serving.interactive import parse_prediction_results

_PHASES = ("queue_wait", "extract", "batch_wait", "device", "total")


def _phase_hist(phase: str):
    return obs.histogram(
        "serving_request_seconds",
        "per-request serving latency by phase: queue_wait (extractor "
        "slot), extract (path extraction), batch_wait (coalescing), "
        "device (model call), total (end to end)", phase=phase)


_H_PHASE = {p: _phase_hist(p) for p in _PHASES}


def _requests_counter(endpoint: str, status: str):
    return obs.counter("serving_requests_total",
                       "HTTP requests by endpoint and outcome",
                       endpoint=endpoint, status=status)


class _HTTPError(Exception):
    def __init__(self, code: int, message: str):
        super().__init__(message)
        self.code = code


class PredictionServer:
    """Owns the pool + batcher + cache around one Code2VecModel.

    Separable from HTTP: `handle(endpoint, code)` returns the response
    bytes, so tests and the bench can drive the full path in-process,
    and the HTTP layer stays a thin framing shim.
    """

    def __init__(self, model, config=None, log=None):
        self.model = model
        self.config = config or model.config
        self.log = log or self.config.log
        self.pool = ExtractorPool(
            self.config, size=self.config.extractor_pool_size, log=self.log)
        # with_code_vectors=True: /predict and /embed rows coalesce into
        # the SAME batches (a per-endpoint batcher would halve fill);
        # the step computes vectors anyway, the flag only materializes
        # them host-side, and _render decides per endpoint what ships.
        self.batcher = DynamicBatcher(
            lambda lines: model.predict(
                lines, batch_size=self.config.serve_batch_size,
                with_code_vectors=True),
            max_batch_rows=self.config.serve_batch_size,
            max_delay_s=self.config.serve_max_delay_ms / 1000.0)
        self.cache = PredictionCache(self.config.serve_cache_entries)
        self.topk = self.config.top_k_words_considered_during_prediction
        # Model-identity token mixed into every cache key: a hot-swapped
        # checkpoint or re-exported artifact must never serve a stale
        # cached prediction (the key hashes source + knobs only
        # otherwise). Surfaced in /healthz so a deploy can assert which
        # weights a replica answers with.
        self.model_fingerprint = model.model_fingerprint()
        self._httpd: Optional[socketserver.BaseServer] = None
        self._inflight = 0
        self._inflight_cond = threading.Condition()
        self._draining = False
        self._drained = threading.Event()
        self.started_at = time.time()
        self.port: Optional[int] = None

    # ---------------------------------------------------------- predict

    def handle(self, endpoint: str, code: str) -> bytes:
        """Full serve path for one request; returns the response BYTES
        (cached verbatim, so a hit is byte-equal to the miss that
        populated it)."""
        if not code.strip():
            raise _HTTPError(400, "empty request body")
        t0 = time.perf_counter()
        phases: Dict[str, float] = {}
        key = cache_key(code, endpoint=endpoint, topk=self.topk,
                        model=self.model_fingerprint)
        cached = self.cache.get(key)
        if cached is not None:
            _H_PHASE["total"].observe(time.perf_counter() - t0)
            return cached  # type: ignore[return-value]
        try:
            lines, hash_to_string = self.pool.extract_source(
                code, phases=phases)
        except FileNotFoundError as e:
            raise _HTTPError(503, f"no extractor available: {e}")
        except (ExtractorCrash, OSError) as e:
            # infra failure (workers dying through every retry), NOT the
            # client's source: 503 tells a well-behaved client to retry.
            # Must precede the ValueError arm — ExtractorCrash subclasses
            # it so the REPL's catch-all keeps working.
            raise _HTTPError(503, f"extractor unavailable: {e}")
        except ValueError as e:  # parse rejection / timeout: input-driven
            raise _HTTPError(422, f"extraction failed: {e}")
        try:
            raw = self.batcher.submit(lines, phases=phases).result()
        except RuntimeError as e:  # draining
            raise _HTTPError(503, str(e))
        body = json.dumps(
            self._render(endpoint, raw, hash_to_string),
            sort_keys=True).encode() + b"\n"
        self.cache.put(key, body)
        phases["total"] = time.perf_counter() - t0
        for phase, dur in phases.items():
            _H_PHASE[phase].observe(dur)
        return body

    def _render(self, endpoint: str, raw, hash_to_string) -> dict:
        if endpoint == "embed":
            return {"model": "code2vec_tpu",
                    "vectors": [
                        ([] if r.code_vector is None
                         else [float(v) for v in r.code_vector])
                        for r in raw],
                    "method_names": [r.original_name for r in raw]}
        oov = self.model.vocabs.target_vocab.special_words.oov
        methods = []
        for r, parsed in zip(raw, parse_prediction_results(
                raw, hash_to_string, oov, topk=10)):
            entry = {
                "original_name": r.original_name,
                "predictions": [
                    {"name": p["name"], "probability": p["probability"]}
                    for p in parsed.predictions],
                "attention_paths": parsed.attention_paths,
            }
            # /predict ships vectors only when the model was created
            # with --export_code_vectors (/embed always does).
            if (self.config.export_code_vectors
                    and r.code_vector is not None):
                entry["code_vector"] = [float(v) for v in r.code_vector]
            methods.append(entry)
        return {"model": "code2vec_tpu", "methods": methods}

    def handle_embed(self, code: str) -> bytes:
        return self.handle("embed", code)

    # ------------------------------------------------------------- http

    def healthz(self) -> dict:
        return {
            "status": "draining" if self._draining else "serving",
            "uptime_s": time.time() - self.started_at,
            "pid": os.getpid(),
            "model_fingerprint": self.model_fingerprint,
            "extractor_pool": {"size": self.pool.size,
                               "warm": self.pool.warm},
            "batcher": {"max_batch_rows": self.batcher.max_batch_rows,
                        "max_delay_ms":
                            self.batcher.max_delay_s * 1000.0,
                        "batches_dispatched":
                            self.batcher.batches_dispatched},
            "cache": {"capacity": self.cache.capacity,
                      "entries": len(self.cache)},
            "buckets": list(self.model.context_buckets),
            # compiled shapes AT THE SERVE BATCH SIZE — the serving
            # compilation budget, bounded by len(buckets). (An offline
            # predict through the same facade at another batch size
            # adds its own bounded set; predict_compile_count() has the
            # overall number.) list() snapshots the dict atomically —
            # the batcher thread inserts newly compiled shapes
            # concurrently, and a generator over the live dict could
            # raise mid-iteration.
            "compiled_predict_steps": sum(
                1 for rows, _ in list(self.model._predict_steps)
                if rows == self.config.serve_batch_size),
            "compiled_predict_steps_all": (
                self.model.predict_compile_count()),
            "inflight": self._inflight,
        }

    def start(self, port: Optional[int] = None,
              host: Optional[str] = None) -> int:
        """Bind + serve on a daemon thread; returns the bound port
        (port 0 picks a free one)."""
        server = self

        class Handler(http.server.BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *args):  # per-request stderr silenced
                pass

            def _respond(self, code: int, body: bytes,
                         ctype: str = "application/json") -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _error(self, code: int, message: str) -> None:
                self._respond(code, json.dumps(
                    {"error": message}).encode() + b"\n")

            def do_GET(self):  # noqa: N802 (stdlib API name)
                path = self.path.split("?", 1)[0]
                try:
                    if path == "/healthz":
                        self._respond(200, json.dumps(
                            server.healthz(),
                            sort_keys=True).encode() + b"\n")
                    elif path in ("/metrics", "/"):
                        self._respond(
                            200, obs.default_registry()
                            .render_prometheus().encode(),
                            ctype="text/plain; version=0.0.4; "
                                  "charset=utf-8")
                    else:
                        self._error(404, f"no such endpoint: {path}")
                except Exception as e:  # noqa: BLE001 — a probe must get
                    # an HTTP response, never a torn connection (a failed
                    # liveness probe can restart-loop the replica)
                    self._error(500, f"{type(e).__name__}: {e}")

            def do_POST(self):  # noqa: N802 (stdlib API name)
                path = self.path.split("?", 1)[0]
                endpoint = path.lstrip("/")
                if endpoint not in ("predict", "embed"):
                    self._error(404, f"no such endpoint: {path}")
                    return
                if not server._enter_request():
                    _requests_counter(endpoint, "draining").inc()
                    self._error(503, "server is draining")
                    return
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    raw = self.rfile.read(length)
                    code = server._decode_body(raw, self.headers)
                    body = server.handle(endpoint, code)
                except _HTTPError as e:
                    _requests_counter(endpoint, str(e.code)).inc()
                    self._error(e.code, str(e))
                except Exception as e:  # noqa: BLE001 — 500, not a hang
                    _requests_counter(endpoint, "500").inc()
                    self._error(500, f"{type(e).__name__}: {e}")
                else:
                    _requests_counter(endpoint, "200").inc()
                    self._respond(200, body)
                finally:
                    server._exit_request()

        httpd = http.server.ThreadingHTTPServer(
            (host if host is not None else self.config.serve_host,
             port if port is not None else self.config.serve_port),
            Handler)
        httpd.daemon_threads = True
        self._httpd = httpd
        self.port = httpd.server_address[1]
        threading.Thread(target=httpd.serve_forever,
                         name="serving-http", daemon=True).start()
        self.log(f"Prediction server listening on "
                 f"http://{httpd.server_address[0]}:{self.port} "
                 f"(POST /predict, POST /embed, GET /healthz, "
                 f"GET /metrics)")
        return self.port

    @staticmethod
    def _decode_body(raw: bytes, headers) -> str:
        text = raw.decode("utf-8", errors="replace")
        ctype = (headers.get("Content-Type") or "").split(";")[0].strip()
        if ctype == "application/json":
            try:
                payload = json.loads(text)
            except json.JSONDecodeError as e:
                raise _HTTPError(400, f"bad JSON body: {e}")
            if not isinstance(payload, dict) or "code" not in payload:
                raise _HTTPError(400, 'JSON body must be {"code": "..."}')
            return str(payload["code"])
        return text

    def _enter_request(self) -> bool:
        with self._inflight_cond:
            if self._draining:
                return False
            self._inflight += 1
            return True

    def _exit_request(self) -> None:
        with self._inflight_cond:
            self._inflight -= 1
            self._inflight_cond.notify_all()

    # ------------------------------------------------------------ drain

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Graceful stop: refuse new requests, wait for in-flight ones
        (bounded), flush the batcher, tear down pool + listener.
        Idempotent; returns True when everything in flight finished
        inside the budget."""
        with self._inflight_cond:
            if self._draining:
                self._drained.wait(timeout)
                return self._inflight == 0
            self._draining = True
        budget = (timeout if timeout is not None
                  else self.config.serve_drain_timeout_s)
        self.log(f"Drain: refusing new requests, waiting up to "
                 f"{budget:g}s for {self._inflight} in-flight")
        deadline = time.monotonic() + budget
        clean = True
        with self._inflight_cond:
            while self._inflight > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    clean = False
                    self.log(f"Drain timeout: {self._inflight} "
                             f"request(s) still in flight")
                    break
                self._inflight_cond.wait(timeout=remaining)
        self.batcher.drain(timeout=max(deadline - time.monotonic(), 1.0))
        self.pool.close()
        if self._httpd is not None:
            try:
                self._httpd.shutdown()
                self._httpd.server_close()
            except Exception:
                pass  # teardown must never mask the drain result
        self._drained.set()
        self.log(f"Drain complete ({'clean' if clean else 'timed out'})")
        return clean


def serve_main(config, model=None) -> int:
    """The `serve` CLI subcommand body: build the model, start the
    server, park the main thread until SIGTERM/SIGINT, drain, exit.
    Returns the process exit code."""
    if model is None:
        from code2vec_tpu.model_facade import Code2VecModel
        model = Code2VecModel(config)
    server = PredictionServer(model, config)
    stop = threading.Event()

    def _on_signal(signum, frame):
        config.log(f"Signal {signal.Signals(signum).name} received: "
                   f"draining")
        stop.set()

    prev_term = signal.signal(signal.SIGTERM, _on_signal)
    prev_int = signal.signal(signal.SIGINT, _on_signal)
    server.start()
    if config.heartbeat_file:
        obs.exporters.write_heartbeat(
            config.heartbeat_file, status="serving", port=server.port)
    try:
        stop.wait()
    finally:
        clean = server.drain()
        signal.signal(signal.SIGTERM, prev_term)
        signal.signal(signal.SIGINT, prev_int)
        if config.metrics_file:
            obs.exporters.write_prometheus(config.metrics_file)
        if config.heartbeat_file:
            obs.exporters.write_heartbeat(
                config.heartbeat_file,
                status="done" if clean else "error",
                port=server.port)
    return 0 if clean else 1
