"""Batched prediction HTTP server: the paper's model behind real traffic.

Request path:

    POST /predict  --cache miss-->  admission gate (bounded queue +
    deadline budget check) --> extractor pool (warm --server workers,
    circuit-broken, deadline as timeout) --> dynamic batcher (coalesce +
    context-bucketed padded shapes, deadline-aware) --> jitted predict
    step (circuit-broken) --> JSON response --> LRU cache

Endpoints (JSON unless noted; schema in README "Serving"):

- `POST /predict`  body = raw Java source (or `{"code": "..."}`);
  per-method top-k name predictions + attention paths (+ code vectors
  when the model was created with --export_code_vectors).
- `POST /embed`    same input; code vectors only (forces them on
  regardless of --export_code_vectors — the embedding IS the product).
  Carries `embedding_fingerprint` so clients can detect cross-model
  vector mixing (the same field `/neighbors` stamps).
- `POST /neighbors`  same input; nearest stored methods per input
  method via the mounted retrieval index (`serve --retrieval_index
  DIR`): snippet -> extractor pool -> embed batch -> ANN search ->
  method ids + scores + distances. JSON bodies may add `"k"` /
  `"nprobe"` knobs. Requires the index fingerprint to match the
  weights that embedded the batch — never answers across embedding
  spaces (503 instead).
- `POST /admin/reload`  `{"artifact": DIR}` — health-gated live model
  hot-swap (serving/swap.py): loads + validates off the request path,
  then swaps the model reference between batches. 202 accepted; poll
  `/healthz` `model.swap_status`. SIGHUP re-reads `--artifact`.
- `GET  /healthz`  liveness + pool/batcher/cache/breaker/admission
  gauges; `"status": "serving"` flips to `"draining"` — and the HTTP
  status to 503, the load-balancer eviction contract — during SIGTERM
  grace.
- `GET  /metrics`  Prometheus text format — the same registry/plumbing
  as the trainer's --metrics_port (obs/exporters.py). Under
  `--replicas N` scrape the SUPERVISOR's merged endpoint instead
  (serving/telemetry.py; this per-replica one samples a single
  kernel-chosen replica).
- `POST /admin/dump`  write the incident flight recorder's rings
  (obs/flight.py: last-N terminal request records + anomaly events) to
  a timestamped JSON file now; body `{"path": ...}`.

Request-scoped tracing (obs/reqtrace.py, README "Telemetry"): every
request carries a trace id — inbound W3C `traceparent` honored,
otherwise minted — echoed in the `X-Trace-Id` + `traceparent` response
headers on EVERY terminal status; the request's span tree (admission,
cache lookup, extractor pool, batcher, the shared device-batch span,
render) lands in the ring tracer for the bulk Chrome export and, with
`--serve_debug_trace` + `?debug=trace`, in the response itself.

Resilience semantics (serving/admission.py, serving/breaker.py; README
"Operating the server"):

- every request carries a DEADLINE (`--serve_deadline_ms`, client
  `X-Deadline-Ms` header, clamped by `--serve_deadline_max_ms`),
  propagated through the whole pipeline; expiry mid-pipeline is an
  honest 504 that never occupies a device slot;
- overload SHEDS with 503 + Retry-After instead of queueing unboundedly
  (`serving_requests_shed_total{reason=queue_full|deadline|breaker|
  draining}`);
- circuit breakers around the extractor pool and the device step fail
  fast when a dependency is down — cache hits still serve while the
  extractor breaker is open (graceful degradation);
- every response carries the `model_fingerprint` of the exact weights
  that produced it (hot-swap attribution).

Every request is timed into per-phase SLO histograms
(`serving_request_seconds{phase=queue_wait|extract|batch_wait|device}`),
and the `total` phase carries a `status` label and is recorded for
EVERY terminal status — errored and shed requests are part of the tail,
not invisible.

Shutdown mirrors the trainer's preemption-grace pattern
(training/loop.py PreemptionWatcher): SIGTERM stops intake, in-flight
requests finish (bounded by config.serve_drain_timeout_s), the batcher
flushes, the extractor pool is torn down, and the process exits 0 — or
1 with the abandoned-request count in the final heartbeat when the
drain timed out.
"""

from __future__ import annotations

import http.server
import json
import os
import signal
import socket
import socketserver
import threading
import time
from concurrent.futures import TimeoutError as _FutureTimeout
from typing import Dict, Optional, Tuple

import numpy as np

from code2vec_tpu import obs
from code2vec_tpu.obs.flight import default_flight_recorder
from code2vec_tpu.obs.reqtrace import RequestTrace
from code2vec_tpu.serving.admission import (
    _SHED_HELP, AdmissionController, Deadline, DeadlineExceeded, Shed,
    deadline_from_request, expired_counter, retry_after_seconds,
)
from code2vec_tpu.serving.batcher import (
    ContinuousBatcher, DynamicBatcher, StaleParse,
)
from code2vec_tpu.serving.breaker import CircuitBreaker
from code2vec_tpu.serving.cache import (
    PredictionCache, cache_key_normalized, normalize_source,
)
from code2vec_tpu.serving.extractor_bridge import (
    ExtractionTimeout, ExtractorCrash,
)
from code2vec_tpu.serving.extractor_pool import ExtractorPool
from code2vec_tpu.serving.interactive import parse_prediction_results
from code2vec_tpu.serving.swap import SwapError, SwapManager
from code2vec_tpu.serving.tenancy import (
    TENANT_HEADER, TenantPolicy, tenant_metric,
)
from code2vec_tpu.utils.faults import FaultInjected

_PIPELINE_PHASES = ("queue_wait", "extract", "batch_wait", "device")

# Env hook (set by the serving supervisor): bind the listen socket with
# SO_REUSEPORT so N replica processes share one port and the kernel
# load-balances accepts across them.
REUSEPORT_ENV = "C2V_SERVE_REUSEPORT"

_PHASE_HELP = (
    "per-request serving latency by phase: queue_wait (extractor "
    "slot), extract (path extraction), batch_wait (coalescing), "
    "device (model call), total (end to end; carries a `status` label "
    "and is recorded for EVERY terminal status, shed/errored included)")


def _phase_hist(phase: str):
    return obs.histogram("serving_request_seconds", _PHASE_HELP,
                         phase=phase)


_H_PHASE = {p: _phase_hist(p) for p in _PIPELINE_PHASES}


def _total_hist(status: str):
    return obs.histogram("serving_request_seconds", _PHASE_HELP,
                         phase="total", status=status)


_REQUESTS_HELP = "HTTP requests by endpoint and outcome"


def _requests_counter(endpoint: str, status: str):
    return obs.counter("serving_requests_total", _REQUESTS_HELP,
                       endpoint=endpoint, status=status)


class _HTTPError(Exception):
    def __init__(self, code: int, message: str):
        super().__init__(message)
        self.code = code


class _ContinuousBackend:
    """ContinuousBatcher's model adapter: the zero-copy slot path.

    Every method reads the server's (model, fingerprint) reference
    exactly once, so parse and predict each bind to one weights
    generation; `predict_rows` refuses (StaleParse) when the slot's
    parse-time fingerprint is no longer the live one — the batcher then
    re-parses via `predict_lines` under the current model, preserving
    one-fingerprint-per-batch across hot-swaps."""

    def __init__(self, server: "PredictionServer"):
        self._server = server

    def supports_rows(self) -> bool:
        """The CURRENT model exposes the zero-copy slot surface (the
        facade and ReleaseModel both do via BucketedPredictMixin; a
        swapped-in minimal model may not). Checked per submit, so
        slots formed after a swap to a lines-only model degrade to the
        predict_lines path instead of failing on a missing method."""
        model, _ = self._server._model_ref
        return (hasattr(model, "parse_lines_into")
                and hasattr(model, "alloc_predict_batch")
                and hasattr(model, "predict_parsed"))

    def alloc(self, rows: int):
        model, _ = self._server._model_ref
        return model.alloc_predict_batch(rows)

    def parse_into(self, lines, buffer, row_offset: int) -> str:
        model, fp = self._server._model_ref
        model.parse_lines_into(lines, buffer, row_offset)
        return fp

    def predict_rows(self, buffer, n_rows: int, fingerprint: str):
        server = self._server
        model, fp = server._model_ref
        if fp != fingerprint:
            raise StaleParse(
                f"slot rows were parsed under fingerprint "
                f"{fingerprint}; live model is {fp}")
        server.device_breaker.check()
        try:
            results = model.predict_parsed(
                buffer, n_rows,
                batch_size=server.config.serve_batch_size,
                with_code_vectors=True)
        except BaseException:
            server.device_breaker.record(ok=False)
            raise
        server.device_breaker.record(ok=True)
        return [(r, fp) for r in results]

    def predict_lines(self, lines):
        return self._server._batched_predict(lines)


class PredictionServer:
    """Owns the pool + batcher + cache + admission gate + breakers +
    swap manager around one (swappable) model.

    Separable from HTTP: `handle_request(endpoint, code, ...)` returns
    `(status, body, headers)`, so tests and the bench can drive the
    full path — including shedding and deadline accounting — in
    process, and the HTTP layer stays a thin framing shim.
    """

    def __init__(self, model, config=None, log=None,
                 swap_build_model=None, swap_mount_index=None):
        self.config = config or model.config
        self.log = log or self.config.log
        # The model reference is (model, fingerprint), swapped
        # atomically by swap_model(): the batcher reads it ONCE per
        # dispatched batch, so a response can never mix weights.
        self._model_lock = threading.Lock()
        self._model_ref: Tuple[object, str] = (model,
                                               model.model_fingerprint())
        self.pool = ExtractorPool(
            self.config, size=self.config.extractor_pool_size, log=self.log)
        # with_code_vectors=True: /predict and /embed rows coalesce into
        # the SAME batches (a per-endpoint batcher would halve fill);
        # the step computes vectors anyway, the flag only materializes
        # them host-side, and _render decides per endpoint what ships.
        # Tenancy policy (serving/tenancy.py): None when
        # --serve_tenants is unset — the whole tenant layer is then
        # inert and the serve path is bit-identical to a build without
        # it (pinned in tests/test_tenancy.py).
        self.tenancy = TenantPolicy.from_config(self.config)
        batcher_kw = dict(
            max_batch_rows=self.config.serve_batch_size,
            max_delay_s=self.config.serve_max_delay_ms / 1000.0,
            buckets=model.context_buckets,
            tenancy=self.tenancy)
        if getattr(self.config, "serve_continuous", False):
            # --serve_continuous: slot-reservation dispatcher + the
            # zero-copy parse-into-slot path (batcher.ContinuousBatcher)
            self.batcher = ContinuousBatcher(
                self._batched_predict,
                inflight_steps=getattr(self.config,
                                       "serve_inflight_steps", 2),
                backend=_ContinuousBackend(self), **batcher_kw)
        else:
            self.batcher = DynamicBatcher(self._batched_predict,
                                          **batcher_kw)
        self.cache = PredictionCache(self.config.serve_cache_entries)
        self.topk = self.config.top_k_words_considered_during_prediction
        # Live-traffic sample for the continuous-training pipeline's
        # shadow eval (serving/traffic.py): every Nth cache-miss
        # request's EXTRACTED lines into a bounded ring file that the
        # pipeline replays through incumbent and candidate
        # (--serve_traffic_sample; None = off).
        from code2vec_tpu.serving.traffic import sampler_for
        self.traffic = sampler_for(self.config, log=self.log)
        # Retrieval mount (serve --retrieval_index DIR): /neighbors
        # serves ANN code search from this index. Mounting validates the
        # index artifact AND that its recorded embedding fingerprint is
        # the live model's — a stale index is a startup error, loud.
        self.retrieval = None
        if getattr(self.config, "retrieval_index", None):
            from code2vec_tpu.retrieval.api import RetrievalHandle
            self.retrieval = RetrievalHandle.mount(
                self.config.retrieval_index, self._model_ref[1],
                default_topk=getattr(self.config, "retrieval_topk", 10),
                log=self.log)
        self.admission = AdmissionController(
            max_depth=self.config.serve_queue_depth,
            concurrency=self.config.extractor_pool_size,
            tenancy=self.tenancy)
        # Flight recorder (obs/flight.py): terminal request records +
        # anomaly events, dumped on incident (README "Telemetry"). Dump
        # dir defaults next to the heartbeat file so the supervisor's
        # run dir collects every replica's black boxes.
        self.flight = default_flight_recorder()
        flight_dir = getattr(self.config, "serve_flight_dir", None)
        if not flight_dir and self.config.heartbeat_file:
            flight_dir = os.path.dirname(
                os.path.abspath(self.config.heartbeat_file))
        self.flight.configure(
            dump_dir=flight_dir,
            capacity=getattr(self.config, "serve_flight_records", 512),
            max_dumps=getattr(self.config, "serve_flight_max_dumps",
                              64),
            log=self.log)
        breaker_kw = dict(
            window_s=self.config.serve_breaker_window_s,
            failure_ratio=self.config.serve_breaker_failure_ratio,
            min_requests=self.config.serve_breaker_min_requests,
            cooldown_s=self.config.serve_breaker_cooldown_s,
            on_transition=self._on_breaker_transition)
        self.extractor_breaker = CircuitBreaker("extractor", **breaker_kw)
        self.device_breaker = CircuitBreaker("device", **breaker_kw)
        # swap_build_model/swap_mount_index: injection seams mirroring
        # SwapManager's — the fleet chaos children swap between
        # in-process fake models (and mount scripted index handles for
        # the retrieval-refresh restart drills)
        self.swap = SwapManager(self, build_model=swap_build_model,
                                mount_index=swap_mount_index)
        self._httpd: Optional[socketserver.BaseServer] = None
        self._inflight = 0
        self._inflight_cond = threading.Condition()
        self._draining = False
        self._drained = threading.Event()
        self.abandoned_requests = 0
        self.started_at = time.time()
        self.port: Optional[int] = None

    # ------------------------------------------------------------ model

    @property
    def model(self):
        return self._model_ref[0]

    @property
    def model_fingerprint(self) -> str:
        """Fingerprint of the weights currently serving — mixed into
        every cache key and stamped on every response. Swappable."""
        return self._model_ref[1]

    def swap_model(self, new_model, retrieval_handle=None) -> str:
        """Atomically replace the serving model (called by the
        SwapManager AFTER validation). In-flight batches finish on the
        model reference they already read; the next dispatched batch —
        and the next cache key — uses the new one. `retrieval_handle`
        (an already-mounted, fingerprint-checked RetrievalHandle)
        remounts /neighbors atomically WITH the flip — the pipeline's
        retrieval-refresh stage delivers a rebuilt index this way."""
        fp = new_model.model_fingerprint()
        with self._model_lock:
            self._model_ref = (new_model, fp)
            # the deadline-feasibility math must run against the NEW
            # model's bucket grid (and fresh device-time samples — p95s
            # keyed to the old grid would misprice every refusal)
            self.batcher.rebucket(new_model.context_buckets)
            if retrieval_handle is not None:
                self.retrieval = retrieval_handle
                self.log(f"Retrieval index remounted atomically with "
                         f"the model swap (fingerprint "
                         f"{retrieval_handle.fingerprint})")
            # Embedding-space backstop, atomic with the flip: a mounted
            # index whose vectors came from different weights must never
            # answer /neighbors again (the SwapManager's `refuse` policy
            # normally rejects such a swap before it gets here; under
            # `detach` — or any future caller bypassing validation —
            # this is what keeps the invariant).
            if (self.retrieval is not None and self.retrieval.attached
                    and self.retrieval.fingerprint != fp):
                self.retrieval.detach(
                    f"model hot-swapped to fingerprint {fp}, index "
                    f"holds vectors from "
                    f"{self.retrieval.fingerprint}; rebuild the index "
                    f"(embed + index-build) against the new model")
                self.log("Retrieval index DETACHED on hot-swap: "
                         "embedding fingerprints diverged; /neighbors "
                         "now answers 503 (see /healthz retrieval)")
        return fp

    def _on_breaker_transition(self, name: str, to: str) -> None:
        """Breaker flips are flight-recorder anomalies; an OPEN is an
        incident (auto-dump when a dump dir is configured) — the black
        box captures both the failures that opened it and the shed storm
        that follows."""
        if to == "open":
            self.flight.incident("breaker_open", breaker=name)
        else:
            self.flight.event("breaker_transition", breaker=name, to=to)

    def _batched_predict(self, lines):
        """The batcher's predict_fn: ONE model-reference read per batch
        (swap atomicity), device circuit breaker around the call, and
        the computing model's fingerprint attached to every result so
        responses are attributable to exactly one set of weights."""
        self.device_breaker.check()
        model, fp = self._model_ref
        try:
            results = model.predict(
                lines, batch_size=self.config.serve_batch_size,
                with_code_vectors=True)
        except BaseException:
            self.device_breaker.record(ok=False)
            raise
        self.device_breaker.record(ok=True)
        return [(r, fp) for r in results]

    # ---------------------------------------------------------- predict

    def handle_request(self, endpoint: str, code: str,
                       deadline: Optional[Deadline] = None,
                       params: Optional[Dict] = None,
                       trace: Optional[RequestTrace] = None,
                       tenant: Optional[str] = None
                       ) -> Tuple[int, bytes, Dict[str, str]]:
        """Full serve path for one request -> (http_status, body,
        extra_headers). EVERY terminal status lands in
        serving_request_seconds{phase=total,status=...} and
        serving_requests_total — overload and errors are measured, not
        invisible. Every request carries a trace (inbound `traceparent`
        or minted here): the id rides the X-Trace-Id response header,
        the span tree lands in the ring tracer, and the terminal record
        goes into the flight recorder.

        `tenant` is the raw X-Tenant header value; with a tenancy
        policy it is collapsed onto the closed label set for
        scheduling and metrics, recorded verbatim in the trace and
        flight record. Without a policy it is ignored entirely."""
        t0 = time.perf_counter()
        if trace is None:
            trace = RequestTrace()
        tlabel: Optional[str] = None
        if self.tenancy is not None:
            tenant = self.tenancy.resolve(tenant)
            tlabel = self.tenancy.label(tenant)
        root = trace.span("request", endpoint=endpoint)
        root.__enter__()
        if tlabel is not None:
            root.attrs["tenant"] = tenant
        phases: Dict[str, float] = {}
        status, body, headers = 500, b"", {}
        reason: Optional[str] = None
        try:
            body = self._handle(endpoint, code, deadline, phases,
                                params=params, trace=trace,
                                tenant=tlabel)
            status = 200
        except Shed as e:
            if tlabel is None:
                e.count()
            else:
                tenant_metric(
                    "counter", "serving_requests_shed_total",
                    _SHED_HELP, tlabel, self.tenancy.labels,
                    reason=e.reason).inc()
            status = 503
            reason = e.reason
            # jittered: a synchronized shed (open breaker, drain) must
            # not teach every client the same retry instant
            headers["Retry-After"] = str(retry_after_seconds(
                e.retry_after_s))
            body = json.dumps({"error": str(e), "shed": e.reason,
                               "trace_id": trace.trace_id}
                              ).encode() + b"\n"
        except DeadlineExceeded as e:
            status = 504
            reason = "deadline_expired"
            self.flight.event("deadline_expired",
                              trace_id=trace.trace_id, endpoint=endpoint)
            body = json.dumps({"error": f"deadline exceeded: {e}",
                               "trace_id": trace.trace_id}
                              ).encode() + b"\n"
        except _HTTPError as e:
            status = e.code
            body = json.dumps({"error": str(e),
                               "trace_id": trace.trace_id}
                              ).encode() + b"\n"
        except FaultInjected as e:
            # chaos drills must surface as honest errors, never hangs
            status = 500
            body = json.dumps({"error": f"FaultInjected: {e}",
                               "trace_id": trace.trace_id}
                              ).encode() + b"\n"
        except Exception as e:  # noqa: BLE001 — 500, not a torn socket
            status = 500
            body = json.dumps({"error": f"{type(e).__name__}: {e}",
                               "trace_id": trace.trace_id}
                              ).encode() + b"\n"
        finally:
            total = time.perf_counter() - t0
            root.attrs["status"] = status
            root.__exit__(None, None, None)
            # snapshot: the batcher dispatcher can still write phase
            # keys for a request that exited early via the result
            # backstop — iterating the live dict could raise mid-walk
            phases = dict(list(phases.items()))
            for phase, dur in phases.items():
                _H_PHASE[phase].observe(dur)
            if tlabel is None:
                _total_hist(str(status)).observe(total)
                _requests_counter(endpoint, str(status)).inc()
            else:
                # tenancy on: the terminal-status families carry a
                # `tenant` label (bounded by the policy's closed set;
                # serving/tenancy.tenant_metric is the guard). The
                # per-phase histograms above stay tenant-free — phases
                # are a pipeline property, not a tenant one.
                tenant_metric(
                    "histogram", "serving_request_seconds",
                    _PHASE_HELP, tlabel, self.tenancy.labels,
                    phase="total", status=str(status)).observe(total)
                tenant_metric(
                    "counter", "serving_requests_total",
                    _REQUESTS_HELP, tlabel, self.tenancy.labels,
                    endpoint=endpoint, status=str(status)).inc()
            self.flight.record_request(
                trace_id=trace.trace_id, endpoint=endpoint,
                status=status, duration_s=total, phases=phases,
                reason=reason, fingerprint=self.model_fingerprint,
                **({} if tlabel is None else {"tenant": tenant}))
            headers.setdefault("X-Trace-Id", trace.trace_id)
            headers.setdefault("traceparent", trace.traceparent())
        return status, body, headers

    def _neighbor_knobs(self, params: Optional[Dict]) -> Dict:
        """Per-request retrieval knobs (JSON body `k`/`nprobe`),
        defaulted and clamped; part of the cache key — a different k or
        nprobe is a different answer."""
        params = params or {}
        try:
            k = int(params.get("k", self.retrieval.default_topk))
            nprobe = params.get("nprobe")
            nprobe = None if nprobe is None else int(nprobe)
        except (TypeError, ValueError):
            raise _HTTPError(400, "k and nprobe must be integers")
        if k < 1 or (nprobe is not None and nprobe < 1):
            raise _HTTPError(400, "k and nprobe must be >= 1")
        return {"k": k, "nprobe": nprobe}

    def _handle(self, endpoint: str, code: str,
                deadline: Optional[Deadline],
                phases: Dict[str, float],
                params: Optional[Dict] = None,
                trace: Optional[RequestTrace] = None,
                tenant: Optional[str] = None) -> bytes:
        if trace is None:
            trace = RequestTrace()
        if not code.strip():
            raise _HTTPError(400, "empty request body")
        knobs: Dict = {}
        if endpoint == "neighbors":
            if self.retrieval is None:
                raise _HTTPError(
                    404, "no retrieval index mounted; start the server "
                         "with serve --retrieval_index DIR")
            try:
                self.retrieval.require_attached()
            except Exception as e:
                raise _HTTPError(503, str(e))
            knobs = self._neighbor_knobs(params)
            knobs["index"] = self.retrieval.fingerprint
        model, fp = self._model_ref
        # ONE normalization pass per request: the same bytes feed the
        # cache probe here and the hot-swap re-key below.
        normalized = normalize_source(code)
        key = cache_key_normalized(normalized, endpoint=endpoint,
                                   topk=self.topk, model=fp, **knobs)
        with trace.span("cache_lookup") as sp:
            cached = self.cache.get(key)
            sp.attrs["hit"] = cached is not None
        if cached is not None:
            # Cache hits serve BEFORE admission and breakers: graceful
            # degradation — a dead extractor pool cannot take the hit
            # path down with it (pinned in tests/test_serving_chaos.py).
            return cached  # type: ignore[return-value]
        with trace.span("admission"):
            self.admission.admit(deadline, tenant=tenant)
        t_admit = time.perf_counter()
        worked = True
        try:
            lines, hash_to_string = self._extract(code, deadline, phases,
                                                  trace=trace)
            if self.traffic is not None:
                self.traffic.record(lines)
            future = self.batcher.submit(lines, phases=phases,
                                         deadline=deadline, trace=trace,
                                         tenant=tenant)
            try:
                if deadline is not None and deadline.bounded:
                    # Backstop: the batcher settles expired futures
                    # itself; this bounds a wedged device call so the
                    # CLIENT still gets its 504 near the deadline.
                    raw = future.result(
                        timeout=max(deadline.remaining(), 0) + 5.0)
                else:
                    raw = future.result()
            except _FutureTimeout:
                expired_counter("device").inc()
                raise DeadlineExceeded(
                    "request expired waiting on the device step")
            except RuntimeError as e:
                if "draining" in str(e):
                    raise Shed("draining", str(e))
                raise
            results = [r for r, _ in raw]
            result_fp = raw[0][1] if raw else fp
            with trace.span("render"):
                body = json.dumps(
                    self._render(endpoint, results, hash_to_string,
                                 result_fp, knobs=knobs, trace=trace),
                    sort_keys=True).encode() + b"\n"
            if result_fp != fp:
                # the model was hot-swapped between our cache probe and
                # the device batch: key the entry by the weights that
                # actually computed it, never the stale fingerprint
                key = cache_key_normalized(normalized,
                                           endpoint=endpoint,
                                           topk=self.topk,
                                           model=result_fp, **knobs)
            self.cache.put(key, body)
            return body
        except Shed:
            # a post-admission shed (batcher DeadlineInfeasible, an
            # open breaker, draining) refused the request instead of
            # working it: feeding its ~0ms turnaround into the
            # queue-wait EWMA would make the admission estimate wildly
            # optimistic under overload
            worked = False
            raise
        finally:
            self.admission.finish(
                (time.perf_counter() - t_admit) if worked else -1.0,
                tenant=tenant)

    def _extract(self, code: str, deadline: Optional[Deadline],
                 phases: Dict[str, float],
                 trace: Optional[RequestTrace] = None):
        """Extractor-pool call behind its circuit breaker, with the
        request's remaining deadline budget as the per-request
        timeout."""
        self.extractor_breaker.check()
        try:
            result = self.pool.extract_source(code, phases=phases,
                                              deadline=deadline,
                                              trace=trace)
        except DeadlineExceeded:
            # the request's budget, not the extractor's health: no
            # verdict recorded — but a half-open probe slot must be
            # re-armed or the breaker wedges in half_open forever
            self.extractor_breaker.abort()
            raise
        except FileNotFoundError as e:
            self.extractor_breaker.record(ok=False)
            raise _HTTPError(503, f"no extractor available: {e}")
        except (ExtractorCrash, OSError) as e:
            # infra failure (workers dying through every retry), NOT the
            # client's source: 503 tells a well-behaved client to retry.
            # Must precede the ValueError arm — ExtractorCrash subclasses
            # it so the REPL's catch-all keeps working.
            self.extractor_breaker.record(ok=False)
            raise _HTTPError(503, f"extractor unavailable: {e}")
        except ExtractionTimeout as e:
            # a hang is an infra failure for breaker purposes, but the
            # client's source MIGHT be the pathological input: 422
            self.extractor_breaker.record(ok=False)
            raise _HTTPError(422, f"extraction failed: {e}")
        except ValueError as e:
            # deterministic parse rejection: the extractor is HEALTHY
            # (it answered); a storm of bad client input must not open
            # the breaker and shed good clients.
            self.extractor_breaker.record(ok=True)
            raise _HTTPError(422, f"extraction failed: {e}")
        except Exception:
            # anything else (pool closed mid-drain, acquire timeout
            # with an unbounded deadline) carries no dependency
            # verdict — but a half-open probe slot must still re-arm
            # or the breaker wedges shedding forever
            self.extractor_breaker.abort()
            raise
        self.extractor_breaker.record(ok=True)
        return result

    def _render(self, endpoint: str, raw, hash_to_string,
                fingerprint: str, knobs: Optional[Dict] = None,
                trace: Optional[RequestTrace] = None) -> dict:
        if endpoint == "embed":
            # embedding_fingerprint is the embedding-SPACE identity —
            # the same field /neighbors stamps — so a client holding
            # vectors from two /embed calls (or an offline store) can
            # detect cross-model vector mixing before cosine math lies
            # to it.
            return {"model": "code2vec_tpu",
                    "model_fingerprint": fingerprint,
                    "embedding_fingerprint": fingerprint,
                    "vectors": [
                        ([] if r.code_vector is None
                         else [float(v) for v in r.code_vector])
                        for r in raw],
                    "method_names": [r.original_name for r in raw]}
        if endpoint == "neighbors":
            from code2vec_tpu.retrieval.api import EmbeddingSpaceMismatch
            knobs = knobs or {}
            k = knobs.get("k") or self.retrieval.default_topk
            nprobe = knobs.get("nprobe")
            if not raw:
                # zero extracted methods (an empty class, an interface):
                # an empty answer, not a search over a (0, ?) batch
                return {"model": "code2vec_tpu",
                        "model_fingerprint": fingerprint,
                        "embedding_fingerprint":
                            self.retrieval.index.fingerprint,
                        "index": {"rows": self.retrieval.index.rows,
                                  "backend": self.retrieval.index.backend,
                                  "metric": self.retrieval.index.metric,
                                  "k": k,
                                  "nprobe": (self.retrieval.index.nprobe
                                             if nprobe is None
                                             else nprobe)},
                        "methods": []}
            vectors = np.asarray(
                [r.code_vector for r in raw], dtype=np.float32)
            try:
                neighbor_lists = self.retrieval.neighbors(
                    vectors, fingerprint, k=k, nprobe=nprobe,
                    trace=trace)
            except EmbeddingSpaceMismatch as e:
                raise _HTTPError(503, str(e))
            return {
                "model": "code2vec_tpu",
                "model_fingerprint": fingerprint,
                "embedding_fingerprint":
                    self.retrieval.index.fingerprint,
                "index": {"rows": self.retrieval.index.rows,
                          "backend": self.retrieval.index.backend,
                          "metric": self.retrieval.index.metric,
                          "k": k,
                          "nprobe": (self.retrieval.index.nprobe
                                     if nprobe is None else nprobe)},
                "methods": [
                    {"original_name": r.original_name,
                     "neighbors": neighbors}
                    for r, neighbors in zip(raw, neighbor_lists)],
            }
        oov = self.model.vocabs.target_vocab.special_words.oov
        methods = []
        for r, parsed in zip(raw, parse_prediction_results(
                raw, hash_to_string, oov, topk=10)):
            entry = {
                "original_name": r.original_name,
                "predictions": [
                    {"name": p["name"], "probability": p["probability"]}
                    for p in parsed.predictions],
                "attention_paths": parsed.attention_paths,
            }
            # /predict ships vectors only when the model was created
            # with --export_code_vectors (/embed always does).
            if (self.config.export_code_vectors
                    and r.code_vector is not None):
                entry["code_vector"] = [float(v) for v in r.code_vector]
            methods.append(entry)
        return {"model": "code2vec_tpu",
                "model_fingerprint": fingerprint, "methods": methods}

    def handle(self, endpoint: str, code: str,
               deadline: Optional[Deadline] = None,
               params: Optional[Dict] = None) -> bytes:
        """Body-or-raise convenience used by in-process callers; HTTP
        goes through handle_request (which owns the SLO accounting)."""
        return self._handle(endpoint, code, deadline, {}, params=params)

    def handle_embed(self, code: str) -> bytes:
        return self.handle("embed", code)

    # ------------------------------------------------------------- http

    def healthz(self) -> dict:
        model = self.model
        return {
            "status": "draining" if self._draining else "serving",
            "uptime_s": time.time() - self.started_at,
            "pid": os.getpid(),
            "model": {
                "fingerprint": self.model_fingerprint,
                "swap_status": self.swap.status(),
            },
            # kept at top level too: deploy tooling from PR 8 reads it
            "model_fingerprint": self.model_fingerprint,
            "extractor_pool": {"size": self.pool.size,
                               "warm": self.pool.warm},
            "batcher": {"max_batch_rows": self.batcher.max_batch_rows,
                        "max_delay_ms":
                            self.batcher.max_delay_s * 1000.0,
                        "batches_dispatched":
                            self.batcher.batches_dispatched,
                        "continuous":
                            isinstance(self.batcher, ContinuousBatcher),
                        "inflight_rides":
                            getattr(self.batcher, "rides", 0)},
            "cache": {"capacity": self.cache.capacity,
                      "entries": len(self.cache)},
            "admission": {
                "depth": self.admission.depth,
                "max_depth": self.admission.max_depth,
                "estimated_wait_ms": (
                    None if (w := self.admission.estimated_wait_s())
                    is None else w * 1000.0),
            },
            "deadlines": {
                "default_ms": self.config.serve_deadline_ms,
                "max_ms": self.config.serve_deadline_max_ms,
            },
            "breakers": {"extractor": self.extractor_breaker.state,
                         "device": self.device_breaker.state},
            # weighted-fair tenancy (README "Multi-tenancy"); absent
            # key semantics preserved for tenancy-off deployments by
            # only adding it when a policy is configured
            **({} if self.tenancy is None
               else {"tenancy": self.tenancy.healthz()}),
            # request-scoped telemetry (README "Telemetry"): whether
            # ?debug=trace is honored, and the flight recorder's state
            "telemetry": {
                "debug_trace": bool(getattr(self.config,
                                            "serve_debug_trace", False)),
                "flight": {
                    "dump_dir": self.flight.dump_dir,
                    "requests_recorded": self.flight.requests_recorded,
                    "events_recorded": self.flight.events_recorded,
                },
            },
            # /neighbors data plane: attached/detached (+ the detach
            # reason — deploy tooling reads this after a hot-swap)
            "retrieval": (None if self.retrieval is None
                          else self.retrieval.status()),
            "buckets": list(model.context_buckets),
            # compiled shapes AT THE SERVE BATCH SIZE — the serving
            # compilation budget, bounded by len(buckets). (An offline
            # predict through the same facade at another batch size
            # adds its own bounded set; predict_compile_count() has the
            # overall number.) list() snapshots the dict atomically —
            # the batcher thread inserts newly compiled shapes
            # concurrently, and a generator over the live dict could
            # raise mid-iteration.
            "compiled_predict_steps": sum(
                1 for rows, _ in list(model._predict_steps)
                if rows == self.config.serve_batch_size),
            "compiled_predict_steps_all": (
                model.predict_compile_count()),
            "inflight": self._inflight,
        }

    def start(self, port: Optional[int] = None,
              host: Optional[str] = None) -> int:
        """Bind + serve on a daemon thread; returns the bound port
        (port 0 picks a free one). With C2V_SERVE_REUSEPORT=1 in the
        environment (set by the serving supervisor) the socket binds
        with SO_REUSEPORT so replica processes share the port."""
        server = self

        class Handler(http.server.BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *args):  # per-request stderr silenced
                pass

            def _respond(self, code: int, body: bytes,
                         ctype: str = "application/json",
                         extra_headers: Optional[Dict[str, str]] = None
                         ) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                for k, v in (extra_headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def _error(self, code: int, message: str,
                       extra_headers: Optional[Dict[str, str]] = None
                       ) -> None:
                self._respond(code, json.dumps(
                    {"error": message}).encode() + b"\n",
                    extra_headers=extra_headers)

            def do_GET(self):  # noqa: N802 (stdlib API name)
                path = self.path.split("?", 1)[0]
                try:
                    if path == "/healthz":
                        hz = server.healthz()
                        # the load-balancer eviction contract: a
                        # draining replica is NOT ready — probes must
                        # see 503 the moment SIGTERM lands, body still
                        # carrying the full introspection payload
                        code = 503 if hz["status"] == "draining" else 200
                        self._respond(code, json.dumps(
                            hz, sort_keys=True).encode() + b"\n")
                    elif path in ("/metrics", "/"):
                        self._respond(
                            200, obs.default_registry()
                            .render_prometheus().encode(),
                            ctype="text/plain; version=0.0.4; "
                                  "charset=utf-8")
                    else:
                        self._error(404, f"no such endpoint: {path}")
                except Exception as e:  # noqa: BLE001 — a probe must get
                    # an HTTP response, never a torn connection (a failed
                    # liveness probe can restart-loop the replica)
                    self._error(500, f"{type(e).__name__}: {e}")

            def do_POST(self):  # noqa: N802 (stdlib API name)
                path, _, query = self.path.partition("?")
                endpoint = path.lstrip("/")
                if path == "/admin/reload":
                    self._admin_reload()
                    return
                if path == "/admin/dump":
                    self._admin_dump()
                    return
                if endpoint not in ("predict", "embed", "neighbors"):
                    self._error(404, f"no such endpoint: {path}")
                    return
                # Inbound W3C traceparent joins the caller's distributed
                # trace; otherwise a trace id is minted. Either way the
                # id is echoed in X-Trace-Id + traceparent (even on the
                # shed/error paths below).
                trace = RequestTrace.from_headers(
                    self.headers.get("traceparent"))

                def trace_headers(**extra):
                    # built lazily: the fallback traceparent span id is
                    # only minted on the early-terminal paths that
                    # answer before handle_request opens the root span
                    return dict({"X-Trace-Id": trace.trace_id,
                                 "traceparent": trace.traceparent()},
                                **extra)

                deadline = deadline_from_request(
                    server.config, self.headers.get("X-Deadline-Ms"))
                # tenant identity is parsed ONCE here at the edge; the
                # fleet router / supervisor proxy forward the header
                # verbatim (forwarding.REQUEST_FORWARD_HEADERS)
                tenant = self.headers.get(TENANT_HEADER)
                if not server._enter_request():
                    if server.tenancy is None:
                        Shed("draining", "").count()
                        _requests_counter(endpoint, "draining").inc()
                    else:
                        tl = server.tenancy.label(tenant)
                        tenant_metric(
                            "counter", "serving_requests_shed_total",
                            _SHED_HELP, tl, server.tenancy.labels,
                            reason="draining").inc()
                        tenant_metric(
                            "counter", "serving_requests_total",
                            _REQUESTS_HELP, tl,
                            server.tenancy.labels, endpoint=endpoint,
                            status="draining").inc()
                    self._error(503, "server is draining",
                                extra_headers=trace_headers(
                                    **{"Retry-After": str(
                                        retry_after_seconds(1.0))}))
                    return
                try:
                    try:
                        length = int(self.headers.get(
                            "Content-Length", 0))
                        raw = self.rfile.read(length)
                        code_text, params = server._decode_body(
                            raw, self.headers)
                    except _HTTPError as e:
                        _requests_counter(endpoint, str(e.code)).inc()
                        self._error(e.code, str(e),
                                    extra_headers=trace_headers())
                        return
                    status, body, headers = server.handle_request(
                        endpoint, code_text, deadline, params=params,
                        trace=trace, tenant=tenant)
                    if ("debug=trace" in query.split("&")
                            and server.config.serve_debug_trace):
                        # post-cache injection: hits and misses both
                        # carry THIS request's tree, and the cached
                        # bytes stay trace-free/byte-stable
                        body = server._inject_trace(body, trace)
                    self._respond(status, body, extra_headers=headers)
                finally:
                    server._exit_request()

            def _admin_dump(self) -> None:
                """POST /admin/dump: write the flight-recorder rings to
                a timestamped JSON file now; body {"path": ...}."""
                try:
                    # drain the (ignored) request body: unread bytes
                    # would desync the next request on this HTTP/1.1
                    # keep-alive connection
                    length = int(self.headers.get("Content-Length", 0))
                    if length:
                        self.rfile.read(length)
                    path = server.flight.dump(reason="admin")
                    # counts from the file itself, so the response can
                    # never disagree with what was actually written
                    with open(path) as f:
                        written = json.load(f)
                except Exception as e:  # noqa: BLE001
                    self._error(500, f"{type(e).__name__}: {e}")
                else:
                    self._respond(200, json.dumps(
                        {"path": path,
                         "requests": len(written["requests"]),
                         "events": len(written["events"])},
                        sort_keys=True).encode() + b"\n")

            def _admin_reload(self) -> None:
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    payload = json.loads(
                        self.rfile.read(length).decode("utf-8",
                                                       errors="replace")
                        or "{}")
                    if not isinstance(payload, dict):
                        raise _HTTPError(
                            400, 'body must be {"artifact": DIR}')
                    target = payload.get("artifact")
                    status = server.swap.request_reload(
                        target,
                        retrieval_index=payload.get("retrieval_index"))
                except json.JSONDecodeError as e:
                    self._error(400, f"bad JSON body: {e}")
                except SwapError as e:
                    code = 409 if "in flight" in str(e) else 400
                    self._error(code, str(e))
                except _HTTPError as e:
                    self._error(e.code, str(e))
                except Exception as e:  # noqa: BLE001
                    self._error(500, f"{type(e).__name__}: {e}")
                else:
                    self._respond(202, json.dumps(
                        {"accepted": True, "swap_status": status},
                        sort_keys=True).encode() + b"\n")

        reuseport = os.environ.get(REUSEPORT_ENV) == "1"

        class _Listener(http.server.ThreadingHTTPServer):
            # the stdlib default accept backlog (5) refuses connections
            # at the KERNEL under a burst — overload must reach the
            # admission gate so it can shed honestly with a 503
            request_queue_size = 128

            def server_bind(self):
                if reuseport:
                    try:
                        self.socket.setsockopt(
                            socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
                    except (AttributeError, OSError) as e:
                        server.log(f"SO_REUSEPORT unavailable ({e}); "
                                   f"plain bind")
                http.server.ThreadingHTTPServer.server_bind(self)

        httpd = _Listener(
            (host if host is not None else self.config.serve_host,
             port if port is not None else self.config.serve_port),
            Handler)
        httpd.daemon_threads = True
        self._httpd = httpd
        self.port = httpd.server_address[1]
        threading.Thread(target=httpd.serve_forever,
                         name="serving-http", daemon=True).start()
        self.log(f"Prediction server listening on "
                 f"http://{httpd.server_address[0]}:{self.port} "
                 f"(POST /predict, POST /embed, POST /admin/reload, "
                 f"GET /healthz, GET /metrics"
                 f"{', SO_REUSEPORT' if reuseport else ''})")
        return self.port

    @staticmethod
    def _inject_trace(body: bytes, trace: RequestTrace) -> bytes:
        """`?debug=trace` (gated by --serve_debug_trace): append the
        request's span tree to the JSON response. Runs AFTER the cache
        layer, so cached bytes never embed a stale trace and the hit
        path stays byte-equal to the miss path for normal requests."""
        try:
            payload = json.loads(body)
        except ValueError:
            return body
        if not isinstance(payload, dict):
            return body
        payload["trace"] = trace.to_dict()
        return json.dumps(payload, sort_keys=True).encode() + b"\n"

    @staticmethod
    def _decode_body(raw: bytes, headers) -> Tuple[str, Optional[Dict]]:
        """(code, extra params). JSON bodies may carry per-request
        knobs beside "code" (today: /neighbors' `k` and `nprobe`);
        plain-text bodies have none."""
        text = raw.decode("utf-8", errors="replace")
        ctype = (headers.get("Content-Type") or "").split(";")[0].strip()
        if ctype == "application/json":
            try:
                payload = json.loads(text)
            except json.JSONDecodeError as e:
                raise _HTTPError(400, f"bad JSON body: {e}")
            if not isinstance(payload, dict) or "code" not in payload:
                raise _HTTPError(400, 'JSON body must be {"code": "..."}')
            params = {k: v for k, v in payload.items()
                      if k in ("k", "nprobe")}
            return str(payload["code"]), (params or None)
        return text, None

    def _enter_request(self) -> bool:
        with self._inflight_cond:
            if self._draining:
                return False
            self._inflight += 1
            return True

    def _exit_request(self) -> None:
        with self._inflight_cond:
            self._inflight -= 1
            self._inflight_cond.notify_all()

    # ------------------------------------------------------------ drain

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Graceful stop: refuse new requests, wait for in-flight ones
        (bounded), flush the batcher, tear down pool + listener.
        Idempotent; returns True when everything in flight finished
        inside the budget. On timeout, `abandoned_requests` records how
        many were left behind (surfaced in the final heartbeat)."""
        with self._inflight_cond:
            if self._draining:
                self._drained.wait(timeout)
                return self._inflight == 0
            self._draining = True
        budget = (timeout if timeout is not None
                  else self.config.serve_drain_timeout_s)
        self.log(f"Drain: refusing new requests, waiting up to "
                 f"{budget:g}s for {self._inflight} in-flight")
        self.flight.event("drain_start", inflight=self._inflight,
                          budget_s=budget)
        deadline = time.monotonic() + budget
        clean = True
        with self._inflight_cond:
            while self._inflight > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    clean = False
                    self.abandoned_requests = self._inflight
                    self.log(f"Drain timeout: {self._inflight} "
                             f"request(s) still in flight (abandoned)")
                    self.flight.incident(
                        "drain_timeout", immediate=True,
                        abandoned=self._inflight)
                    break
                self._inflight_cond.wait(timeout=remaining)
        self.batcher.drain(timeout=max(deadline - time.monotonic(), 1.0))
        if self.traffic is not None:
            self.traffic.flush()
        self.pool.close()
        if self._httpd is not None:
            try:
                self._httpd.shutdown()
                self._httpd.server_close()
            except Exception:
                pass  # teardown must never mask the drain result
        self._drained.set()
        self.log(f"Drain complete ({'clean' if clean else 'timed out'})")
        return clean


RELOAD_TARGET_FILENAME = "reload-target.json"


def reload_target_info(config) -> Optional[dict]:
    """The reload-target payload a SIGHUP should act on, when the
    supervisor dropped a reload-target file into the run dir (next to
    this replica's heartbeat file): {"artifact": DIR} plus an optional
    "retrieval_index" DIR to remount atomically with the swap (the
    pipeline's retrieval-refresh stage). None otherwise."""
    if not config.heartbeat_file:
        return None
    path = os.path.join(
        os.path.dirname(os.path.abspath(config.heartbeat_file)),
        RELOAD_TARGET_FILENAME)
    try:
        with open(path) as f:
            payload = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(payload, dict) or not payload.get("artifact"):
        return None
    return {"artifact": str(payload["artifact"]),
            "retrieval_index": (str(payload["retrieval_index"])
                                if payload.get("retrieval_index")
                                else None)}


def _heartbeat_fields(server: PredictionServer) -> dict:
    reg = obs.default_registry().collect()

    def total(name):
        fam = reg.get(name, {})
        return int(sum(child.value for child in fam.values()))

    swap_status = server.swap.status()
    return {
        "port": server.port,
        "inflight": server._inflight,
        "model_fingerprint": server.model_fingerprint,
        "swap_state": swap_status["state"],
        # which artifact the swap state refers to: the fleet swap
        # driver keys its convergence poll on this, so a replica still
        # showing LAST rollout's "ready" can never satisfy a new one
        "swap_target": swap_status["target"],
        # ...and which index rode along (None for a plain model swap):
        # a retrieval-refresh rollout re-targets the SAME artifact, so
        # the driver needs this to tell the new rollout's "ready" from
        # the promote rollout's
        "swap_retrieval_index": swap_status.get("retrieval_index"),
        "breakers": {"extractor": server.extractor_breaker.state,
                     "device": server.device_breaker.state},
        "requests_total": total("serving_requests_total"),
        "requests_shed_total": total("serving_requests_shed_total"),
        "requests_expired_total": total("serving_requests_expired_total"),
        # span-ring pressure: lets /fleet show which replica's trace
        # export is truncated when a stitched trace is missing spans
        "spans_dropped": obs.default_tracer().dropped,
        "span_ring_high_water": obs.default_tracer().high_water,
    }


def serve_main(config, model=None, *, stop: Optional[threading.Event]
               = None, install_signals: Optional[bool] = None,
               swap_build_model=None, swap_mount_index=None) -> int:
    """The `serve` CLI subcommand body: build the model, start the
    server, park until SIGTERM/SIGINT (or the injected `stop` event —
    the testable form), drain, exit. Returns the process exit code.

    While parked, a heartbeat ticker rewrites --heartbeat_file every
    config.serve_heartbeat_interval_s (the supervisor's staleness
    signal — a replica whose heartbeat stops is HUNG and gets
    restarted; fault point `replica_heartbeat` in utils/faults.py
    simulates exactly that). SIGHUP triggers a live hot-swap re-reading
    --artifact."""
    from code2vec_tpu.utils.faults import fault_point

    if model is None:
        from code2vec_tpu.model_facade import Code2VecModel
        model = Code2VecModel(config)
    server = PredictionServer(model, config,
                              swap_build_model=swap_build_model,
                              swap_mount_index=swap_mount_index)
    if stop is None:
        stop = threading.Event()
    if install_signals is None:
        install_signals = (threading.current_thread()
                           is threading.main_thread())

    def _on_signal(signum, frame):
        config.log(f"Signal {signal.Signals(signum).name} received: "
                   f"draining")
        stop.set()

    def _on_hup(signum, frame):
        # Reload target: a `reload-target.json` next to the heartbeat
        # file (written by the supervisor's fleet-wide reload fan-out —
        # under SO_REUSEPORT a POST /admin/reload reaches one
        # kernel-chosen replica, so the file + SIGHUP is how EVERY
        # replica learns a NEW artifact dir) wins over the boot-time
        # --artifact.
        info = reload_target_info(config)
        target = (info["artifact"] if info else None) \
            or config.serve_artifact
        if target:
            config.log(f"SIGHUP: reloading artifact {target}")
            try:
                server.swap.request_reload(
                    target,
                    retrieval_index=(info or {}).get("retrieval_index"))
            except SwapError as e:
                config.log(f"SIGHUP reload rejected: {e}")
        else:
            config.log("SIGHUP ignored: no --artifact or reload-target "
                       "file to reload (use POST /admin/reload)")

    prev_term = prev_int = prev_hup = None
    if install_signals:
        prev_term = signal.signal(signal.SIGTERM, _on_signal)
        prev_int = signal.signal(signal.SIGINT, _on_signal)
        if hasattr(signal, "SIGHUP"):
            prev_hup = signal.signal(signal.SIGHUP, _on_hup)
    if config.trace_export:
        # bulk per-request span trees ride the same ring the trainer
        # uses; exported as one Chrome trace every heartbeat tick (so
        # live `fleet trace` stitching and a crash both see recent
        # spans) and finally at shutdown
        obs.default_tracer().enable()
    server.start()

    hb_stop = threading.Event()

    def _publish():
        if config.heartbeat_file:
            obs.exporters.write_heartbeat(
                config.heartbeat_file,
                status="draining" if server._draining else "serving",
                **_heartbeat_fields(server))
        if config.metrics_file:
            # the replica's fleet-telemetry feed: an atomic snapshot the
            # supervisor merges into its /metrics and /fleet views
            # (serving/telemetry.py) — rewritten every ticker interval,
            # not just at exit
            obs.exporters.write_prometheus(config.metrics_file)
        if config.trace_export and len(obs.default_tracer()):
            try:
                obs.default_tracer().export_chrome_trace(
                    config.trace_export)
            except OSError:
                pass  # next tick retries; shutdown still exports

    def _heartbeat_loop():
        while not hb_stop.wait(config.serve_heartbeat_interval_s):
            # An armed fault here kills the ticker (raise) or the whole
            # replica (exit) — the supervisor's stale-heartbeat /
            # crash detection drills.
            fault_point("replica_heartbeat")
            _publish()

    if config.heartbeat_file or config.metrics_file:
        _publish()
        threading.Thread(target=_heartbeat_loop, name="serving-heartbeat",
                         daemon=True).start()
    try:
        stop.wait()
    finally:
        clean = server.drain()
        hb_stop.set()
        if install_signals:
            signal.signal(signal.SIGTERM, prev_term)
            signal.signal(signal.SIGINT, prev_int)
            if prev_hup is not None:
                signal.signal(signal.SIGHUP, prev_hup)
        if config.metrics_file:
            obs.exporters.write_prometheus(config.metrics_file)
        if config.trace_export:
            obs.default_tracer().export_chrome_trace(config.trace_export)
            config.log(f"Serving span trace written to "
                       f"{config.trace_export}")
        if config.heartbeat_file:
            obs.exporters.write_heartbeat(
                config.heartbeat_file,
                status="done" if clean else "error",
                abandoned_requests=server.abandoned_requests,
                **_heartbeat_fields(server))
    return 0 if clean else 1
