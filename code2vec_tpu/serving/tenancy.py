"""Tenant-fair serving: identity, weighted shares, and rate quotas.

The admission gate from PR 9 is honest under overload but
tenant-blind: one hot tenant's burst fills the whole in-flight budget
and every other tenant starves behind it. This module gives the
serving tier a tenant dimension without changing anything for
deployments that don't opt in:

- **Identity**: the `X-Tenant` request header names the tenant;
  absent/blank means the `"default"` tenant. With `--serve_tenants`
  unset there is NO policy object and the whole layer is inert —
  responses are byte-identical to a tenancy-free build (pinned in
  tests/test_tenancy.py).
- **Shares** (`--serve_tenants name=weight,...`): each configured
  tenant owns `weight / sum(active weights)` of the admission gate's
  in-flight budget (`--serve_queue_depth`). The bound is computed
  against *recently active* tenants only, so a lone tenant still uses
  the full queue (work conservation) while contending tenants converge
  to their weighted shares. Tenants not named in the spec collapse
  into one `"other"` bucket at `--serve_tenant_default_weight`.
- **Rate quotas** (`--serve_tenant_qps`): a deterministic token bucket
  per tenant; an over-quota request sheds as 503
  `shed_reason=tenant_quota` with `Retry-After` derived from THAT
  tenant's bucket refill time — never the fleet-wide queue estimate.
- **Batch fairness**: `dwrr_take` is the deficit-weighted-round-robin
  order the classic batcher uses to fill a device batch when multiple
  tenants are pending, so a filled slot cannot be monopolized by one
  tenant's backlog.
- **Bounded metric cardinality**: every tenant-labeled metric
  registration funnels through `tenant_metric`, which refuses any
  label value outside the policy's closed set (configured tenants +
  `default` + `other`). The registration names here are mirrored in
  scripts/check_metrics_doc.py's `_DYNAMIC_REGISTRATIONS` allowlist —
  labels are the dynamic dimension, the name set stays closed.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from code2vec_tpu import obs

# The request header naming the tenant, parsed once at the server edge
# and forwarded verbatim by the fleet router and the supervisor proxy
# (serving/forwarding.py REQUEST_FORWARD_HEADERS).
TENANT_HEADER = "X-Tenant"
# Absent/blank header ⇒ this tenant. Always part of the label set.
DEFAULT_TENANT = "default"
# Metric label (and scheduling bucket) every UNCONFIGURED tenant
# collapses into: the label set stays closed no matter what clients
# send, so a header fuzzer cannot grow the registry.
OTHER_LABEL = "other"

# How long (seconds) a tenant stays in the "active" set after its last
# admission attempt. Share bounds divide the queue among active tenants
# only: a tenant idle longer than this stops reserving queue room
# (work conservation), while any tenant probing at >= 1/window Hz keeps
# its share reserved against a hot tenant's flood.
ACTIVE_WINDOW_S = 10.0


def parse_tenant_weights(spec) -> Dict[str, float]:
    """Parse `--serve_tenants` ("name=weight,name=weight,..."; a bare
    name means weight 1) into an ordered {name: weight} map. Raises
    ValueError on empty names, non-positive or unparsable weights, and
    duplicates — a typo'd share spec must fail at startup, not skew
    production fairness silently."""
    out: Dict[str, float] = {}
    for part in str(spec or "").replace(" ", "").split(","):
        if not part:
            continue
        name, sep, raw = part.partition("=")
        if not name:
            raise ValueError(
                f"--serve_tenants entry {part!r} has an empty tenant "
                f"name")
        if name in out:
            raise ValueError(
                f"--serve_tenants names tenant {name!r} twice")
        if sep:
            try:
                weight = float(raw)
            except ValueError:
                raise ValueError(
                    f"--serve_tenants weight for {name!r} must be a "
                    f"number, got {raw!r}")
        else:
            weight = 1.0
        if weight <= 0:
            raise ValueError(
                f"--serve_tenants weight for {name!r} must be > 0 "
                f"(got {weight:g}); use 0 qps, not 0 weight, to block "
                f"a tenant")
        out[name] = weight
    return out


def parse_tenant_qps(spec) -> Dict[str, float]:
    """Parse `--serve_tenant_qps`: either one bare number (the same
    quota for every tenant, `*` internally) or "name=qps,..." per
    tenant. 0 or unset = uncapped. Raises ValueError on negative or
    unparsable rates."""
    text = str(spec or "").replace(" ", "")
    if not text:
        return {}
    out: Dict[str, float] = {}
    for part in text.split(","):
        if not part:
            continue
        name, sep, raw = part.partition("=")
        if not sep:
            name, raw = "*", part
        if not name:
            raise ValueError(
                f"--serve_tenant_qps entry {part!r} has an empty "
                f"tenant name")
        if name in out:
            raise ValueError(
                f"--serve_tenant_qps names tenant {name!r} twice")
        try:
            qps = float(raw)
        except ValueError:
            raise ValueError(
                f"--serve_tenant_qps rate for {name!r} must be a "
                f"number, got {raw!r}")
        if qps < 0:
            raise ValueError(
                f"--serve_tenant_qps rate for {name!r} must be >= 0 "
                f"(0 = uncapped), got {qps:g}")
        out[name] = qps
    return out


class TokenBucket:
    """Deterministic token bucket: `rate_qps` tokens/s up to `burst`.
    The clock is injectable so refill behavior is testable to the
    token — the fairness-law tests advance a fake clock and assert
    exact admit/refuse sequences."""

    def __init__(self, rate_qps: float, burst: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.rate = float(rate_qps)
        # default burst: one second's worth of quota, at least 1 token
        self.burst = float(burst) if burst is not None \
            else max(1.0, self.rate)
        self._clock = clock
        self._tokens = self.burst
        self._t_last = clock()
        self._lock = threading.Lock()

    def _refill_locked(self) -> None:
        now = self._clock()
        if now > self._t_last:
            self._tokens = min(
                self.burst,
                self._tokens + (now - self._t_last) * self.rate)
        self._t_last = now

    def try_take(self) -> bool:
        with self._lock:
            self._refill_locked()
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                return True
            return False

    def retry_after_s(self) -> float:
        """Seconds until this bucket holds a whole token again — the
        per-tenant Retry-After base for a tenant_quota shed (the
        server adds jitter on top, as for every shed)."""
        with self._lock:
            self._refill_locked()
            if self._tokens >= 1.0:
                return 0.0
            if self.rate <= 0:
                # a zero-rate bucket never refills: the tenant is
                # administratively blocked; tell it to back off hard
                return 60.0
            return (1.0 - self._tokens) / self.rate


class TenantPolicy:
    """Parsed tenancy configuration: weighted shares, per-tenant rate
    quotas, and the CLOSED metric-label set. One instance per server,
    shared by the admission controller and the batcher. `None` (no
    `--serve_tenants`) means the layer is off end to end."""

    def __init__(self, weights: Dict[str, float],
                 default_weight: float = 1.0,
                 qps: Optional[Dict[str, float]] = None,
                 clock: Callable[[], float] = time.monotonic,
                 active_window_s: float = ACTIVE_WINDOW_S):
        if not weights:
            raise ValueError("TenantPolicy needs at least one "
                             "configured tenant (use None for no "
                             "tenancy)")
        self.weights = dict(weights)
        self.default_weight = float(default_weight)
        if self.default_weight <= 0:
            raise ValueError("--serve_tenant_default_weight must be "
                             "> 0")
        self.qps = dict(qps or {})
        self.clock = clock
        self.active_window_s = float(active_window_s)
        # The closed label set: configured tenants + the default tenant
        # + the collapse bucket. This IS the cardinality bound — every
        # tenant-labeled registration is checked against it.
        self.labels: Tuple[str, ...] = tuple(dict.fromkeys(
            list(self.weights) + [DEFAULT_TENANT, OTHER_LABEL]))
        self._buckets: Dict[str, Optional[TokenBucket]] = {}
        self._bucket_lock = threading.Lock()

    @classmethod
    def from_config(cls, config,
                    clock: Callable[[], float] = time.monotonic
                    ) -> Optional["TenantPolicy"]:
        """Policy from --serve_tenants / --serve_tenant_default_weight
        / --serve_tenant_qps; None when --serve_tenants is unset (the
        zero-behavior-change contract)."""
        weights = parse_tenant_weights(
            getattr(config, "serve_tenants", ""))
        if not weights:
            return None
        return cls(
            weights,
            default_weight=float(getattr(
                config, "serve_tenant_default_weight", 1.0)),
            qps=parse_tenant_qps(
                getattr(config, "serve_tenant_qps", "")),
            clock=clock)

    # ---------------------------------------------------- identity

    @staticmethod
    def resolve(header_value: Optional[str]) -> str:
        """Raw tenant id from the X-Tenant header: stripped, blank or
        absent ⇒ the default tenant. This is the value recorded in
        trace attrs and flight-recorder entries (bounded rings, full
        fidelity); scheduling and metrics use `label()`."""
        tenant = (header_value or "").strip()
        return tenant or DEFAULT_TENANT

    def label(self, tenant: Optional[str]) -> str:
        """Collapse a raw tenant id onto the closed label set: a
        configured tenant keeps its name, `default` stays `default`,
        everything else becomes `other`."""
        tenant = self.resolve(tenant)
        if tenant in self.weights or tenant == DEFAULT_TENANT:
            return tenant
        return OTHER_LABEL

    def weight(self, label: Optional[str]) -> float:
        """Fair-share weight of a (collapsed) label; unconfigured
        labels (`default`, `other`) ride at the default weight."""
        if label is None:
            return self.default_weight
        return self.weights.get(label, self.default_weight)

    def bucket(self, label: str) -> Optional[TokenBucket]:
        """The label's rate-quota bucket; None = uncapped. Buckets are
        created once per label and shared across requests — `other` is
        ONE bucket for all unconfigured tenants together, matching its
        one metric label and one scheduling share."""
        with self._bucket_lock:
            if label not in self._buckets:
                qps = self.qps.get(label, self.qps.get("*", 0.0))
                self._buckets[label] = (
                    TokenBucket(qps, clock=self.clock) if qps > 0
                    else None)
            return self._buckets[label]

    def healthz(self) -> dict:
        return {
            "tenants": {name: {"weight": w,
                               "qps": self.qps.get(
                                   name, self.qps.get("*", 0.0))}
                        for name, w in self.weights.items()},
            "default_weight": self.default_weight,
            "labels": list(self.labels),
        }


# ------------------------------------------------------------ metrics

# The ONLY metric families that may carry a tenant label, mirrored in
# scripts/check_metrics_doc.py _DYNAMIC_REGISTRATIONS (the doc gate
# fails if this module registers a name outside that closed allowlist).
# Help strings match the literal registrations in server.py/admission.py
# so the registry's idempotent _get() sees one family either way.
_TENANT_METRICS = ("serving_requests_total",
                   "serving_requests_shed_total",
                   "serving_request_seconds")


def tenant_metric(kind: str, name: str, help_text: str, tenant: str,
                  allowed: Sequence[str], **labels):
    """The guarded funnel for every tenant-labeled registration:
    refuses a metric name outside the closed `_TENANT_METRICS` set and
    a tenant label value outside the policy's closed label set, so the
    registry can never grow unbounded tenant cardinality — a client
    fuzzing X-Tenant values hits `TenantPolicy.label()`'s collapse
    first and this assertion second."""
    if name not in _TENANT_METRICS:
        raise ValueError(
            f"{name!r} is not a tenant-labeled metric family "
            f"(allowed: {', '.join(_TENANT_METRICS)})")
    if tenant not in allowed:
        raise ValueError(
            f"tenant label {tenant!r} is outside the configured label "
            f"set {tuple(allowed)!r}; collapse it with "
            f"TenantPolicy.label() first (bounded-cardinality guard)")
    if kind == "counter":
        return obs.counter(name, help_text, tenant=tenant, **labels)
    if kind == "histogram":
        return obs.histogram(name, help_text, tenant=tenant, **labels)
    raise ValueError(f"unknown tenant metric kind {kind!r}")


# --------------------------------------------------------------- DWRR

def dwrr_take(pending, max_rows: int,
              weight_of: Callable[[Optional[str]], float],
              state: dict) -> Optional[List[int]]:
    """Deficit-weighted-round-robin batch fill: pick indices into
    `pending` (objects with `.tenant` and `.lines`) totalling at most
    `max_rows` rows, interleaving tenants by weighted deficit, FIFO
    within a tenant. Returns None when at most one tenant is pending —
    the caller keeps its plain FIFO path, byte-identical to the
    tenancy-free batcher for a single tenant.

    `state` persists across calls: {"deficits": {label: rows},
    "last": label} — a tenant's unused credit carries to the next
    batch, its deficit resets when its queue empties (classic DRR),
    and rotation resumes after the last-served tenant so the
    first-listed tenant holds no permanent head-of-batch advantage."""
    queues: Dict[Optional[str], List[int]] = {}
    for i, item in enumerate(pending):
        queues.setdefault(item.tenant, []).append(i)
    if len(queues) <= 1:
        return None
    deficits = state.setdefault("deficits", {})
    labels = sorted(queues, key=lambda t: (t is None, t))
    last = state.get("last")
    if last in labels:
        k = labels.index(last) + 1
        labels = labels[k:] + labels[:k]
    total_w = sum(weight_of(t) for t in labels) or 1.0
    taken: List[int] = []
    rows = 0
    while rows < max_rows:
        # can any nonempty queue's head still fit the batch?
        if taken and not any(
                q and rows + len(pending[q[0]].lines) <= max_rows
                for q in queues.values()):
            break
        progressed = False
        for t in labels:
            q = queues[t]
            if not q:
                deficits.pop(t, None)
                continue
            # quantum: this tenant's weighted slice of one full batch
            deficits[t] = deficits.get(t, 0.0) \
                + max_rows * weight_of(t) / total_w
            while q and rows < max_rows:
                n = len(pending[q[0]].lines)
                if taken and rows + n > max_rows:
                    break
                if deficits[t] < n and taken:
                    break
                taken.append(q.pop(0))
                deficits[t] -= n
                rows += n
                state["last"] = t
                progressed = True
            if not q:
                deficits.pop(t, None)
            if rows >= max_rows:
                break
        if not progressed:
            break
    return taken
