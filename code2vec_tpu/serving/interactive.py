"""Interactive prediction REPL (reference: interactive_predict.py:28-57).

Reads `Input.java`, extracts path-contexts, predicts names, prints top-k
predictions with per-context attention (paths un-hashed via the
extractor's hash->string map) and optionally the code vector.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

from code2vec_tpu.common import get_subtokens
from code2vec_tpu.serving.extractor_pool import ExtractorPool

SHOW_TOP_CONTEXTS = 10
MAX_PATH_LENGTH = 8
MAX_PATH_WIDTH = 2


class MethodPredictionResults:
    # reference: common.py:204-217
    def __init__(self, original_name: str):
        self.original_name = original_name
        self.predictions: List[dict] = []
        self.attention_paths: List[dict] = []

    def append_prediction(self, name, probability):
        self.predictions.append({"name": name, "probability": probability})

    def append_attention_path(self, attention_score, token1, path, token2):
        self.attention_paths.append({"score": attention_score, "path": path,
                                     "token1": token1, "token2": token2})


def parse_prediction_results(raw_prediction_results, hash_to_string: Dict[str, str],
                             oov_word: str, topk: int = SHOW_TOP_CONTEXTS
                             ) -> List[MethodPredictionResults]:
    # reference: common.py:135-158
    out = []
    for raw in raw_prediction_results:
        res = MethodPredictionResults(raw.original_name)
        for i, predicted in enumerate(raw.topk_predicted_words):
            if predicted == oov_word:
                continue
            res.append_prediction(
                get_subtokens(predicted),
                float(raw.topk_predicted_words_scores[i]))
        sorted_contexts = sorted(raw.attention_per_context.items(),
                                 key=lambda kv: kv[1], reverse=True)[:topk]
        for (token1, hashed_path, token2), weight in sorted_contexts:
            if hashed_path in hash_to_string:
                res.append_attention_path(
                    float(weight), token1=token1,
                    path=hash_to_string[hashed_path], token2=token2)
        out.append(res)
    return out


class InteractivePredictor:
    exit_keywords = ["exit", "quit", "q"]

    def __init__(self, config, model):
        self.model = model
        self.config = config
        # ONE warm extractor held for the whole session (the serving
        # pool, size 1) instead of a fresh subprocess per snippet:
        # re-predicting after an edit costs a parse, not a process
        # spawn. Prediction rides the same bucketed compiled-step cache
        # the HTTP server uses (model_facade.predict).
        self.extractor_pool = ExtractorPool(
            config, size=1, max_path_length=MAX_PATH_LENGTH,
            max_path_width=MAX_PATH_WIDTH)

    def close(self):
        self.extractor_pool.close()

    def predict(self, input_filename: str = "Input.java"):
        print("Starting interactive prediction...")
        oov = self.model.vocabs.target_vocab.special_words.oov
        try:
            while True:
                print(f'Modify the file: "{input_filename}" and press any '
                      'key when ready, or "q" / "quit" / "exit" to exit')
                user_input = input()
                if user_input.lower() in self.exit_keywords:
                    print("Exiting...")
                    return
                try:
                    predict_lines, hash_to_string = \
                        self.extractor_pool.extract_file(input_filename)
                except (ValueError, FileNotFoundError) as e:
                    print(e)
                    continue
                raw_results = self.model.predict(predict_lines)
                method_results = parse_prediction_results(
                    raw_results, hash_to_string, oov,
                    topk=SHOW_TOP_CONTEXTS)
                for raw, method in zip(raw_results, method_results):
                    print("Original name:\t" + method.original_name)
                    for pair in method.predictions:
                        print("\t(%f) predicted: %s" % (pair["probability"],
                                                        pair["name"]))
                    print("Attention:")
                    for att in method.attention_paths:
                        print("%f\tcontext: %s,%s,%s" % (
                            att["score"], att["token1"], att["path"],
                            att["token2"]))
                    if (self.config.export_code_vectors
                            and raw.code_vector is not None):
                        print("Code vector:")
                        print(" ".join(map(str, raw.code_vector)))
        finally:
            self.close()
