"""Warm extractor worker pool: persistent path-extractor processes.

The one-shot bridge (extractor_bridge.PathExtractor) pays a full process
spawn + runtime init per extraction — fine for a REPL, fatal for a
server (BENCH_EVAL.json: the device side sustains 41.3K examples/s; a
subprocess fork per request caps the whole service at tens of requests
per second). This pool keeps N extractor children RESIDENT:

- **warm mode**: the native `c2v-extract --server` worker loop (built in
  cpp/; probed once at pool startup). Requests are line-framed over the
  child's stdin (`FILE <path>` / `SRC <nbytes>` + payload), responses
  framed on stdout (`OK <nlines>` + lines, or `ERR <msg>`). Extraction
  cost is the parse alone.
- **cold mode** (fallback when the binary predates `--server`, or only
  the reference jar is available): each worker slot degrades to the
  one-shot PathExtractor per request. Same API, same concurrency bound,
  no warm amortization.

Failure semantics reuse the bridge's vocabulary and bound
(`config.extractor_retries`):

- A worker that DIES mid-request (OOM kill, signal) has its request
  REQUEUED onto a fresh worker, up to the retry bound; the dead worker
  is replaced so pool capacity never decays. Each failed attempt counts
  `extractor_failures_total` exactly once (retried=yes when another
  attempt follows, =no when the failure surfaces to the caller) — the
  pool does its own accounting and the cold-mode PathExtractor is run
  with retries=0 so the two layers never double-count.
- An `ERR`-framed response is a deterministic rejection (parse failure):
  raised as ValueError immediately, never retried — identical on every
  retry, like the bridge's plain-nonzero-exit policy.
- A request exceeding `config.extractor_timeout_s` kills THAT worker
  (its stdout can no longer be trusted mid-frame), raises
  ExtractionTimeout, and is not retried — bridge policy.
"""

from __future__ import annotations

import os
import subprocess
import tempfile
import threading
import time
from typing import Dict, List, Optional, Tuple

from code2vec_tpu import obs
from code2vec_tpu.serving import extractor_bridge as bridge
from code2vec_tpu.serving.extractor_bridge import (
    DEFAULT_JAR_PATH, ExtractionTimeout, ExtractorCrash, PathExtractor,
    postprocess_extractor_output,
)

_H_EXTRACT = obs.histogram(
    "extractor_pool_extract_seconds",
    "warm-pool path extraction: request handed to a worker to parsed "
    "contexts (excludes the wait for a free worker)")
_H_WAIT = obs.histogram(
    "extractor_pool_wait_seconds",
    "wait for a free extractor worker slot (serving queue pressure)")
_C_REQS = obs.counter("extractor_pool_requests_total",
                      "extractions served by the warm pool")
_C_REQUEUES = obs.counter(
    "extractor_pool_requeues_total",
    "requests re-run on a fresh worker after their worker died "
    "mid-request")
_G_SIZE = obs.gauge("extractor_pool_size", "live extractor workers")


class _Worker:
    """One extractor child. Warm: a resident `--server` process. Cold: a
    per-request PathExtractor (retries=0 — the POOL owns retry
    accounting)."""

    def __init__(self, config, warm_command: Optional[List[str]],
                 max_path_length: int, max_path_width: int,
                 timeout: Optional[float], jar_path: str):
        self.config = config
        self.warm_command = warm_command
        self.timeout = timeout
        self.proc: Optional[subprocess.Popen] = None
        self.dead = False
        self.timed_out = False
        if warm_command is None:
            # retries=0 AND raw single-attempt calls below: the POOL owns
            # retry/failure accounting in both modes, so the bridge's own
            # counting layer is bypassed (no double-counted
            # extractor_failures_total).
            self.cold = PathExtractor(config, jar_path=jar_path,
                                      max_path_length=max_path_length,
                                      max_path_width=max_path_width,
                                      timeout=timeout or 0, retries=0)
        else:
            self.cold = None
            self._spawn()

    # ------------------------------------------------------------- warm

    def _spawn(self) -> None:
        assert self.warm_command is not None
        self.proc = subprocess.Popen(
            self.warm_command, stdin=subprocess.PIPE,
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL)
        timer = threading.Timer(30.0, self._watchdog_kill)
        timer.start()
        try:
            ready = self._readline()
        finally:
            timer.cancel()
        if ready.strip() != "READY":
            self.kill()
            raise ExtractorCrash(
                f"warm extractor worker failed its READY handshake "
                f"(got {ready!r})")

    def _readline(self) -> str:
        """Blocking readline; a hung child is handled by the ONE
        per-request watchdog timer in `_request` (a kill makes this
        return EOF instead of hanging the serving thread forever)."""
        assert self.proc is not None and self.proc.stdout is not None
        return self.proc.stdout.readline().decode(errors="replace")

    def _watchdog_kill(self) -> None:
        self.timed_out = True
        self.kill()

    def _request(self, header: bytes, payload: bytes = b"",
                 timeout_s: Optional[float] = None) -> List[str]:
        """One framed request/response exchange, guarded by a SINGLE
        watchdog Timer covering the whole exchange, cancelled on the
        fast path. (A timer per readline would create a fresh Timer
        thread per response line — thousands of short-lived threads per
        second under sustained load; thread-count stability is pinned
        in tests/test_serving.py.) `timeout_s` overrides the pool-wide
        timeout when the caller's remaining deadline budget is tighter."""
        assert self.proc is not None and self.proc.stdin is not None
        self.timed_out = False
        timeout = self.timeout if timeout_s is None else timeout_s
        timer = None
        if timeout is not None:
            timer = threading.Timer(max(timeout, 0.001),
                                    self._watchdog_kill)
            timer.start()
        try:
            try:
                self.proc.stdin.write(header + payload)
                self.proc.stdin.flush()
            except (BrokenPipeError, OSError) as e:
                raise ExtractorCrash(
                    f"warm extractor worker died before the request could "
                    f"be written: {e}") from e
            status = self._readline()
            if self.timed_out:
                obs.counter(
                    "extractor_timeouts_total",
                    "extractor children killed after "
                    "config.extractor_timeout_s").inc()
                raise ExtractionTimeout(
                    f"warm extraction exceeded {timeout:g}s; worker "
                    f"killed")
            if not status:
                rc = self.proc.poll()
                raise ExtractorCrash(
                    f"warm extractor worker died mid-request "
                    f"(exit code {rc})")
            if status.startswith("ERR"):
                raise ValueError(f"extractor rejected the input: "
                                 f"{status[4:].strip() or 'no detail'}")
            if not status.startswith("OK "):
                raise ExtractorCrash(
                    f"warm extractor framing violation: {status!r}")
            n = int(status[3:])
            lines = []
            for _ in range(n):
                line = self._readline()
                if self.timed_out:
                    # mid-response watchdog fire is a TIMEOUT (never
                    # retried), not a crash — retrying a hang would
                    # double the stall (bridge policy).
                    raise ExtractionTimeout(
                        f"warm extraction exceeded {timeout:g}s "
                        f"mid-response; worker killed")
                if not line:
                    self.kill()
                    raise ExtractorCrash(
                        "warm extractor worker died mid-response")
                lines.append(line.rstrip("\n"))
            return lines
        finally:
            if timer is not None:
                timer.cancel()

    # -------------------------------------------------------------- API

    def extract(self, *, path: Optional[str] = None,
                source: Optional[str] = None, max_contexts: int,
                timeout_s: Optional[float] = None
                ) -> Tuple[List[str], Dict[str, str]]:
        if self.cold is not None:
            return self._extract_cold(path=path, source=source,
                                      timeout_s=timeout_s)
        if path is not None:
            raw = self._request(f"FILE {os.path.abspath(path)}\n".encode(),
                                timeout_s=timeout_s)
        else:
            assert source is not None
            payload = source.encode()
            raw = self._request(f"SRC {len(payload)}\n".encode(),
                                payload + b"\n", timeout_s=timeout_s)
        if not raw:
            raise ValueError("extractor produced no methods "
                             "(empty or unparsable input)")
        return postprocess_extractor_output(raw, max_contexts)

    def _extract_cold(self, *, path: Optional[str],
                      source: Optional[str],
                      timeout_s: Optional[float] = None
                      ) -> Tuple[List[str], Dict[str, str]]:
        assert self.cold is not None
        # _extract_paths_inner = ONE attempt, no failure counting (that
        # lives in the bridge's retry wrapper, which the pool replaces).
        if path is not None:
            return self.cold._extract_paths_inner(path,
                                                  timeout=timeout_s)
        fd, tmp = tempfile.mkstemp(suffix=".java", prefix="c2v-serve-")
        try:
            with os.fdopen(fd, "w") as f:
                f.write(source or "")
            return self.cold._extract_paths_inner(tmp, timeout=timeout_s)
        finally:
            try:
                os.unlink(tmp)
            except OSError:
                pass

    def kill(self) -> None:
        self.dead = True
        if self.proc is not None:
            try:
                self.proc.kill()
                self.proc.wait(timeout=5)
            except Exception:
                pass

    @property
    def alive(self) -> bool:
        if self.cold is not None:
            return not self.dead
        return (not self.dead and self.proc is not None
                and self.proc.poll() is None)


class ExtractorPool:
    """Fixed-size pool of warm extractor workers behind a free-list.

    `extract_file` / `extract_source` block for a free worker (the wait
    is the serving `queue_wait` SLO phase, recorded into
    `extractor_pool_wait_seconds` and surfaced to the caller via the
    optional `phases` out-dict), run the extraction, and return the
    worker to the free list. A worker that dies mid-request is replaced
    and the request requeued, bounded by `config.extractor_retries`.
    """

    def __init__(self, config, size: int = 2,
                 jar_path: str = DEFAULT_JAR_PATH,
                 max_path_length: int = 8, max_path_width: int = 2,
                 log=None):
        self.config = config
        self.size = max(1, int(size))
        self.jar_path = jar_path
        self.max_path_length = max_path_length
        self.max_path_width = max_path_width
        self.log = log or (lambda msg: None)
        timeout = float(getattr(config, "extractor_timeout_s", 120.0))
        self.timeout = timeout if timeout > 0 else None
        self.retries = max(int(getattr(config, "extractor_retries", 2)), 0)
        self._lock = threading.Lock()
        self._free = threading.Semaphore(0)
        self._idle: List[_Worker] = []
        self._closed = False
        self.warm_command = self._probe_warm_command()
        self.warm = self.warm_command is not None
        for _ in range(self.size):
            self._idle.append(self._new_worker())
            self._free.release()
        _G_SIZE.set(self.size)
        self.log(f"Extractor pool up: {self.size} "
                 f"{'warm --server' if self.warm else 'cold one-shot'} "
                 f"worker(s)")

    # ---------------------------------------------------------- workers

    def _probe_warm_command(self) -> Optional[List[str]]:
        """One probe spawn decides warm vs cold for the whole pool: a
        binary that predates --server exits with a flag error instead of
        printing READY, and the pool silently degrades to cold mode."""
        native = bridge._native_extractor_path()
        if not os.path.exists(native):
            return None
        command = [native, "--max_path_length", str(self.max_path_length),
                   "--max_path_width", str(self.max_path_width),
                   "--server", "--no_hash"]
        try:
            proc = subprocess.Popen(command, stdin=subprocess.PIPE,
                                    stdout=subprocess.PIPE,
                                    stderr=subprocess.DEVNULL)
            try:
                line = proc.stdout.readline().decode(errors="replace")
            finally:
                proc.kill()
                proc.wait(timeout=5)
        except OSError:
            return None
        if line.strip() != "READY":
            self.log(f"Extractor binary {native} has no --server mode; "
                     f"pool degrades to cold per-request workers")
            return None
        return command

    def _new_worker(self) -> _Worker:
        return _Worker(self.config, self.warm_command,
                       self.max_path_length, self.max_path_width,
                       self.timeout, self.jar_path)

    def _replacement_worker(self) -> _Worker:
        """A dead worker's replacement MUST materialize or the pool's
        free-list semaphore would leak a permit and capacity would decay
        request by request: if the warm respawn itself fails (binary
        deleted, fork pressure), fall back to a cold slot — PathExtractor
        construction cannot fail — and keep serving."""
        try:
            return self._new_worker()
        except Exception as e:
            self.log(f"Warm extractor respawn failed ({e}); slot "
                     f"degrades to a cold one-shot worker")
            return _Worker(self.config, None, self.max_path_length,
                           self.max_path_width, self.timeout,
                           self.jar_path)

    def _acquire(self, phases: Optional[dict], deadline=None) -> _Worker:
        t0 = time.perf_counter()
        budget = 300.0
        deadline_bound = False
        if deadline is not None and deadline.bounded:
            remaining = deadline.remaining()
            if remaining < budget:
                budget, deadline_bound = max(remaining, 0.001), True
        if not self._free.acquire(timeout=budget):
            if deadline_bound:
                from code2vec_tpu.serving.admission import (
                    DeadlineExceeded, expired_counter,
                )
                expired_counter("extract").inc()
                raise DeadlineExceeded(
                    "request deadline expired waiting for a free "
                    "extractor worker")
            raise TimeoutError("no extractor worker became free in 300s")
        wait = time.perf_counter() - t0
        _H_WAIT.observe(wait)
        if phases is not None:
            phases["queue_wait"] = phases.get("queue_wait", 0.0) + wait
        with self._lock:
            if self._closed:
                self._free.release()
                raise RuntimeError("extractor pool is closed")
            worker = self._idle.pop()
        if not worker.alive:
            # died while idle (OOM killer sweeps idle children too)
            worker.kill()
            worker = self._replacement_worker()
        return worker

    def _release(self, worker: _Worker) -> None:
        if not worker.alive:
            worker.kill()
            worker = self._replacement_worker()
        with self._lock:
            if self._closed:
                worker.kill()
                return
            self._idle.append(worker)
        self._free.release()

    # -------------------------------------------------------------- API

    def extract_file(self, path: str, phases: Optional[dict] = None,
                     deadline=None, trace=None
                     ) -> Tuple[List[str], Dict[str, str]]:
        return self._extract(phases, path=path, deadline=deadline,
                             trace=trace)

    def extract_source(self, source: str, phases: Optional[dict] = None,
                       deadline=None, trace=None
                       ) -> Tuple[List[str], Dict[str, str]]:
        return self._extract(phases, source=source, deadline=deadline,
                             trace=trace)

    def _effective_timeout(self, deadline) -> Tuple[Optional[float], bool]:
        """min(pool timeout, remaining deadline budget) and whether the
        DEADLINE is the binding constraint (a fire then surfaces as
        DeadlineExceeded/504, not ExtractionTimeout/422)."""
        if deadline is None or not deadline.bounded:
            return None, False  # None -> worker uses the pool timeout
        remaining = deadline.remaining()
        if self.timeout is None or remaining < self.timeout:
            return max(remaining, 0.001), True
        return None, False

    def _extract(self, phases: Optional[dict], *,
                 path: Optional[str] = None, source: Optional[str] = None,
                 deadline=None, trace=None
                 ) -> Tuple[List[str], Dict[str, str]]:
        from code2vec_tpu.serving.admission import (
            DeadlineExceeded, expired_counter,
        )
        _C_REQS.inc()
        max_contexts = self.config.max_contexts
        for attempt in range(self.retries + 1):
            if deadline is not None and deadline.expired():
                expired_counter("extract").inc()
                raise DeadlineExceeded(
                    "request deadline expired before extraction")
            t_wait0 = time.perf_counter()
            worker = self._acquire(phases, deadline=deadline)
            if trace is not None:
                trace.add_span("extract_wait", t_wait0,
                               time.perf_counter() - t_wait0)
            timeout_s, deadline_bound = self._effective_timeout(deadline)
            t0 = time.perf_counter()
            try:
                result = worker.extract(path=path, source=source,
                                        max_contexts=max_contexts,
                                        timeout_s=timeout_s)
            except ExtractionTimeout:
                # bridge policy: a hung worker is killed, never retried.
                # When the binding constraint was the request's own
                # deadline budget (not the pool-wide hang timeout), the
                # honest status is 504, not an extraction failure.
                worker.kill()
                if deadline_bound:
                    expired_counter("extract").inc()
                    raise DeadlineExceeded(
                        "request deadline expired during extraction "
                        "(worker killed)")
                raise
            except FileNotFoundError:
                raise  # no extractor installed at all — not transient
            except (ExtractorCrash, OSError) as e:
                final = attempt == self.retries
                worker.kill()
                bridge._count_failure(retried=not final)
                if final:
                    raise
                _C_REQUEUES.inc()
                self.log(f"Extractor worker died mid-request "
                         f"({e}); requeued on a fresh worker "
                         f"(attempt {attempt + 2}/{self.retries + 1})")
                continue
            except ValueError:
                # deterministic rejection (parse error / empty output):
                # identical on every retry, surfaced immediately. Both
                # modes count HERE and only here (cold workers run the
                # bridge's raw single-attempt path, which never counts).
                bridge._count_failure(retried=False)
                raise
            finally:
                dur = time.perf_counter() - t0
                _H_EXTRACT.observe(dur)
                if phases is not None:
                    phases["extract"] = phases.get("extract", 0.0) + dur
                if trace is not None:
                    trace.add_span(
                        "extract", t0, dur,
                        attrs={"attempt": attempt + 1,
                               "mode": "cold" if worker.cold is not None
                               else "warm",
                               "worker_pid": (worker.proc.pid
                                              if worker.proc is not None
                                              else None)})
                self._release(worker)
            return result
        raise AssertionError("unreachable")  # pragma: no cover

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            idle, self._idle = self._idle, []
        for w in idle:
            w.kill()
        _G_SIZE.set(0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
