"""LRU code-vector / prediction cache keyed by normalized method-body
hash.

Serving traffic is heavily repetitive (IDE plugins re-send the method on
every keystroke pause; CI re-submits unchanged files), so a small LRU in
front of extract+predict converts the common case from
subprocess+device work into a dict hit. Keys are a blake2b digest of the
WHITESPACE-NORMALIZED source plus every knob that changes the answer —
endpoint, topk, and the serving model's identity fingerprint
(model_fingerprint(): checkpoint path + step for the facade, artifact
content hash for a release bundle), so a hot-swapped or re-exported
model can never satisfy a stale entry. Reformatting a method must hit,
editing it must miss. Values are opaque to the cache; the HTTP layer
stores the final serialized response bytes, which makes the hit path
byte-equal to the miss path by construction (pinned in
tests/test_serving.py).

Thread-safe: the HTTP server handles requests on a thread per
connection. Hits, misses and evictions are first-class counters.

Tenancy (serving/tenancy.py) deliberately does NOT split this cache
per tenant: the key is a pure content fingerprint, so two tenants
sending the same method body get the same bytes — the hit path stays
byte-equal to the miss path regardless of who asks, and hits stay
PRE-ADMISSION (a cache hit costs no pipeline capacity, so it is never
counted against a tenant's share or rate quota).
"""

from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict
from typing import Optional

from code2vec_tpu import obs

_C_HITS = obs.counter("serving_cache_hits_total",
                      "prediction-cache lookups served from memory")
_C_MISSES = obs.counter("serving_cache_misses_total",
                        "prediction-cache lookups that went to the model")
_C_EVICTIONS = obs.counter(
    "serving_cache_evictions_total",
    "LRU entries dropped to stay under serve_cache_entries")
_G_ENTRIES = obs.gauge("serving_cache_entries",
                       "live prediction-cache entries")


def normalize_source(code: str) -> bytes:
    """Whitespace-insensitive canonical form: any run of whitespace
    (indentation, newlines, trailing blanks) collapses to one space.
    Java is whitespace-insensitive outside string literals; collapsing
    INSIDE a literal could alias two genuinely different methods, but
    only onto a prediction for code differing solely in literal spacing
    — an acceptable trade for reformat-hits, and documented in README
    'Serving'."""
    return " ".join(code.split()).encode()


def cache_key(code: str, **knobs) -> str:
    return cache_key_normalized(normalize_source(code), **knobs)


def cache_key_normalized(normalized: bytes, **knobs) -> str:
    """Key from an ALREADY-normalized source (one `normalize_source`
    pass per request: the server reuses the same bytes for the initial
    probe, the traffic-sampler key and the hot-swap re-key instead of
    re-collapsing the whole body each time)."""
    h = hashlib.blake2b(normalized, digest_size=16)
    for name in sorted(knobs):
        h.update(f"\x00{name}={knobs[name]}".encode())
    return h.hexdigest()


class PredictionCache:
    """Bounded LRU. capacity <= 0 disables (every get misses, puts are
    dropped) so one code path serves cache-on and cache-off runs."""

    def __init__(self, capacity: int):
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        self._data: "OrderedDict[str, object]" = OrderedDict()

    def get(self, key: str) -> Optional[object]:
        if self.capacity <= 0:
            _C_MISSES.inc()
            return None
        with self._lock:
            value = self._data.get(key)
            if value is None:
                _C_MISSES.inc()
                return None
            self._data.move_to_end(key)
        _C_HITS.inc()
        return value

    def put(self, key: str, value: object) -> None:
        if self.capacity <= 0:
            return
        with self._lock:
            self._data[key] = value
            self._data.move_to_end(key)
            while len(self._data) > self.capacity:
                self._data.popitem(last=False)
                _C_EVICTIONS.inc()
            _G_ENTRIES.set(len(self._data))

    def clear(self) -> None:
        with self._lock:
            self._data.clear()
            _G_ENTRIES.set(0)

    def __len__(self) -> int:
        with self._lock:
            return len(self._data)
