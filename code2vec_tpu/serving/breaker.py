"""Circuit breakers: fail fast when a serving dependency is down.

Without a breaker, a dead extractor pool (or a wedged device) makes
every request pay the full timeout before failing — the server stays
"up" while every client waits seconds for a guaranteed error, and the
retry storm keeps the corpse warm. The breaker converts a failing
dependency into *immediate* honest 503s, then probes for recovery:

    CLOSED --(failure rate over the rolling window >= threshold,
              with at least min_requests samples)--> OPEN
    OPEN   --(cooldown elapsed)--> HALF_OPEN (exactly ONE probe
              request is let through; everyone else still sheds)
    HALF_OPEN --probe succeeds--> CLOSED (window reset)
    HALF_OPEN --probe fails-----> OPEN (cooldown restarts)

Two breakers guard the serving pipeline (serving/server.py): one around
the extractor pool (an open breaker fails extraction-dependent requests
fast — cache hits still serve, pinned in the chaos suite) and one
around the device step. Knobs: `--serve_breaker_window`,
`--serve_breaker_failure_ratio`, `--serve_breaker_min_requests`,
`--serve_breaker_cooldown`.

State is exported as `serving_breaker_state{breaker=...}`
(0=closed, 1=open, 2=half_open) plus
`serving_breaker_transitions_total{breaker,to}` so a dashboard shows
both where each breaker is and how often it flaps.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Deque, Tuple

from code2vec_tpu import obs
from code2vec_tpu.serving.admission import Shed

CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"
_STATE_CODE = {CLOSED: 0, OPEN: 1, HALF_OPEN: 2}


class BreakerOpen(Shed):
    """Raised on the request path when a breaker refuses the call; a
    Shed with reason=breaker, so the server's one shed handler maps it
    to 503 + Retry-After (the remaining cooldown)."""

    def __init__(self, name: str, retry_after_s: float):
        super().__init__(
            "breaker",
            f"{name} circuit breaker is open (dependency failing); "
            f"failing fast", retry_after_s=retry_after_s)
        self.breaker = name


class CircuitBreaker:
    """Rolling-failure-rate breaker. Thread-safe; `allow()` before the
    dependency call, `record(ok)` after (never for calls `allow()`
    refused — a shed was not a dependency outcome)."""

    def __init__(self, name: str, window_s: float = 10.0,
                 failure_ratio: float = 0.5, min_requests: int = 4,
                 cooldown_s: float = 5.0, clock=time.monotonic,
                 on_transition=None):
        self.name = name
        self.window_s = float(window_s)
        self.failure_ratio = float(failure_ratio)
        self.min_requests = max(1, int(min_requests))
        self.cooldown_s = float(cooldown_s)
        self._clock = clock
        # Called as on_transition(name, to_state) AFTER the state flip
        # (under the breaker lock — keep it cheap and non-reentrant);
        # the server points it at the flight recorder so every breaker
        # transition is an anomaly event and an OPEN is an incident.
        self.on_transition = on_transition
        self._lock = threading.Lock()
        self._events: Deque[Tuple[float, bool]] = deque()
        self._state = CLOSED
        self._opened_at = 0.0
        self._probe_inflight = False
        self._gauge = obs.gauge(
            "serving_breaker_state",
            "circuit-breaker state: 0=closed, 1=open, 2=half_open",
            breaker=name)
        self._gauge.set(0)

    # ------------------------------------------------------------ state

    @property
    def state(self) -> str:
        with self._lock:
            return self._peek_state_locked()

    def _peek_state_locked(self) -> str:
        # open -> half_open is time-driven; surface it without waiting
        # for the next allow() so healthz never shows a stale "open".
        if (self._state == OPEN
                and self._clock() - self._opened_at >= self.cooldown_s):
            return HALF_OPEN
        return self._state

    def _transition_locked(self, to: str) -> None:
        if to == self._state:
            return
        self._state = to
        self._gauge.set(_STATE_CODE[to])
        obs.counter(
            "serving_breaker_transitions_total",
            "circuit-breaker state transitions",
            breaker=self.name, to=to).inc()
        if self.on_transition is not None:
            try:
                self.on_transition(self.name, to)
            except Exception:  # noqa: BLE001 — telemetry must never
                pass           # turn a state flip into a request error

    def retry_after_s(self) -> float:
        """Seconds until the next probe could be let through."""
        with self._lock:
            if self._state != OPEN:
                return 1.0
            return max(self.cooldown_s
                       - (self._clock() - self._opened_at), 1.0)

    # -------------------------------------------------------------- API

    def allow(self) -> bool:
        """May this call proceed? In half-open, exactly one in-flight
        probe is allowed; the probe slot is re-armed by record()."""
        with self._lock:
            if self._state == OPEN:
                if self._clock() - self._opened_at < self.cooldown_s:
                    return False
                self._transition_locked(HALF_OPEN)
                self._probe_inflight = False
            if self._state == HALF_OPEN:
                if self._probe_inflight:
                    return False
                self._probe_inflight = True
                return True
            return True

    def check(self) -> None:
        """allow() or raise BreakerOpen — the request-path form."""
        if not self.allow():
            raise BreakerOpen(self.name, self.retry_after_s())

    def abort(self) -> None:
        """The guarded call ended WITHOUT a dependency verdict — e.g.
        the request's own deadline expired mid-call, which says nothing
        about the dependency's health. In half-open this re-arms the
        probe slot (otherwise one aborted probe would wedge the breaker
        in half_open forever, shedding every request after the
        dependency recovered); in any other state it is a no-op."""
        with self._lock:
            if self._state == HALF_OPEN:
                self._probe_inflight = False

    def record(self, ok: bool) -> None:
        now = self._clock()
        with self._lock:
            if self._state == HALF_OPEN:
                self._probe_inflight = False
                if ok:
                    self._events.clear()
                    self._transition_locked(CLOSED)
                else:
                    self._opened_at = now
                    self._transition_locked(OPEN)
                return
            self._events.append((now, ok))
            cutoff = now - self.window_s
            while self._events and self._events[0][0] < cutoff:
                self._events.popleft()
            if self._state == CLOSED:
                n = len(self._events)
                failures = sum(1 for _, e_ok in self._events if not e_ok)
                if (n >= self.min_requests
                        and failures / n >= self.failure_ratio):
                    self._opened_at = now
                    self._probe_inflight = False
                    self._transition_locked(OPEN)
