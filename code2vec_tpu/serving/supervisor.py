"""Supervised multi-replica serving: `serve --replicas N`.

One serving process is one failure domain: an OOM kill, a wedged device
call or a poisoned request takes the whole service down until an
operator notices. The supervisor turns the single-process server into a
self-healing N-replica service:

- **Fork**: the parent never builds a model. It re-execs N copies of
  its own command (``--replicas`` stripped, ``C2V_SERVE_REPLICA=<i>``
  set so a replica can never recurse into supervising), each a full
  single-model server with its own extractor pool and cache.
- **Share the port**: every replica binds the SAME listen port with
  ``SO_REUSEPORT`` (the kernel load-balances accepted connections).
  Where the platform lacks it — or when ``C2V_SERVE_FORCE_PROXY=1``
  forces the fallback, which the chaos suite uses for deterministic
  routing — replicas bind free ports and the supervisor runs its own
  lightweight round-robin HTTP proxy on the public port, skipping dead
  replicas and retrying the next one on connection failure.
- **Monitor**: each replica writes the PR-2 JSON heartbeat
  (``--heartbeat_file``, rewritten every serve_heartbeat_interval_s)
  and inherits a liveness pipe. A replica whose process exits is
  CRASHED; one whose heartbeat goes ~3 intervals stale is HUNG (killed,
  then treated as crashed). Either is restarted with exponential
  backoff, up to ``--serve_max_restarts`` restarts per replica — after
  which the supervisor ESCALATES: kills everything and exits nonzero
  (a replica that cannot stay up is a deploy problem, and pretending
  otherwise hides it from the rollout system).
- **Drain**: SIGTERM to the supervisor fans out as SIGTERM to every
  replica (each runs its own in-flight drain bounded by
  serve_drain_timeout_s); the supervisor exits 0 only when every
  replica exited 0.
- **Fleet telemetry** (serving/telemetry.py, README "Telemetry"): each
  replica publishes an atomic Prometheus snapshot (--metrics_file,
  appended per replica below) every heartbeat interval; the supervisor
  serves the MERGE at ``GET /metrics`` on its telemetry listener
  (--serve_telemetry_port, default public port + 1) plus a
  ``GET /fleet`` JSON view (per-replica breaker state, shed rate,
  heartbeat staleness, restarts, fingerprint). This is the documented
  scrape address under reuseport — a scrape of the shared public port
  reaches ONE kernel-chosen replica and samples a random shard of the
  fleet. In proxy mode the public port answers both paths itself.
  Replica restarts are flight-recorder events and an escalation is an
  incident with a synchronous ring dump into the run dir
  (obs/flight.py).

The supervisor's own heartbeat records per-replica pid/port/restarts so
"which replica is which process" is answerable from the file alone —
the serving chaos suite (tests/test_serving_chaos.py) reads it to pick
a SIGKILL victim and to assert convergence back to N live replicas.
"""

from __future__ import annotations

import json
import os
import select
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time
from typing import Dict, List, Optional

from code2vec_tpu import obs
from code2vec_tpu.obs.reqtrace import RequestTrace
from code2vec_tpu.serving.admission import (
    deadline_from_request, retry_after_seconds,
)
from code2vec_tpu.serving.forwarding import (
    REQUEST_FORWARD_HEADERS, forward_with_retry, handle_admin_post,
)

REPLICA_ENV = "C2V_SERVE_REPLICA"
FORCE_PROXY_ENV = "C2V_SERVE_FORCE_PROXY"
# Seconds a replica gets from spawn to its first heartbeat before the
# supervisor declares a hung STARTUP (model build + jit warmup can
# legitimately take tens of seconds on a cold replica).
STARTUP_GRACE_S = 120.0
# Cache-warmth window for scale-down victim selection: the monitor
# loop re-baselines every replica's cache-hit counter at this cadence,
# so "warmth" means hits over the last window (up to 2x this), not
# lifetime.
_WARMTH_WINDOW_S = 60.0
# Hard ceiling on /admin/scale: the per-host replica count is bounded
# by cores/HBM, not ambition — a runaway autoscaler must not fork-bomb
# the host.
MAX_REPLICAS = 64

_C_RESTARTS = obs.counter(
    "serving_replica_restarts_total",
    "replica processes restarted by the serving supervisor "
    "(crash or stale heartbeat)")


def _c_scale(direction: str):
    return obs.counter(
        "serving_replica_scale_total",
        "supervisor replica-count changes applied via /admin/scale "
        "(up = spawned, down = drained and retired)",
        direction=direction)


def _c_snapshot_skipped(replica) -> obs.Counter:
    return obs.counter(
        "serving_telemetry_snapshots_skipped_total",
        "per-replica metrics snapshots the merged /metrics scrape "
        "skipped because the file was torn or unparsable (the scrape "
        "serves the surviving replicas' truth instead of 500ing)",
        replica=str(replica))


def strip_flag(argv: List[str], flag: str,
               has_value: bool = True) -> List[str]:
    """Remove every occurrence of `flag` (and its value, both
    `--flag V` and `--flag=V` forms) from an argv list."""
    out: List[str] = []
    skip = False
    for arg in argv:
        if skip:
            skip = False
            continue
        if arg == flag:
            skip = has_value
            continue
        if has_value and arg.startswith(flag + "="):
            continue
        out.append(arg)
    return out


def child_env(base_env: Dict[str, str]) -> Dict[str, str]:
    """Copy of `base_env` with this package's parent dir on
    PYTHONPATH: the supervisor/fleet re-exec children via
    `python -m code2vec_tpu.cli`, and a parent launched from OUTSIDE
    the repo (cwd anywhere, repo importable only via its own
    sys.path) would otherwise spawn children that cannot import the
    package at all."""
    import code2vec_tpu
    env = dict(base_env)
    root = os.path.dirname(os.path.dirname(
        os.path.abspath(code2vec_tpu.__file__)))
    pythonpath = env.get("PYTHONPATH", "")
    if root not in pythonpath.split(os.pathsep):
        env["PYTHONPATH"] = (root + (os.pathsep + pythonpath
                                     if pythonpath else ""))
    return env


def _free_port(host: str) -> int:
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind((host, 0))
        return s.getsockname()[1]


class _Replica:
    def __init__(self, index: int, heartbeat_path: str, log_path: str,
                 metrics_path: Optional[str] = None):
        self.index = index
        self.heartbeat_path = heartbeat_path
        self.log_path = log_path
        self.metrics_path = metrics_path
        self.proc: Optional[subprocess.Popen] = None
        self.pipe_r: Optional[int] = None
        self.port: Optional[int] = None
        self.restarts = 0
        self.spawned_at = 0.0
        self.restart_at: Optional[float] = None  # backoff gate
        # scale-down lifecycle: a draining replica finishes in-flight
        # work (its own SIGTERM drain), then is RETIRED — never
        # restarted, never counted against the desired replica count
        self.draining = False
        self.drain_started = 0.0
        # reload fan-out deferred until the replica's first heartbeat:
        # a SIGHUP before serve_main installs its handler would KILL a
        # still-starting replica (default SIGHUP disposition)
        self.pending_reload = False
        # cache-warmth window baseline: serving_cache_hits_total at the
        # last warmth sample (monitor loop, ~every _WARMTH_WINDOW_S).
        # Scale-down ranks replicas by hits SINCE this baseline — the
        # lifetime counter measures uptime, not current hit rate, and
        # would protect a long-lived replica whose cache stopped
        # absorbing traffic an hour ago.
        self.warmth_prev = 0.0

    @property
    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    def heartbeat(self) -> Optional[dict]:
        try:
            with open(self.heartbeat_path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None


class Supervisor:
    """Owns N replica processes + (in proxy mode) the public listener."""

    def __init__(self, config, argv: Optional[List[str]] = None,
                 child_command: Optional[List[str]] = None):
        self.config = config
        self.log = config.log
        self.n = int(config.serve_replicas)
        if child_command is not None:
            self.child_command = list(child_command)
        else:
            stripped = strip_flag(list(argv or []), "--replicas")
            # each replica gets its OWN --metrics_file (the fleet
            # telemetry feed) and --trace_export — a user-supplied path
            # would have every replica overwrite the same file (the
            # atomic tmp+rename makes the clobber silent: last replica
            # to exit wins)
            stripped = strip_flag(stripped, "--metrics_file")
            stripped = strip_flag(stripped, "--trace_export")
            # ...and --serve_traffic_sample: every replica rewriting
            # ONE ring file would silently reduce the shadow-eval
            # corpus to whichever replica flushed last
            stripped = strip_flag(stripped, "--serve_traffic_sample")
            self.child_command = ([sys.executable, "-m",
                                   "code2vec_tpu.cli"] + stripped)
        self.trace_export = bool(getattr(config, "trace_export", None))
        # the supervisor's OWN span ring (proxy forwards, reload
        # fan-outs) exports to the --trace_export path the control
        # plane assigned this host; replicas get derived per-replica
        # paths in the same run dir
        self.trace_export_path = getattr(config, "trace_export", None)
        if self.trace_export_path:
            obs.default_tracer().enable()
        self.traffic_sample = getattr(config,
                                      "serve_traffic_sample_file", None)
        base = (os.path.dirname(os.path.abspath(config.heartbeat_file))
                if config.heartbeat_file else None)
        self.run_dir = base or tempfile.mkdtemp(prefix="c2v-serve-sup-")
        os.makedirs(self.run_dir, exist_ok=True)
        self.heartbeat_path = (config.heartbeat_file or os.path.join(
            self.run_dir, "supervisor.heartbeat.json"))
        self.reuseport = (hasattr(socket, "SO_REUSEPORT")
                          and os.environ.get(FORCE_PROXY_ENV) != "1")
        self.port = int(config.serve_port)
        if self.reuseport and self.port == 0:
            # replicas must all bind ONE concrete port; resolve now
            self.port = _free_port(config.serve_host)
        self.replicas = [self._make_replica(i) for i in range(self.n)]
        # /admin/scale: the monitor loop reconciles the live replica set
        # toward `_desired` (spawn up, drain down); indices only ever
        # grow so a retiring replica's run files never collide with a
        # newly spawned one's
        self._desired = self.n
        self._next_index = self.n
        self._scale_lock = threading.Lock()
        self._last_reload: Optional[dict] = None
        self._stop = threading.Event()
        self._escalated = False
        self._proxy = None
        self._rr_lock = threading.Lock()
        self._rr_next = 0
        self._telemetry = None
        # Supervisor-side flight recorder: replica restarts are anomaly
        # events, an escalation is an incident with a synchronous dump
        # into the run dir (the replicas' own dumps land there too when
        # --heartbeat_file puts their run files in one place).
        self.flight = obs.default_flight_recorder()
        self.flight.configure(
            dump_dir=self.run_dir,
            max_dumps=getattr(config, "serve_flight_max_dumps", 64),
            log=self.log)

    # ------------------------------------------------------------ spawn

    def _make_replica(self, index: int) -> _Replica:
        return _Replica(
            index,
            os.path.join(self.run_dir, f"replica{index}.heartbeat.json"),
            os.path.join(self.run_dir, f"replica{index}.log"),
            os.path.join(self.run_dir, f"replica{index}.metrics.prom"))

    def _spawn(self, replica: _Replica) -> None:
        try:
            os.remove(replica.heartbeat_path)
        except OSError:
            pass
        replica.port = None
        cmd = list(self.child_command)
        cmd += ["--heartbeat_file", replica.heartbeat_path]
        if replica.metrics_path:
            # the replica's fleet-telemetry feed: an atomic Prometheus
            # snapshot rewritten every heartbeat interval, merged by
            # the supervisor's /metrics + /fleet (serving/telemetry.py).
            # A restarted replica's counters restart from zero — the
            # stale pre-crash file would double-count, so drop it.
            try:
                os.remove(replica.metrics_path)
            except OSError:
                pass
            cmd += ["--metrics_file", replica.metrics_path]
        if self.trace_export:
            cmd += ["--trace_export",
                    os.path.join(self.run_dir,
                                 f"replica{replica.index}.trace.json")]
        if self.traffic_sample:
            # per-replica (and, under a fleet, per-host) traffic
            # sample ring (README "Continuous training"): point the
            # pipeline's --pipeline_traffic at any one of them (or
            # concatenate)
            host = os.environ.get("C2V_FLEET_HOST")
            suffix = (f".{host}" if host else "") + \
                f".replica{replica.index}"
            cmd += ["--serve_traffic_sample",
                    self.traffic_sample + suffix]
        env = child_env(os.environ)
        env[REPLICA_ENV] = str(replica.index)
        if self.reuseport:
            cmd += ["--serve_port", str(self.port)]
            env["C2V_SERVE_REUSEPORT"] = "1"
            replica.port = self.port
        else:
            cmd += ["--serve_port", "0"]  # report via heartbeat
            env.pop("C2V_SERVE_REUSEPORT", None)
        r, w = os.pipe()  # liveness pipe: EOF = replica gone
        os.set_inheritable(w, True)
        logf = open(replica.log_path, "ab")
        try:
            replica.proc = subprocess.Popen(
                cmd, env=env, pass_fds=(w,), stdout=logf, stderr=logf)
        finally:
            logf.close()
            os.close(w)
        if replica.pipe_r is not None:
            try:
                os.close(replica.pipe_r)
            except OSError:
                pass
        replica.pipe_r = r
        replica.spawned_at = time.monotonic()
        replica.restart_at = None
        # Desired-state reconciliation: a reload-target file means the
        # fleet's current artifact is NOT the boot artifact this child
        # just loaded (reload_all / the control plane wrote it), so a
        # crash-restarted replica must be swapped onto it at its first
        # heartbeat — otherwise one OOM after a committed rollout
        # silently mixes fingerprints on this host forever.
        from code2vec_tpu.serving.server import RELOAD_TARGET_FILENAME
        replica.pending_reload = os.path.exists(
            os.path.join(self.run_dir, RELOAD_TARGET_FILENAME))
        self.log(f"Replica {replica.index} spawned "
                 f"(pid {replica.proc.pid}"
                 f"{f', port {replica.port}' if replica.port else ''})")

    def _kill(self, replica: _Replica, sig=signal.SIGKILL) -> None:
        if replica.proc is not None and replica.proc.poll() is None:
            try:
                replica.proc.send_signal(sig)
            except OSError:
                pass

    def _fan_out_sighup(self) -> None:
        self.log("SIGHUP: fanning reload out to all replicas")
        for replica in list(self.replicas):
            if replica.draining:
                continue
            if replica.heartbeat() is None:
                # no heartbeat = serve_main has not installed its
                # SIGHUP handler yet; the default disposition would
                # KILL the starting child — defer to first heartbeat
                replica.pending_reload = True
                continue
            self._kill(replica, signal.SIGHUP)

    # ------------------------------------------------------------ scale

    def request_scale(self, n) -> dict:
        """POST /admin/scale body — set the desired replica count; the
        monitor loop reconciles (spawn up / coordinated-drain down).
        The fleet control plane drives this off the telemetry signals
        (serving/fleet/control.py); operators can too."""
        try:
            n = int(n)
        except (TypeError, ValueError):
            raise ValueError('body must be {"replicas": N}')
        if not (1 <= n <= MAX_REPLICAS):
            raise ValueError(
                f"replicas must be in [1, {MAX_REPLICAS}] (got {n})")
        with self._scale_lock:
            self._desired = n
        self.log(f"Scale request: desired replicas -> {n}")
        return {"desired_replicas": n,
                "current_replicas": len(self.replicas)}

    def _reconcile_scale(self) -> None:
        with self._scale_lock:
            desired = self._desired
        active = [r for r in self.replicas if not r.draining]
        for _ in range(desired - len(active)):
            replica = self._make_replica(self._next_index)
            self._next_index += 1
            self.replicas.append(replica)
            self._spawn(replica)
            _c_scale("up").inc()
            self.flight.event("replica_scale_up", replica=replica.index)
        excess = len(active) - desired
        if excess > 0:
            for replica in self._scale_down_victims(active, excess):
                replica.draining = True
                replica.drain_started = time.monotonic()
                replica.restart_at = None
                self._kill(replica, signal.SIGTERM)
                _c_scale("down").inc()
                self.flight.event("replica_scale_down",
                                  replica=replica.index)
                self.log(f"Replica {replica.index} draining "
                         f"(scale-down)")

    @staticmethod
    def _read_cache_hits(replica: _Replica) -> float:
        """Lifetime serving_cache_hits_total from the replica's
        telemetry snapshot; 0 for a missing/unreadable one (a replica
        still starting has absorbed nothing)."""
        from code2vec_tpu.serving import telemetry
        if not (replica.metrics_path
                and os.path.isfile(replica.metrics_path)):
            return 0.0
        try:
            with open(replica.metrics_path,
                      encoding="utf-8", errors="replace") as f:
                return telemetry.sum_family(
                    f.read(), "serving_cache_hits_total")
        except (OSError, ValueError):
            return 0.0

    def _sample_warmth_baselines(self) -> None:
        """Roll the cache-warmth window: every live replica's current
        lifetime hit count becomes the next window's baseline (monitor
        loop, ~every _WARMTH_WINDOW_S)."""
        for replica in list(self.replicas):
            replica.warmth_prev = self._read_cache_hits(replica)

    def _scale_down_victims(self, active: List[_Replica],
                            excess: int) -> List[_Replica]:
        """Cache-warmth-aware scale-down selection (PR-13 follow-on):
        retire the replicas whose prediction caches absorbed the
        FEWEST hits over the current warmth window (hits since the
        last ~_WARMTH_WINDOW_S baseline — lifetime counters measure
        uptime, not warmth, and the repo's own autoscaler discipline
        is windowed deltas for exactly that reason). A replica without
        a readable snapshot counts 0; a restarted replica's
        counter-reset clamps to 0 (its fresh cache IS cold). Ties (a
        cold host where every window is 0) fall back to newest-first,
        the previous policy: replica 0's compiled steps are the
        oldest."""
        hits = {replica: max(0.0, self._read_cache_hits(replica)
                             - replica.warmth_prev)
                for replica in active}
        victims = sorted(active,
                         key=lambda r: (hits[r], -r.index))[:excess]
        for v in victims:
            self.log(f"Scale-down victim: replica {v.index} "
                     f"(window cache hits {hits[v]:.0f} — fewest "
                     f"among {len(active)} active)")
        return victims

    def _retire(self, replica: _Replica) -> None:
        """A drained (scale-down) replica exited: reap and REMOVE it —
        its exit is policy, not a failure to restart."""
        if replica.proc is not None:
            replica.proc.wait()
        if replica.pipe_r is not None:
            try:
                os.close(replica.pipe_r)
            except OSError:
                pass
            replica.pipe_r = None
        # its metrics snapshot must leave the merge: a retired
        # replica's frozen counters would shadow the live fleet
        if replica.metrics_path:
            try:
                os.remove(replica.metrics_path)
            except OSError:
                pass
        self.replicas.remove(replica)
        self.log(f"Replica {replica.index} retired "
                 f"(rc={replica.proc.returncode if replica.proc else '?'})")

    # ----------------------------------------------------------- reload

    def reload_all(self, artifact, retrieval_index=None) -> dict:
        """Fan a hot-swap to `artifact` out to EVERY live replica —
        the per-host leg of the fleet-wide coordinated swap
        (serving/fleet/swap.py drives this canary-host-first). Proxy
        mode POSTs each replica's own /admin/reload; under SO_REUSEPORT
        one shared port cannot address a specific replica, so the
        target rides a `reload-target.json` in the run dir + SIGHUP
        (serve_main's handler reads the file). Swap RESULTS are
        asynchronous — callers poll /fleet for per-replica swap_state +
        fingerprint convergence."""
        if not artifact:
            raise ValueError('no artifact: body must be '
                             '{"artifact": DIR}')
        import http.client
        artifact = str(artifact)
        targets = [r for r in list(self.replicas)
                   if r.alive and not r.draining]
        results = []
        # the reload target is written in BOTH modes: a replica still
        # STARTING (no heartbeat yet — its SIGHUP handler is not
        # installed, so a signal now would kill it) gets the fan-out
        # DEFERRED to its first heartbeat, delivered as SIGHUP + this
        # file by the monitor loop
        from code2vec_tpu.serving.server import RELOAD_TARGET_FILENAME
        # _atomic_write's thread-unique tmp matters here: the telemetry
        # listener AND the proxy both accept /admin/reload on their own
        # threads of this pid
        target_payload = {"artifact": artifact,
                          "requested_at": time.time()}
        if retrieval_index:
            target_payload["retrieval_index"] = str(retrieval_index)
        obs.exporters._atomic_write(
            os.path.join(self.run_dir, RELOAD_TARGET_FILENAME),
            json.dumps(target_payload) + "\n")
        ready, starting = [], []
        for replica in targets:
            (ready if replica.heartbeat() is not None
             else starting).append(replica)
        for replica in starting:
            replica.pending_reload = True
            results.append({"index": replica.index, "via": "deferred",
                            "accepted": True})
        if self.reuseport:
            for replica in ready:
                self._kill(replica, signal.SIGHUP)
                results.append({"index": replica.index, "via": "sighup",
                                "accepted": True})
        else:
            for replica in ready:
                if replica.port is None:
                    replica.pending_reload = True
                    results.append({"index": replica.index,
                                    "via": "deferred",
                                    "accepted": True})
                    continue
                try:
                    conn = http.client.HTTPConnection(
                        self.config.serve_host, replica.port,
                        timeout=10)
                    try:
                        body = {"artifact": artifact}
                        if retrieval_index:
                            body["retrieval_index"] = str(
                                retrieval_index)
                        conn.request(
                            "POST", "/admin/reload",
                            body=json.dumps(body).encode(),
                            headers={"Content-Type":
                                     "application/json"})
                        resp = conn.getresponse()
                        resp.read()
                        results.append({"index": replica.index,
                                        "via": "http",
                                        "accepted": resp.status == 202,
                                        "status": resp.status})
                    finally:
                        conn.close()
                except (OSError, http.client.HTTPException) as e:
                    results.append({"index": replica.index,
                                    "via": "http", "accepted": False,
                                    "error": f"{type(e).__name__}: "
                                             f"{e}"})
        status = {"artifact": artifact, "requested_at": time.time(),
                  "replicas": results}
        if retrieval_index:
            # the control plane's respawn reconcile compares this
            # reported pair against its committed pair — the artifact
            # alone would read as "index missing" forever
            status["retrieval_index"] = str(retrieval_index)
        self._last_reload = status
        self.flight.event("host_reload_fanout", artifact=artifact,
                          replicas=len(results))
        self.log(f"Reload fan-out: artifact {artifact} -> "
                 f"{len(results)} replica(s)")
        return status

    def _admin_scale(self, payload: dict):
        return 200, self.request_scale(payload.get("replicas"))

    def _admin_reload(self, payload: dict):
        # The fleet swap driver threads its rollout traceparent INSIDE
        # the JSON body (the telemetry listener's post handlers never
        # see HTTP headers): this host's fan-out span parents under the
        # rollout trace, so `fleet trace` shows operator -> router ->
        # swap driver -> every host as one tree.
        trace = RequestTrace.from_headers(payload.get("traceparent"))
        with trace.span("host.reload_fanout",
                        artifact=str(payload.get("artifact"))):
            return 202, self.reload_all(
                payload.get("artifact"),
                retrieval_index=payload.get("retrieval_index"))

    # ---------------------------------------------------------- monitor

    def _stale_after(self) -> float:
        return 3.0 * self.config.serve_heartbeat_interval_s + 2.0

    def _check_replica(self, replica: _Replica, now: float
                       ) -> Optional[str]:
        """Returns a failure description or None (healthy/waiting)."""
        if replica.restart_at is not None:
            return None  # in backoff; spawned when due
        if replica.proc is None:
            return None
        rc = replica.proc.poll()
        if rc is not None:
            return f"exited rc={rc}"
        hb = replica.heartbeat()
        if hb is None:
            if now - replica.spawned_at > STARTUP_GRACE_S:
                self._kill(replica)
                return (f"no heartbeat within the "
                        f"{STARTUP_GRACE_S:g}s startup grace (hung "
                        f"startup; killed)")
            return None
        if replica.port is None:
            port = hb.get("port")
            if port:
                replica.port = int(port)
                self.log(f"Replica {replica.index} listening on port "
                         f"{replica.port}")
        if replica.pending_reload:
            # deferred reload fan-out: the first heartbeat proves
            # serve_main's SIGHUP handler is installed (handlers are
            # set before the server starts publishing), so the signal
            # now triggers a swap instead of killing a starting child
            replica.pending_reload = False
            self._kill(replica, signal.SIGHUP)
            self.log(f"Replica {replica.index} ready; delivering the "
                     f"deferred reload fan-out (SIGHUP)")
        age = time.time() - float(hb.get("wall_time", 0))
        if age > self._stale_after():
            self._kill(replica)
            return (f"heartbeat stale ({age:.1f}s > "
                    f"{self._stale_after():.1f}s; hung; killed)")
        return None

    def _handle_failure(self, replica: _Replica, why: str) -> bool:
        """Schedule a backoff restart; False when the budget is
        exhausted (escalate)."""
        if replica.proc is not None:
            replica.proc.wait()  # reap
        if replica.pipe_r is not None:
            # drop the dead replica's liveness pipe from the monitor's
            # select set NOW: at EOF it is permanently readable, and
            # leaving it in would busy-spin the loop for the whole
            # backoff window
            try:
                os.close(replica.pipe_r)
            except OSError:
                pass
            replica.pipe_r = None
        if replica.restarts >= self.config.serve_max_restarts:
            self.log(f"Replica {replica.index} {why}; restart budget "
                     f"({self.config.serve_max_restarts}) exhausted — "
                     f"escalating to supervisor exit")
            self.flight.incident(
                "replica_escalation", immediate=True,
                replica=replica.index, why=why,
                restarts=replica.restarts)
            return False
        replica.restarts += 1
        _C_RESTARTS.inc()
        self.flight.event("replica_restart", replica=replica.index,
                          why=why, restart=replica.restarts)
        backoff = min(0.5 * (2 ** (replica.restarts - 1)), 10.0)
        replica.restart_at = time.monotonic() + backoff
        self.log(f"Replica {replica.index} {why}; restart "
                 f"{replica.restarts}/{self.config.serve_max_restarts} "
                 f"in {backoff:.1f}s")
        return True

    def _write_heartbeat(self, status: str, **extra) -> None:
        obs.exporters.write_heartbeat(
            self.heartbeat_path, status=status,
            role="serving-supervisor",
            mode="reuseport" if self.reuseport else "proxy",
            port=self.port,
            telemetry_port=(self._telemetry.port
                            if self._telemetry else None),
            desired_replicas=self._desired,
            replicas=[{
                "index": r.index,
                "pid": r.proc.pid if r.proc is not None else None,
                "port": r.port,
                "alive": r.alive,
                "restarts": r.restarts,
                "draining": r.draining,
                "heartbeat_file": r.heartbeat_path,
            } for r in list(self.replicas)], **extra)

    # -------------------------------------------------------- telemetry

    def merged_metrics(self) -> str:
        """Fleet-accurate /metrics: every replica's latest snapshot file
        parsed and merged (counters/histograms summed, gauges labeled
        replica="<i>"), plus the supervisor's own registry as
        replica="supervisor" — fixes the reuseport one-replica-scrape
        gap (README "Telemetry")."""
        from code2vec_tpu.serving import telemetry
        snapshots = {}
        for replica in list(self.replicas):
            if not replica.metrics_path:
                continue
            try:
                # errors="replace": a corrupt byte must surface as an
                # unparsable (skip-and-count) snapshot, not a
                # UnicodeDecodeError 500ing the scrape
                with open(replica.metrics_path,
                          errors="replace") as f:
                    text = f.read()
            except OSError:
                continue  # not written yet / replica restarting
            try:
                families = telemetry.parse_prometheus_text(text)
            except Exception:  # noqa: BLE001 — a torn snapshot must
                # not 500 the whole scrape
                families = None
            if not families:
                if text.strip():
                    # torn / mid-rewrite / foreign garbage:
                    # skip-and-count this replica, serve the others'
                    # truth (pinned in tests/test_telemetry.py)
                    _c_snapshot_skipped(replica.index).inc()
                continue  # empty file = not written yet, no count
            # already-parsed families: the merge accepts them as-is,
            # so validation does not buy a second parse per scrape
            snapshots[str(replica.index)] = families
        snapshots["supervisor"] = \
            obs.default_registry().render_prometheus()
        return telemetry.merge_prometheus_snapshots(snapshots)

    def fleet_view(self) -> dict:
        """GET /fleet: the signal set the ROADMAP fleet item consumes —
        per-replica liveness, heartbeat staleness, breaker state, shed
        rate, restart count and model fingerprint, from the heartbeats
        the supervisor already monitors."""
        from code2vec_tpu.serving import telemetry
        now = time.time()
        replicas = [dict(
            telemetry.fleet_replica_view(r.heartbeat(), now),
            index=r.index,
            pid=r.proc.pid if r.proc is not None else None,
            port=r.port,
            alive=r.alive,
            restarts=r.restarts,
            draining=r.draining,
            in_backoff=r.restart_at is not None,
        ) for r in list(self.replicas)]
        return {
            "mode": "reuseport" if self.reuseport else "proxy",
            "port": self.port,
            "telemetry_port": (self._telemetry.port
                               if self._telemetry else None),
            "replica_count": len(replicas),
            "desired_replicas": self._desired,
            "escalated": self._escalated,
            "stale_after_s": self._stale_after(),
            # the host's fingerprint window: >1 entry = a swap is in
            # flight (or wedged) on this host — the fleet swap driver
            # polls this for convergence
            "fingerprints": sorted({r["model_fingerprint"]
                                    for r in replicas
                                    if r["model_fingerprint"]}),
            "last_reload": self._last_reload,
            "replicas": replicas,
        }

    def _resolve_telemetry_port(self) -> int:
        configured = getattr(self.config, "serve_telemetry_port", None)
        if configured is not None:
            return int(configured)
        # default: the public port + 1 — a deterministic scrape address
        # next to the service (0 below falls back to a free port when
        # the public port was itself dynamic)
        return self.port + 1 if self.port else 0

    def _start_telemetry(self) -> None:
        from code2vec_tpu.serving.telemetry import TelemetryServer
        explicit = getattr(self.config, "serve_telemetry_port",
                           None) is not None
        port = self._resolve_telemetry_port()
        # the control-plane verbs ride the telemetry listener: one port
        # per host is both the scrape address and the fleet control
        # address (serving/fleet/control.py drives these)
        post_handlers = {"/admin/scale": self._admin_scale,
                         "/admin/reload": self._admin_reload}
        try:
            self._telemetry = TelemetryServer(
                self.merged_metrics, self.fleet_view,
                host=self.config.serve_host, port=port,
                post_handlers=post_handlers)
        except OSError as e:
            if explicit or port == 0:
                # an operator-pinned scrape address that cannot bind is
                # a startup error (like the public port) — a silent
                # fallback would leave Prometheus scraping the wrong
                # process while the fleet reports healthy
                raise
            self.log(f"Telemetry port {port} (public port + 1 default) "
                     f"unavailable ({e}); binding a free port instead")
            self._telemetry = TelemetryServer(
                self.merged_metrics, self.fleet_view,
                host=self.config.serve_host, port=0,
                post_handlers=post_handlers)
        self.log(f"Fleet telemetry on http://{self.config.serve_host}:"
                 f"{self._telemetry.port} (GET /metrics merged across "
                 f"replicas, GET /fleet, POST /admin/scale, "
                 f"POST /admin/reload)")

    # ------------------------------------------------------------ proxy

    def _live_ports(self) -> List[int]:
        # draining (scale-down) replicas stop receiving new work; they
        # only finish what they already hold
        return [r.port for r in list(self.replicas)
                if r.alive and r.port is not None and not r.draining]

    def _start_proxy(self) -> None:
        import http.server

        sup = self

        class ProxyHandler(http.server.BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *args):
                pass

            def _reply(self, code, body, headers=None,
                       ctype="application/json"):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def _forward(self, method: str) -> None:
                length = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(length) if length else b""
                # proxy-generated terminal statuses carry trace ids
                # too: the correlation contract holds even when no
                # replica ever saw the request
                trace = RequestTrace.from_headers(
                    self.headers.get("traceparent"))
                # the proxy span opens BEFORE the traceparent is
                # re-serialized for the replica: the parent id handed
                # downstream must name a span this process records, or
                # the stitched trace breaks at the host hop
                with trace.span(f"host.proxy {self.path}") as px_span:
                    self._forward_in_span(method, body, trace, px_span)

            def _forward_in_span(self, method, body, trace,
                                 px_span) -> None:
                trace_headers = {"X-Trace-Id": trace.trace_id,
                                 "traceparent": trace.traceparent()}
                deadline = deadline_from_request(
                    sup.config, self.headers.get("X-Deadline-Ms"))
                fwd_headers = {"traceparent": trace.traceparent()}
                for name in REQUEST_FORWARD_HEADERS:
                    if self.headers.get(name):
                        fwd_headers[name] = self.headers[name]
                ports = sup._live_ports()
                if not ports:
                    px_span.attrs["outcome"] = "no_replica"
                    self._reply(503, json.dumps(
                        {"error": "no live replica",
                         "trace_id": trace.trace_id}).encode() + b"\n",
                        dict(trace_headers, **{
                            "Retry-After": str(retry_after_seconds(
                                1.0))}))
                    return
                with sup._rr_lock:
                    start = sup._rr_next
                    sup._rr_next += 1
                # Round-robin order, then the SAME deadline-bounded
                # forward/retry loop the fleet router runs
                # (serving/forwarding.py): this proxy is its
                # single-host degenerate case.
                ordered = [ports[(start + k) % len(ports)]
                           for k in range(len(ports))]
                forward_with_retry(
                    method=method, path=self.path, body=body,
                    fwd_headers=fwd_headers,
                    targets=[(f"replica:{port}", sup.config.serve_host,
                              port) for port in ordered],
                    deadline=deadline, trace=trace,
                    reply=self._reply,
                    what="replicas",
                    unreachable_error="all replicas unreachable",
                    retry_after=str(retry_after_seconds(1.0)),
                    on_outcome=lambda outcome: px_span.attrs.update(
                        outcome=outcome))

            def do_GET(self):  # noqa: N802
                # fleet views are answered HERE, not forwarded: a
                # round-robined /metrics would sample one replica —
                # the exact gap the merged endpoint exists to fix
                path = self.path.split("?", 1)[0]
                if path in ("/metrics", "/fleet"):
                    try:
                        if path == "/metrics":
                            self._reply_raw(
                                200, sup.merged_metrics().encode(),
                                "text/plain; version=0.0.4; "
                                "charset=utf-8")
                        else:
                            self._reply(200, json.dumps(
                                sup.fleet_view(),
                                sort_keys=True).encode() + b"\n")
                    except Exception as e:  # noqa: BLE001 — a scraper
                        # must get an HTTP error, never a torn
                        # connection
                        self._reply(500, json.dumps(
                            {"error": f"{type(e).__name__}: {e}"}
                        ).encode() + b"\n")
                    return
                self._forward("GET")

            def _reply_raw(self, code, body, ctype):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):  # noqa: N802
                # fleet control verbs are answered by the SUPERVISOR:
                # a round-robined /admin/reload would reach ONE replica
                # — the exact gap reload_all exists to fix
                path = self.path.split("?", 1)[0]
                if path in ("/admin/scale", "/admin/reload"):
                    self._admin(path)
                    return
                self._forward("POST")

            def _admin(self, path: str) -> None:
                handle_admin_post(
                    self,
                    (sup._admin_scale if path == "/admin/scale"
                     else sup._admin_reload),
                    lambda code, out: self._reply(code, json.dumps(
                        out, sort_keys=True).encode() + b"\n"))

        class _ProxyServer(http.server.ThreadingHTTPServer):
            # match the replica listeners: a burst must not be refused
            # at the kernel before the proxy can route or 503 it
            request_queue_size = 128

        proxy = _ProxyServer(
            (self.config.serve_host, self.port), ProxyHandler)
        proxy.daemon_threads = True
        self.port = proxy.server_address[1]
        self._proxy = proxy
        threading.Thread(target=proxy.serve_forever,
                         name="serving-supervisor-proxy",
                         daemon=True).start()
        self.log(f"Supervisor proxy on "
                 f"http://{self.config.serve_host}:{self.port} "
                 f"(round-robin over {self.n} replicas)")

    # -------------------------------------------------------------- run

    def run(self) -> int:
        installed = threading.current_thread() is threading.main_thread()
        prev = {}
        if installed:
            for sig in (signal.SIGTERM, signal.SIGINT):
                prev[sig] = signal.signal(
                    sig, lambda s, f: self._stop.set())
            if hasattr(signal, "SIGHUP"):
                # fan a reload out to EVERY replica: in reuseport mode
                # POST /admin/reload reaches whichever replica the
                # kernel hands the connection to, so the supervisor is
                # the one address that can drive a fleet-wide hot-swap
                prev[signal.SIGHUP] = signal.signal(
                    signal.SIGHUP, lambda s, f: self._fan_out_sighup())
        try:
            return self._run_inner()
        finally:
            for sig, handler in prev.items():
                signal.signal(sig, handler)

    def _run_inner(self) -> int:
        if not self.reuseport:
            self._start_proxy()
        self._start_telemetry()
        mode = "SO_REUSEPORT" if self.reuseport else "proxy"
        self.log(f"Serving supervisor: {self.n} replica(s), {mode} on "
                 f"port {self.port}, restart budget "
                 f"{self.config.serve_max_restarts}/replica")
        for replica in self.replicas:
            self._spawn(replica)
        self._write_heartbeat("supervising")
        last_hb = time.monotonic()
        last_warmth = time.monotonic()
        last_trace = time.monotonic()
        try:
            while not self._stop.is_set():
                # liveness pipes double as the wakeup: a dying replica
                # EOFs its pipe and the select returns immediately
                # instead of waiting out the poll tick
                fds = [r.pipe_r for r in self.replicas
                       if r.pipe_r is not None]
                try:
                    select.select(fds, [], [], 0.2)
                except (OSError, ValueError):
                    pass
                now = time.monotonic()
                self._reconcile_scale()
                for replica in list(self.replicas):
                    if replica.draining:
                        if (replica.proc is None
                                or replica.proc.poll() is not None):
                            self._retire(replica)
                        elif (now - replica.drain_started
                              > self.config.serve_drain_timeout_s
                              + 10.0):
                            # a scale-down drain that outlives the
                            # replica's own drain budget is wedged
                            self._kill(replica)
                        continue
                    if (replica.restart_at is not None
                            and now >= replica.restart_at):
                        self._spawn(replica)
                        continue
                    why = self._check_replica(replica, now)
                    if why is not None:
                        if not self._handle_failure(replica, why):
                            self._escalated = True
                            self._stop.set()
                            break
                if now - last_warmth >= _WARMTH_WINDOW_S:
                    self._sample_warmth_baselines()
                    last_warmth = now
                if now - last_hb >= 1.0:
                    self._write_heartbeat("supervising")
                    last_hb = now
                if (self.trace_export_path and now - last_trace >= 5.0
                        and len(obs.default_tracer())):
                    # periodic (not per-request) export: the stitcher
                    # reads files, so a crash loses at most 5s of spans
                    try:
                        obs.default_tracer().export_chrome_trace(
                            self.trace_export_path)
                    except OSError as e:
                        self.log(f"Supervisor trace export failed: {e}")
                    last_trace = now
        finally:
            rc = self._shutdown()
        return rc

    def _shutdown(self) -> int:
        escalated = self._escalated
        self.log("Supervisor shutdown: "
                 + ("restart budget exhausted — killing replicas"
                    if escalated else
                    "fanning SIGTERM out as a coordinated drain"))
        for replica in self.replicas:
            self._kill(replica,
                       signal.SIGKILL if escalated else signal.SIGTERM)
        budget = self.config.serve_drain_timeout_s + 15.0
        deadline = time.monotonic() + budget
        clean = not escalated
        for replica in self.replicas:
            if replica.proc is None:
                continue
            if replica.restart_at is not None:
                # already dead and reaped, waiting out its restart
                # backoff: its stale crash rc is not a DRAIN failure
                # (the crash was handled by the restart policy)
                continue
            try:
                rc = replica.proc.wait(
                    timeout=max(deadline - time.monotonic(), 0.1))
            except subprocess.TimeoutExpired:
                self._kill(replica)
                replica.proc.wait()
                rc = replica.proc.returncode
            if rc == -signal.SIGTERM and replica.heartbeat() is None:
                # the drain SIGTERM landed on a replica still STARTING
                # (no heartbeat yet => no signal handlers, no traffic
                # served, nothing in flight): the default-disposition
                # kill is a clean outcome, not a failed drain
                self.log(f"Replica {replica.index} was still starting "
                         f"at drain; terminated clean")
            elif rc != 0:
                clean = False
                self.log(f"Replica {replica.index} exited rc={rc}")
            if replica.pipe_r is not None:
                try:
                    os.close(replica.pipe_r)
                except OSError:
                    pass
                replica.pipe_r = None
        if self._proxy is not None:
            try:
                self._proxy.shutdown()
                self._proxy.server_close()
            except Exception:
                pass
        if self._telemetry is not None:
            self._telemetry.close()
        if self.trace_export_path and len(obs.default_tracer()):
            try:
                obs.default_tracer().export_chrome_trace(
                    self.trace_export_path)
            except OSError:
                pass  # exiting anyway; the periodic export is recent
        self._write_heartbeat(
            "error" if (escalated or not clean) else "done",
            escalated=escalated)
        self.log(f"Supervisor exit: "
                 f"{'clean' if clean and not escalated else 'FAILED'}")
        return 0 if clean and not escalated else 1


def supervisor_main(config, argv: Optional[List[str]] = None,
                    child_command: Optional[List[str]] = None) -> int:
    """`serve --replicas N` parent body (cli.main dispatches here
    BEFORE building any model). `child_command` overrides the re-exec
    command — the chaos suite points it at a lightweight replica
    driver; production re-execs this CLI."""
    return Supervisor(config, argv=argv,
                      child_command=child_command).run()
