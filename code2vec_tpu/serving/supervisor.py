"""Supervised multi-replica serving: `serve --replicas N`.

One serving process is one failure domain: an OOM kill, a wedged device
call or a poisoned request takes the whole service down until an
operator notices. The supervisor turns the single-process server into a
self-healing N-replica service:

- **Fork**: the parent never builds a model. It re-execs N copies of
  its own command (``--replicas`` stripped, ``C2V_SERVE_REPLICA=<i>``
  set so a replica can never recurse into supervising), each a full
  single-model server with its own extractor pool and cache.
- **Share the port**: every replica binds the SAME listen port with
  ``SO_REUSEPORT`` (the kernel load-balances accepted connections).
  Where the platform lacks it — or when ``C2V_SERVE_FORCE_PROXY=1``
  forces the fallback, which the chaos suite uses for deterministic
  routing — replicas bind free ports and the supervisor runs its own
  lightweight round-robin HTTP proxy on the public port, skipping dead
  replicas and retrying the next one on connection failure.
- **Monitor**: each replica writes the PR-2 JSON heartbeat
  (``--heartbeat_file``, rewritten every serve_heartbeat_interval_s)
  and inherits a liveness pipe. A replica whose process exits is
  CRASHED; one whose heartbeat goes ~3 intervals stale is HUNG (killed,
  then treated as crashed). Either is restarted with exponential
  backoff, up to ``--serve_max_restarts`` restarts per replica — after
  which the supervisor ESCALATES: kills everything and exits nonzero
  (a replica that cannot stay up is a deploy problem, and pretending
  otherwise hides it from the rollout system).
- **Drain**: SIGTERM to the supervisor fans out as SIGTERM to every
  replica (each runs its own in-flight drain bounded by
  serve_drain_timeout_s); the supervisor exits 0 only when every
  replica exited 0.
- **Fleet telemetry** (serving/telemetry.py, README "Telemetry"): each
  replica publishes an atomic Prometheus snapshot (--metrics_file,
  appended per replica below) every heartbeat interval; the supervisor
  serves the MERGE at ``GET /metrics`` on its telemetry listener
  (--serve_telemetry_port, default public port + 1) plus a
  ``GET /fleet`` JSON view (per-replica breaker state, shed rate,
  heartbeat staleness, restarts, fingerprint). This is the documented
  scrape address under reuseport — a scrape of the shared public port
  reaches ONE kernel-chosen replica and samples a random shard of the
  fleet. In proxy mode the public port answers both paths itself.
  Replica restarts are flight-recorder events and an escalation is an
  incident with a synchronous ring dump into the run dir
  (obs/flight.py).

The supervisor's own heartbeat records per-replica pid/port/restarts so
"which replica is which process" is answerable from the file alone —
the serving chaos suite (tests/test_serving_chaos.py) reads it to pick
a SIGKILL victim and to assert convergence back to N live replicas.
"""

from __future__ import annotations

import json
import os
import select
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time
from typing import Dict, List, Optional

from code2vec_tpu import obs

REPLICA_ENV = "C2V_SERVE_REPLICA"
FORCE_PROXY_ENV = "C2V_SERVE_FORCE_PROXY"
# Seconds a replica gets from spawn to its first heartbeat before the
# supervisor declares a hung STARTUP (model build + jit warmup can
# legitimately take tens of seconds on a cold replica).
STARTUP_GRACE_S = 120.0

_C_RESTARTS = obs.counter(
    "serving_replica_restarts_total",
    "replica processes restarted by the serving supervisor "
    "(crash or stale heartbeat)")


def strip_flag(argv: List[str], flag: str,
               has_value: bool = True) -> List[str]:
    """Remove every occurrence of `flag` (and its value, both
    `--flag V` and `--flag=V` forms) from an argv list."""
    out: List[str] = []
    skip = False
    for arg in argv:
        if skip:
            skip = False
            continue
        if arg == flag:
            skip = has_value
            continue
        if has_value and arg.startswith(flag + "="):
            continue
        out.append(arg)
    return out


def _free_port(host: str) -> int:
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind((host, 0))
        return s.getsockname()[1]


class _Replica:
    def __init__(self, index: int, heartbeat_path: str, log_path: str,
                 metrics_path: Optional[str] = None):
        self.index = index
        self.heartbeat_path = heartbeat_path
        self.log_path = log_path
        self.metrics_path = metrics_path
        self.proc: Optional[subprocess.Popen] = None
        self.pipe_r: Optional[int] = None
        self.port: Optional[int] = None
        self.restarts = 0
        self.spawned_at = 0.0
        self.restart_at: Optional[float] = None  # backoff gate

    @property
    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    def heartbeat(self) -> Optional[dict]:
        try:
            with open(self.heartbeat_path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None


class Supervisor:
    """Owns N replica processes + (in proxy mode) the public listener."""

    def __init__(self, config, argv: Optional[List[str]] = None,
                 child_command: Optional[List[str]] = None):
        self.config = config
        self.log = config.log
        self.n = int(config.serve_replicas)
        if child_command is not None:
            self.child_command = list(child_command)
        else:
            stripped = strip_flag(list(argv or []), "--replicas")
            # each replica gets its OWN --metrics_file (the fleet
            # telemetry feed) and --trace_export — a user-supplied path
            # would have every replica overwrite the same file (the
            # atomic tmp+rename makes the clobber silent: last replica
            # to exit wins)
            stripped = strip_flag(stripped, "--metrics_file")
            stripped = strip_flag(stripped, "--trace_export")
            self.child_command = ([sys.executable, "-m",
                                   "code2vec_tpu.cli"] + stripped)
        self.trace_export = bool(getattr(config, "trace_export", None))
        base = (os.path.dirname(os.path.abspath(config.heartbeat_file))
                if config.heartbeat_file else None)
        self.run_dir = base or tempfile.mkdtemp(prefix="c2v-serve-sup-")
        os.makedirs(self.run_dir, exist_ok=True)
        self.heartbeat_path = (config.heartbeat_file or os.path.join(
            self.run_dir, "supervisor.heartbeat.json"))
        self.reuseport = (hasattr(socket, "SO_REUSEPORT")
                          and os.environ.get(FORCE_PROXY_ENV) != "1")
        self.port = int(config.serve_port)
        if self.reuseport and self.port == 0:
            # replicas must all bind ONE concrete port; resolve now
            self.port = _free_port(config.serve_host)
        self.replicas = [
            _Replica(i,
                     os.path.join(self.run_dir,
                                  f"replica{i}.heartbeat.json"),
                     os.path.join(self.run_dir, f"replica{i}.log"),
                     os.path.join(self.run_dir,
                                  f"replica{i}.metrics.prom"))
            for i in range(self.n)]
        self._stop = threading.Event()
        self._escalated = False
        self._proxy = None
        self._rr_lock = threading.Lock()
        self._rr_next = 0
        self._telemetry = None
        # Supervisor-side flight recorder: replica restarts are anomaly
        # events, an escalation is an incident with a synchronous dump
        # into the run dir (the replicas' own dumps land there too when
        # --heartbeat_file puts their run files in one place).
        self.flight = obs.default_flight_recorder()
        self.flight.configure(dump_dir=self.run_dir, log=self.log)

    # ------------------------------------------------------------ spawn

    def _spawn(self, replica: _Replica) -> None:
        try:
            os.remove(replica.heartbeat_path)
        except OSError:
            pass
        replica.port = None
        cmd = list(self.child_command)
        cmd += ["--heartbeat_file", replica.heartbeat_path]
        if replica.metrics_path:
            # the replica's fleet-telemetry feed: an atomic Prometheus
            # snapshot rewritten every heartbeat interval, merged by
            # the supervisor's /metrics + /fleet (serving/telemetry.py).
            # A restarted replica's counters restart from zero — the
            # stale pre-crash file would double-count, so drop it.
            try:
                os.remove(replica.metrics_path)
            except OSError:
                pass
            cmd += ["--metrics_file", replica.metrics_path]
        if self.trace_export:
            cmd += ["--trace_export",
                    os.path.join(self.run_dir,
                                 f"replica{replica.index}.trace.json")]
        env = dict(os.environ)
        env[REPLICA_ENV] = str(replica.index)
        if self.reuseport:
            cmd += ["--serve_port", str(self.port)]
            env["C2V_SERVE_REUSEPORT"] = "1"
            replica.port = self.port
        else:
            cmd += ["--serve_port", "0"]  # report via heartbeat
            env.pop("C2V_SERVE_REUSEPORT", None)
        r, w = os.pipe()  # liveness pipe: EOF = replica gone
        os.set_inheritable(w, True)
        logf = open(replica.log_path, "ab")
        try:
            replica.proc = subprocess.Popen(
                cmd, env=env, pass_fds=(w,), stdout=logf, stderr=logf)
        finally:
            logf.close()
            os.close(w)
        if replica.pipe_r is not None:
            try:
                os.close(replica.pipe_r)
            except OSError:
                pass
        replica.pipe_r = r
        replica.spawned_at = time.monotonic()
        replica.restart_at = None
        self.log(f"Replica {replica.index} spawned "
                 f"(pid {replica.proc.pid}"
                 f"{f', port {replica.port}' if replica.port else ''})")

    def _kill(self, replica: _Replica, sig=signal.SIGKILL) -> None:
        if replica.proc is not None and replica.proc.poll() is None:
            try:
                replica.proc.send_signal(sig)
            except OSError:
                pass

    def _fan_out_sighup(self) -> None:
        self.log("SIGHUP: fanning reload out to all replicas")
        for replica in self.replicas:
            self._kill(replica, signal.SIGHUP)

    # ---------------------------------------------------------- monitor

    def _stale_after(self) -> float:
        return 3.0 * self.config.serve_heartbeat_interval_s + 2.0

    def _check_replica(self, replica: _Replica, now: float
                       ) -> Optional[str]:
        """Returns a failure description or None (healthy/waiting)."""
        if replica.restart_at is not None:
            return None  # in backoff; spawned when due
        if replica.proc is None:
            return None
        rc = replica.proc.poll()
        if rc is not None:
            return f"exited rc={rc}"
        hb = replica.heartbeat()
        if hb is None:
            if now - replica.spawned_at > STARTUP_GRACE_S:
                self._kill(replica)
                return (f"no heartbeat within the "
                        f"{STARTUP_GRACE_S:g}s startup grace (hung "
                        f"startup; killed)")
            return None
        if replica.port is None:
            port = hb.get("port")
            if port:
                replica.port = int(port)
                self.log(f"Replica {replica.index} listening on port "
                         f"{replica.port}")
        age = time.time() - float(hb.get("wall_time", 0))
        if age > self._stale_after():
            self._kill(replica)
            return (f"heartbeat stale ({age:.1f}s > "
                    f"{self._stale_after():.1f}s; hung; killed)")
        return None

    def _handle_failure(self, replica: _Replica, why: str) -> bool:
        """Schedule a backoff restart; False when the budget is
        exhausted (escalate)."""
        if replica.proc is not None:
            replica.proc.wait()  # reap
        if replica.pipe_r is not None:
            # drop the dead replica's liveness pipe from the monitor's
            # select set NOW: at EOF it is permanently readable, and
            # leaving it in would busy-spin the loop for the whole
            # backoff window
            try:
                os.close(replica.pipe_r)
            except OSError:
                pass
            replica.pipe_r = None
        if replica.restarts >= self.config.serve_max_restarts:
            self.log(f"Replica {replica.index} {why}; restart budget "
                     f"({self.config.serve_max_restarts}) exhausted — "
                     f"escalating to supervisor exit")
            self.flight.incident(
                "replica_escalation", immediate=True,
                replica=replica.index, why=why,
                restarts=replica.restarts)
            return False
        replica.restarts += 1
        _C_RESTARTS.inc()
        self.flight.event("replica_restart", replica=replica.index,
                          why=why, restart=replica.restarts)
        backoff = min(0.5 * (2 ** (replica.restarts - 1)), 10.0)
        replica.restart_at = time.monotonic() + backoff
        self.log(f"Replica {replica.index} {why}; restart "
                 f"{replica.restarts}/{self.config.serve_max_restarts} "
                 f"in {backoff:.1f}s")
        return True

    def _write_heartbeat(self, status: str, **extra) -> None:
        obs.exporters.write_heartbeat(
            self.heartbeat_path, status=status,
            role="serving-supervisor",
            mode="reuseport" if self.reuseport else "proxy",
            port=self.port,
            telemetry_port=(self._telemetry.port
                            if self._telemetry else None),
            replicas=[{
                "index": r.index,
                "pid": r.proc.pid if r.proc is not None else None,
                "port": r.port,
                "alive": r.alive,
                "restarts": r.restarts,
                "heartbeat_file": r.heartbeat_path,
            } for r in self.replicas], **extra)

    # -------------------------------------------------------- telemetry

    def merged_metrics(self) -> str:
        """Fleet-accurate /metrics: every replica's latest snapshot file
        parsed and merged (counters/histograms summed, gauges labeled
        replica="<i>"), plus the supervisor's own registry as
        replica="supervisor" — fixes the reuseport one-replica-scrape
        gap (README "Telemetry")."""
        from code2vec_tpu.serving import telemetry
        snapshots = {}
        for replica in self.replicas:
            if not replica.metrics_path:
                continue
            try:
                with open(replica.metrics_path) as f:
                    snapshots[str(replica.index)] = f.read()
            except OSError:
                continue  # not written yet / replica restarting
        snapshots["supervisor"] = \
            obs.default_registry().render_prometheus()
        return telemetry.merge_prometheus_snapshots(snapshots)

    def fleet_view(self) -> dict:
        """GET /fleet: the signal set the ROADMAP fleet item consumes —
        per-replica liveness, heartbeat staleness, breaker state, shed
        rate, restart count and model fingerprint, from the heartbeats
        the supervisor already monitors."""
        from code2vec_tpu.serving import telemetry
        now = time.time()
        return {
            "mode": "reuseport" if self.reuseport else "proxy",
            "port": self.port,
            "telemetry_port": (self._telemetry.port
                               if self._telemetry else None),
            "replica_count": self.n,
            "escalated": self._escalated,
            "stale_after_s": self._stale_after(),
            "replicas": [dict(
                telemetry.fleet_replica_view(r.heartbeat(), now),
                index=r.index,
                pid=r.proc.pid if r.proc is not None else None,
                port=r.port,
                alive=r.alive,
                restarts=r.restarts,
                in_backoff=r.restart_at is not None,
            ) for r in self.replicas],
        }

    def _resolve_telemetry_port(self) -> int:
        configured = getattr(self.config, "serve_telemetry_port", None)
        if configured is not None:
            return int(configured)
        # default: the public port + 1 — a deterministic scrape address
        # next to the service (0 below falls back to a free port when
        # the public port was itself dynamic)
        return self.port + 1 if self.port else 0

    def _start_telemetry(self) -> None:
        from code2vec_tpu.serving.telemetry import TelemetryServer
        explicit = getattr(self.config, "serve_telemetry_port",
                           None) is not None
        port = self._resolve_telemetry_port()
        try:
            self._telemetry = TelemetryServer(
                self.merged_metrics, self.fleet_view,
                host=self.config.serve_host, port=port)
        except OSError as e:
            if explicit or port == 0:
                # an operator-pinned scrape address that cannot bind is
                # a startup error (like the public port) — a silent
                # fallback would leave Prometheus scraping the wrong
                # process while the fleet reports healthy
                raise
            self.log(f"Telemetry port {port} (public port + 1 default) "
                     f"unavailable ({e}); binding a free port instead")
            self._telemetry = TelemetryServer(
                self.merged_metrics, self.fleet_view,
                host=self.config.serve_host, port=0)
        self.log(f"Fleet telemetry on http://{self.config.serve_host}:"
                 f"{self._telemetry.port} (GET /metrics merged across "
                 f"replicas, GET /fleet)")

    # ------------------------------------------------------------ proxy

    def _live_ports(self) -> List[int]:
        return [r.port for r in self.replicas
                if r.alive and r.port is not None]

    def _start_proxy(self) -> None:
        import http.server

        sup = self

        class ProxyHandler(http.server.BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *args):
                pass

            def _reply(self, code, body, headers=None):
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def _forward(self, method: str) -> None:
                import http.client
                length = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(length) if length else b""
                fwd_headers = {}
                for name in ("Content-Type", "X-Deadline-Ms",
                             "traceparent"):
                    if self.headers.get(name):
                        fwd_headers[name] = self.headers[name]
                ports = sup._live_ports()
                if not ports:
                    self._reply(503, json.dumps(
                        {"error": "no live replica"}).encode() + b"\n",
                        {"Retry-After": "1"})
                    return
                with sup._rr_lock:
                    start = sup._rr_next
                    sup._rr_next += 1
                last_err = None
                for k in range(len(ports)):
                    port = ports[(start + k) % len(ports)]
                    try:
                        conn = http.client.HTTPConnection(
                            sup.config.serve_host, port, timeout=300)
                        try:
                            conn.request(method, self.path, body=body,
                                         headers=fwd_headers)
                            resp = conn.getresponse()
                            payload = resp.read()
                            headers = {}
                            # trace headers ride back through the
                            # proxy: the id must reach the client on
                            # EVERY terminal status or proxy mode
                            # breaks the correlation contract
                            for name in ("Retry-After", "X-Trace-Id",
                                         "traceparent"):
                                if resp.getheader(name):
                                    headers[name] = resp.getheader(name)
                            ctype = resp.getheader(
                                "Content-Type", "application/json")
                            self.send_response(resp.status)
                            self.send_header("Content-Type", ctype)
                            self.send_header("Content-Length",
                                             str(len(payload)))
                            for hk, hv in headers.items():
                                self.send_header(hk, hv)
                            self.end_headers()
                            self.wfile.write(payload)
                            return
                        finally:
                            conn.close()
                    except OSError as e:
                        # dead/draining replica: honest retry on the
                        # next one — the client never sees a torn or
                        # corrupt response from a killed replica
                        last_err = e
                        continue
                self._reply(503, json.dumps(
                    {"error": f"all replicas unreachable "
                              f"({last_err})"}).encode() + b"\n",
                    {"Retry-After": "1"})

            def do_GET(self):  # noqa: N802
                # fleet views are answered HERE, not forwarded: a
                # round-robined /metrics would sample one replica —
                # the exact gap the merged endpoint exists to fix
                path = self.path.split("?", 1)[0]
                if path in ("/metrics", "/fleet"):
                    try:
                        if path == "/metrics":
                            self._reply_raw(
                                200, sup.merged_metrics().encode(),
                                "text/plain; version=0.0.4; "
                                "charset=utf-8")
                        else:
                            self._reply(200, json.dumps(
                                sup.fleet_view(),
                                sort_keys=True).encode() + b"\n")
                    except Exception as e:  # noqa: BLE001 — a scraper
                        # must get an HTTP error, never a torn
                        # connection
                        self._reply(500, json.dumps(
                            {"error": f"{type(e).__name__}: {e}"}
                        ).encode() + b"\n")
                    return
                self._forward("GET")

            def _reply_raw(self, code, body, ctype):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):  # noqa: N802
                self._forward("POST")

        class _ProxyServer(http.server.ThreadingHTTPServer):
            # match the replica listeners: a burst must not be refused
            # at the kernel before the proxy can route or 503 it
            request_queue_size = 128

        proxy = _ProxyServer(
            (self.config.serve_host, self.port), ProxyHandler)
        proxy.daemon_threads = True
        self.port = proxy.server_address[1]
        self._proxy = proxy
        threading.Thread(target=proxy.serve_forever,
                         name="serving-supervisor-proxy",
                         daemon=True).start()
        self.log(f"Supervisor proxy on "
                 f"http://{self.config.serve_host}:{self.port} "
                 f"(round-robin over {self.n} replicas)")

    # -------------------------------------------------------------- run

    def run(self) -> int:
        installed = threading.current_thread() is threading.main_thread()
        prev = {}
        if installed:
            for sig in (signal.SIGTERM, signal.SIGINT):
                prev[sig] = signal.signal(
                    sig, lambda s, f: self._stop.set())
            if hasattr(signal, "SIGHUP"):
                # fan a reload out to EVERY replica: in reuseport mode
                # POST /admin/reload reaches whichever replica the
                # kernel hands the connection to, so the supervisor is
                # the one address that can drive a fleet-wide hot-swap
                prev[signal.SIGHUP] = signal.signal(
                    signal.SIGHUP, lambda s, f: self._fan_out_sighup())
        try:
            return self._run_inner()
        finally:
            for sig, handler in prev.items():
                signal.signal(sig, handler)

    def _run_inner(self) -> int:
        if not self.reuseport:
            self._start_proxy()
        self._start_telemetry()
        mode = "SO_REUSEPORT" if self.reuseport else "proxy"
        self.log(f"Serving supervisor: {self.n} replica(s), {mode} on "
                 f"port {self.port}, restart budget "
                 f"{self.config.serve_max_restarts}/replica")
        for replica in self.replicas:
            self._spawn(replica)
        self._write_heartbeat("supervising")
        last_hb = time.monotonic()
        try:
            while not self._stop.is_set():
                # liveness pipes double as the wakeup: a dying replica
                # EOFs its pipe and the select returns immediately
                # instead of waiting out the poll tick
                fds = [r.pipe_r for r in self.replicas
                       if r.pipe_r is not None]
                try:
                    select.select(fds, [], [], 0.2)
                except (OSError, ValueError):
                    pass
                now = time.monotonic()
                for replica in self.replicas:
                    if (replica.restart_at is not None
                            and now >= replica.restart_at):
                        self._spawn(replica)
                        continue
                    why = self._check_replica(replica, now)
                    if why is not None:
                        if not self._handle_failure(replica, why):
                            self._escalated = True
                            self._stop.set()
                            break
                if now - last_hb >= 1.0:
                    self._write_heartbeat("supervising")
                    last_hb = now
        finally:
            rc = self._shutdown()
        return rc

    def _shutdown(self) -> int:
        escalated = self._escalated
        self.log("Supervisor shutdown: "
                 + ("restart budget exhausted — killing replicas"
                    if escalated else
                    "fanning SIGTERM out as a coordinated drain"))
        for replica in self.replicas:
            self._kill(replica,
                       signal.SIGKILL if escalated else signal.SIGTERM)
        budget = self.config.serve_drain_timeout_s + 15.0
        deadline = time.monotonic() + budget
        clean = not escalated
        for replica in self.replicas:
            if replica.proc is None:
                continue
            if replica.restart_at is not None:
                # already dead and reaped, waiting out its restart
                # backoff: its stale crash rc is not a DRAIN failure
                # (the crash was handled by the restart policy)
                continue
            try:
                rc = replica.proc.wait(
                    timeout=max(deadline - time.monotonic(), 0.1))
            except subprocess.TimeoutExpired:
                self._kill(replica)
                replica.proc.wait()
                rc = replica.proc.returncode
            if rc != 0:
                clean = False
                self.log(f"Replica {replica.index} exited rc={rc}")
            if replica.pipe_r is not None:
                try:
                    os.close(replica.pipe_r)
                except OSError:
                    pass
                replica.pipe_r = None
        if self._proxy is not None:
            try:
                self._proxy.shutdown()
                self._proxy.server_close()
            except Exception:
                pass
        if self._telemetry is not None:
            self._telemetry.close()
        self._write_heartbeat(
            "error" if (escalated or not clean) else "done",
            escalated=escalated)
        self.log(f"Supervisor exit: "
                 f"{'clean' if clean and not escalated else 'FAILED'}")
        return 0 if clean and not escalated else 1


def supervisor_main(config, argv: Optional[List[str]] = None,
                    child_command: Optional[List[str]] = None) -> int:
    """`serve --replicas N` parent body (cli.main dispatches here
    BEFORE building any model). `child_command` overrides the re-exec
    command — the chaos suite points it at a lightweight replica
    driver; production re-execs this CLI."""
    return Supervisor(config, argv=argv,
                      child_command=child_command).run()
