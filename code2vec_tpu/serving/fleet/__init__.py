"""Cross-host serving fleet: control plane, health-gated router,
coordinated hot-swap.

The layer above `serving/supervisor.py` (one host's replica
supervisor), closing the ROADMAP "cross-host serving fleet" item:

- `control.py` — launches/adopts per-host supervisors through a
  pluggable HostLauncher, tracks health off each host's PR-12
  telemetry plane (`/fleet` + heartbeat staleness), restarts dead
  hosts with backoff, and scales each host's replica count off shed
  rate / phase p95 with hysteresis (`POST /admin/scale` to the host
  supervisor).
- `router.py` — the fleet's one public address: weighted routing away
  from hosts with open breakers or stale heartbeats,
  connection-failure retry bounded by the request's remaining
  `X-Deadline-Ms` budget, coordinated drain, multi-model routing on
  the `X-Model` header — with the 503-honesty and trace-propagation
  contracts intact end to end.
- `swap.py` — fleet-wide coordinated hot-swap: canary host first,
  halt-and-report on first failure, rollback instead of a permanently
  mixed fleet, mixed-fingerprint windows observable in `GET /fleet`.

Entry point: the `fleet` CLI subcommand (`control.fleet_main`).
README "Fleet" is the runbook.
"""

from code2vec_tpu.serving.fleet.control import (
    ControlPlane, HostLauncher, HostSpec, LocalHostLauncher,
    fleet_main, parse_fleet_models,
)
from code2vec_tpu.serving.fleet.router import FleetRouter
from code2vec_tpu.serving.fleet.swap import FleetSwapBusy, FleetSwapDriver

__all__ = [
    "ControlPlane", "FleetRouter", "FleetSwapBusy", "FleetSwapDriver",
    "HostLauncher", "HostSpec", "LocalHostLauncher", "fleet_main",
    "parse_fleet_models",
]
