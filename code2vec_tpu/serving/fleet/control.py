"""Fleet control plane: launch/adopt per-host supervisors, track their
health, scale their replica counts off the telemetry they export.

The PR-12 telemetry plane gave every host ONE endpoint carrying the
whole signal set a fleet needs (`/fleet` JSON: per-replica liveness,
heartbeat staleness, breaker state, shed counters, fingerprint, swap
state; merged `/metrics` for the phase histograms). This control plane
is the consumer that endpoint was built for:

- **Placement**: each host is a `serve --replicas N` SUPERVISOR
  process, launched through a pluggable `HostLauncher` — locally a
  subprocess (the test/chaos/dev path; the `fleet` CLI subcommand
  re-execs itself per host), remotely whatever the deployment
  substrate provides (ssh, a k8s Job, ...) as long as the host's
  heartbeat file is visible to the control plane and its ports are
  reachable. A host whose process dies is restarted with exponential
  backoff up to `--fleet_max_host_restarts`, then the control plane
  ESCALATES (exits nonzero) — the supervisor's deploy-problem
  philosophy, one level up.
- **Health**: each poll tick reads the host heartbeat (staleness) and
  its `/fleet` + `/metrics`. Health feeds the router's weights: a
  healthy host weighs 1.0; an open breaker or stale heartbeat
  down-weights to 0.1 (cache hits still serve there); dead and
  draining hosts weigh 0.
- **Scaling**: per host, per tick, over the WINDOW since the last tick
  (counters are lifetime-cumulative — lifetime rates would never show
  a regression fading): shed rate above `--fleet_scale_up_shed_rate`
  or total-phase p95 above `--fleet_scale_up_p95_ms` for
  `--fleet_scale_up_ticks` consecutive ticks scales UP one replica;
  zero requests for `--fleet_scale_down_ticks` consecutive ticks
  scales DOWN one. Bounded by `--fleet_scale_min/max`, with a
  `--fleet_scale_cooldown` after every action — hysteresis on both
  edges so a noisy signal cannot flap the replica count. Actions are
  `POST /admin/scale` to the host's supervisor.
- **Coordinated swap + drain**: `request_swap` hands off to the
  canary-first FleetSwapDriver (serving/fleet/swap.py); `drain_host`
  marks a host draining (router weight 0 — no new work), SIGTERMs its
  supervisor (which coordinates the replica drains), and retires it
  when the process exits.

- **Edge tier** (`--fleet_routers N`, N >= 2): the public address
  becomes N stateless ROUTER processes on consecutive ports (VIP
  convention documented in README "Edge"), each holding nothing but a
  polled copy of the fleet view (serving/fleet/edge.py). The embedded
  router demotes to a PRIVATE control listener the agents poll and
  relay admin verbs to. Router processes are supervised exactly like
  hosts: death or a stale heartbeat restarts them with the same
  exponential backoff, the same `--fleet_max_host_restarts` budget and
  the same escalation exit.

`fleet_main` is the `fleet` CLI subcommand body: control plane + the
health-gated router (serving/fleet/router.py) on the public port.
"""

from __future__ import annotations

import json
import os
import shlex
import signal
import subprocess
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request
from typing import Dict, List, Optional, Tuple

from code2vec_tpu import obs
from code2vec_tpu.obs import slo as obs_slo
from code2vec_tpu.obs import tsdb as obs_tsdb
from code2vec_tpu.serving import telemetry
from code2vec_tpu.serving.fleet.router import DEFAULT_MODEL, FleetRouter
from code2vec_tpu.serving.fleet.swap import FleetSwapDriver

FLEET_HOST_ENV = "C2V_FLEET_HOST"
# Router-agent child marker (cli.main dispatches on it) + the host's
# reachable address, exported for address-templated remote launchers.
FLEET_ROUTER_ENV = "C2V_FLEET_ROUTER"
FLEET_HOST_ADDRESS_ENV = "C2V_FLEET_HOST_ADDRESS"
# Seconds a host gets from spawn to its first supervisor heartbeat
# (replica fork + model build happen below it; the supervisor itself
# heartbeats within ~a second of starting).
HOST_STARTUP_GRACE_S = 120.0
# Router weight of a host with an open breaker or stale heartbeat:
# routed AWAY from, not excluded — its caches still serve and it may be
# the only capacity left standing.
UNHEALTHY_WEIGHT = 0.1

_C_HOST_RESTARTS = obs.counter(
    "fleet_host_restarts_total",
    "host supervisor processes restarted by the fleet control plane "
    "(process death or stale host heartbeat)")

_C_ROUTER_RESTARTS = obs.counter(
    "edge_router_restarts_total",
    "edge router processes restarted by the fleet control plane "
    "(process death or stale router heartbeat)")


def _g_routers(state: str):
    return obs.gauge(
        "edge_routers",
        "edge-tier router processes by state (routing | down)",
        state=state)


def _c_scale_actions(direction: str):
    return obs.counter(
        "fleet_scale_actions_total",
        "telemetry-driven per-host replica scaling actions the control "
        "plane applied (up: shed rate / p95 over threshold; down: "
        "sustained idle)",
        direction=direction)


def _g_hosts(model: str, state: str):
    return obs.gauge(
        "fleet_hosts",
        "fleet hosts by model group and health state "
        "(healthy | degraded | down | draining)",
        model=model, state=state)


_HOST_STATES = ("healthy", "degraded", "down", "draining")


def parse_fleet_models(spec: str) -> Dict[str, str]:
    """`--fleet_models name=artifact_dir,...` -> {name: dir}. Empty
    spec -> {} (single default group from --artifact/--load)."""
    out: Dict[str, str] = {}
    for entry in (spec or "").split(","):
        entry = entry.strip()
        if not entry:
            continue
        name, sep, artifact = entry.partition("=")
        name, artifact = name.strip(), artifact.strip()
        if not sep or not name or not artifact:
            raise ValueError(
                f"bad --fleet_models entry {entry!r}: expected "
                f"name=artifact_dir[,name=artifact_dir...]")
        if name in out:
            raise ValueError(
                f"duplicate model name {name!r} in --fleet_models")
        out[name] = artifact
    return out


class HostLauncher:
    """Pluggable host-process launcher — the remote seam. The contract:
    `launch` starts the host supervisor command and returns a
    process-like handle (pid, poll(), wait(), send_signal()); the
    command's `--heartbeat_file` must end up readable by the control
    plane (shared fs for remote substrates) and the ports the host
    reports in it reachable."""

    def launch(self, command: List[str], env: Dict[str, str],
               log_path: str):
        raise NotImplementedError


class LocalHostLauncher(HostLauncher):
    """Subprocess launcher: every "host" is a local process. The dev,
    test and chaos-drill substrate — and an honest single-machine
    deployment (one supervisor per NUMA domain / accelerator)."""

    def launch(self, command: List[str], env: Dict[str, str],
               log_path: str):
        logf = open(log_path, "ab")
        try:
            return subprocess.Popen(command, env=env,
                                    stdout=logf, stderr=logf)
        finally:
            logf.close()


class RemoteHostLauncher(HostLauncher):
    """Wrapper-command launcher: the remote half of the HostLauncher
    seam, good enough to demo a real multi-machine fleet from one CLI
    (`--fleet_launcher "ssh {address}"` + `--fleet_addresses a,b,...`;
    a container substrate is the same shape, e.g.
    `"docker exec {address}"`).

    `{address}` in the template is replaced by the host's reachable
    address (exported as C2V_FLEET_HOST_ADDRESS), the template is
    shlex-split into the wrapper argv, and the host command — plus the
    C2V_*/PYTHONPATH/JAX* env the fleet children need — is flattened
    into ONE `env K=V ... cmd` shell word, quoted, so it survives the
    remote shell. The handle is the local wrapper process: ssh holds
    the remote command's lifetime, so poll()/wait()/send_signal() keep
    their meaning and a failed launch (unreachable machine, rejected
    key, missing binary) surfaces as an immediate nonzero exit that
    flows down the EXISTING host_down -> backoff -> host_escalation
    incident path, never a new one.

    Contract (unchanged from the seam): the host's --heartbeat_file
    must end up readable by the control plane — run the fleet's run
    dir on a shared filesystem — and the ports it reports reachable at
    the host's address."""

    # env worth exporting across the wrapper: the fleet/replica
    # protocol markers plus interpreter/runtime selection. Everything
    # else is the REMOTE machine's business.
    _ENV_KEEP_PREFIXES = ("C2V_", "JAX_", "XLA_")
    _ENV_KEEP = ("PYTHONPATH",)

    def __init__(self, template: str):
        if not (template or "").strip():
            raise ValueError(
                "RemoteHostLauncher needs a wrapper template, e.g. "
                '"ssh {address}"')
        self.template = template

    def launch(self, command: List[str], env: Dict[str, str],
               log_path: str):
        address = env.get(FLEET_HOST_ADDRESS_ENV, "")
        wrapper = shlex.split(
            self.template.replace("{address}", address))
        keep = {k: v for k, v in env.items()
                if k in self._ENV_KEEP
                or k.startswith(self._ENV_KEEP_PREFIXES)}
        remote = " ".join(
            ["env"]
            + [f"{k}={shlex.quote(v)}" for k, v in sorted(keep.items())]
            + [shlex.quote(c) for c in command])
        logf = open(log_path, "ab")
        try:
            return subprocess.Popen(wrapper + [remote], env=env,
                                    stdout=logf, stderr=logf)
        finally:
            logf.close()


class HostSpec:
    """What to run for one host: id, model group, the supervisor
    command (WITHOUT --heartbeat_file — the control plane owns run
    files), and the address its reported ports are reachable at."""

    def __init__(self, host_id: str, command: List[str],
                 model: str = DEFAULT_MODEL,
                 address: str = "127.0.0.1",
                 boot_artifact: Optional[str] = None,
                 boot_retrieval_index: Optional[str] = None):
        self.id = host_id
        self.command = list(command)
        self.model = model
        self.address = address
        # the (artifact, retrieval_index) pair baked into `command` —
        # when the model group has since been swapped to a different
        # one, a (re)spawned host gets a reload-target file (and the
        # first-heartbeat reconcile re-checks over HTTP) so its
        # replicas converge onto the fleet's CURRENT pair instead of
        # reviving the boot one
        self.boot_artifact = boot_artifact
        self.boot_retrieval_index = boot_retrieval_index


class _Host:
    def __init__(self, spec: HostSpec, run_dir: str):
        self.spec = spec
        self.id = spec.id
        self.model = spec.model
        self.address = spec.address
        # each host gets its OWN run dir: the supervisor roots its
        # replica heartbeats/metrics/flight dumps next to its
        # heartbeat file, and two hosts sharing a dir would clobber
        # each other's replica files
        self.host_dir = os.path.join(run_dir, f"host-{spec.id}")
        os.makedirs(self.host_dir, exist_ok=True)
        self.heartbeat_path = os.path.join(
            self.host_dir, "supervisor.heartbeat.json")
        self.log_path = os.path.join(self.host_dir, "host.log")
        self.proc = None
        self.port: Optional[int] = None
        self.telemetry_port: Optional[int] = None
        self.restarts = 0
        self.restart_at: Optional[float] = None  # backoff gate
        self.spawned_at = 0.0
        self.draining = False
        self.retired = False
        self.state = "down"
        self.weight = 0.0
        self.view: Optional[dict] = None     # last /fleet JSON
        self.metrics_text: str = ""          # last /metrics text
        # scaling hysteresis state (the WINDOWS live in the control
        # plane's tsdb now — reset-aware, restart-surviving)
        self.up_ticks = 0
        self.idle_ticks = 0
        self.cooldown_until = 0.0
        self.desired_replicas: Optional[int] = None
        # set by _spawn, cleared by the first-heartbeat reconcile:
        # the control plane checks this host's reported reload state
        # against the committed (artifact, retrieval_index) pair once
        # per spawn (the reload-target file covers only locally
        # launched hosts; a remote host or a self-restarted
        # supervisor never reads it)
        self.needs_reconcile = False

    @property
    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    def heartbeat(self) -> Optional[dict]:
        try:
            with open(self.heartbeat_path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None


class RouterSpec:
    """One edge router process: id + CLI re-exec command (WITHOUT
    --heartbeat_file — the control plane owns run files)."""

    def __init__(self, router_id: str, command: List[str]):
        self.id = router_id
        self.command = list(command)


class _Router:
    def __init__(self, spec: RouterSpec, run_dir: str):
        self.spec = spec
        self.id = spec.id
        self.router_dir = os.path.join(run_dir, spec.id)
        os.makedirs(self.router_dir, exist_ok=True)
        self.heartbeat_path = os.path.join(self.router_dir,
                                           "router.heartbeat.json")
        self.log_path = os.path.join(self.router_dir, "router.log")
        self.proc = None
        self.port: Optional[int] = None
        self.restarts = 0
        self.restart_at: Optional[float] = None  # backoff gate
        self.spawned_at = 0.0
        self.state = "down"

    @property
    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    def heartbeat(self) -> Optional[dict]:
        try:
            with open(self.heartbeat_path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None


class ControlPlane:
    """Owns the host processes + their health/scaling state; the
    router consumes it through hosts_for/fleet_view/..."""

    def __init__(self, config, specs: List[HostSpec],
                 launcher: Optional[HostLauncher] = None, log=None):
        self.config = config
        self.log = log or config.log
        self.launcher = launcher or LocalHostLauncher()
        base = (os.path.dirname(os.path.abspath(config.heartbeat_file))
                if config.heartbeat_file else None)
        self.run_dir = base or tempfile.mkdtemp(prefix="c2v-fleet-")
        os.makedirs(self.run_dir, exist_ok=True)
        self.heartbeat_path = (config.heartbeat_file or os.path.join(
            self.run_dir, "fleet.heartbeat.json"))
        self.hosts = [_Host(spec, self.run_dir) for spec in specs]
        self.models = sorted({h.model for h in self.hosts})
        # edge-tier router processes (add_router); routers colocate
        # with the control plane — they are the public address, not the
        # capacity — so they always launch through the local seam even
        # when hosts go through a remote one
        self.routers: List[_Router] = []
        self.router_launcher: HostLauncher = LocalHostLauncher()
        # per-model (artifact, retrieval_index) PAIR currently rolled
        # out — the artifact doubles as the rollback target for a
        # failed coordinated swap (fleet/swap.py), and a (re)spawned
        # host reconciles onto the pair, not just the artifact: a host
        # dying after a pipeline retrieval_refresh must come back with
        # the refreshed index, not none/stale
        self._artifacts: Dict[str, Optional[str]] = {}
        self._retrieval_indexes: Dict[str, Optional[str]] = {}
        self._stop = threading.Event()
        self._escalated = False
        self._lock = threading.Lock()
        self.swap = FleetSwapDriver(self)
        self.router: Optional[FleetRouter] = None
        self._poll_pool = None  # lazily created, lives for the run
        self.flight = obs.default_flight_recorder()
        self.flight.configure(
            dump_dir=self.run_dir,
            max_dumps=getattr(config, "serve_flight_max_dumps", 64),
            log=self.log)
        # telemetry history + SLO judgment (obs/tsdb.py, obs/slo.py):
        # every poll tick's pre-merge snapshot set lands in the
        # segment ring under the run dir; the autoscaler and the SLO
        # engine both read windows back out of it, and GET /query
        # exposes the same windows to operators
        self.tsdb = obs_tsdb.TsdbStore(
            os.path.join(self.run_dir, "tsdb"),
            retention_s=getattr(config, "fleet_tsdb_retention_s",
                                3600.0),
            max_mb=getattr(config, "fleet_tsdb_max_mb", 64.0),
            log=self.log)
        self.slo = obs_slo.SloEngine(
            obs_slo.objectives_from_config(config),
            period_s=getattr(config, "fleet_slo_period_s",
                             2592000.0),
            window_scale=getattr(config, "fleet_slo_window_scale",
                                 1.0),
            flight=self.flight, log=self.log)
        # cross-process stitching: the control plane records swap /
        # admin spans into its own ring and exports them beside the
        # hosts' files so `fleet trace` sees the whole tree
        self._trace_path = os.path.join(self.run_dir,
                                        "control.trace.json")
        if getattr(config, "trace_export", None):
            obs.default_tracer().enable()

    def set_initial_artifact(self, model: str,
                             artifact: Optional[str],
                             retrieval_index: Optional[str] = None
                             ) -> None:
        self._artifacts[model] = artifact
        self._retrieval_indexes[model] = retrieval_index

    def add_router(self, spec: RouterSpec) -> None:
        """Register an edge router process (before start())."""
        self.routers.append(_Router(spec, self.run_dir))

    # ------------------------------------------------------------ spawn

    def _spawn(self, host: _Host) -> None:
        try:
            os.remove(host.heartbeat_path)
        except OSError:
            pass
        host.port = host.telemetry_port = None
        host.view = None
        host.metrics_text = ""
        from code2vec_tpu.serving.server import RELOAD_TARGET_FILENAME
        from code2vec_tpu.serving.supervisor import child_env
        current = self._artifacts.get(host.model)
        index = self._retrieval_indexes.get(host.model)
        target_path = os.path.join(host.host_dir,
                                   RELOAD_TARGET_FILENAME)
        boot_index = host.spec.boot_retrieval_index
        if current and (current != host.spec.boot_artifact
                        or (index or None) != (boot_index or None)):
            # desired-state reconciliation across a host restart: the
            # fleet committed a swap (and possibly a retrieval_refresh)
            # after this host's command was built, so its supervisor
            # must deliver the CURRENT (artifact, retrieval_index)
            # PAIR to every replica at first heartbeat — the artifact
            # alone would revive the model with no/stale index
            payload = {"artifact": current,
                       "requested_at": time.time()}
            if index:
                payload["retrieval_index"] = index
            obs.exporters._atomic_write(
                target_path, json.dumps(payload) + "\n")
        else:
            try:
                os.remove(target_path)
            except OSError:
                pass
        command = host.spec.command + ["--heartbeat_file",
                                       host.heartbeat_path]
        if getattr(self.config, "trace_export", None):
            # thread span-file export down the tree: the host
            # supervisor exports its own ring into the host dir and
            # hands each replica a per-replica path there, so every
            # span file `fleet trace` stitches lives under ONE run dir
            command = command + [
                "--trace_export",
                os.path.join(host.host_dir, "supervisor.trace.json")]
        env = child_env(os.environ)
        env[FLEET_HOST_ENV] = host.id
        env[FLEET_HOST_ADDRESS_ENV] = host.address
        try:
            host.proc = self.launcher.launch(command, env,
                                             host.log_path)
        except OSError as e:
            # a launcher that cannot even start its wrapper (missing
            # ssh/docker binary, bad template) joins the ordinary
            # death path: backoff, restart budget, escalation
            host.proc = None
            host.spawned_at = time.monotonic()
            self._handle_host_death(host, f"launch failed ({e})")
            return
        host.spawned_at = time.monotonic()
        host.restart_at = None
        host.needs_reconcile = True
        self.log(f"Fleet host {host.id} (model {host.model}) spawned "
                 f"(pid {host.proc.pid})")

    def _spawn_router(self, router: _Router) -> None:
        try:
            os.remove(router.heartbeat_path)
        except OSError:
            pass
        router.port = None
        from code2vec_tpu.serving.supervisor import child_env
        command = router.spec.command + ["--heartbeat_file",
                                         router.heartbeat_path]
        if getattr(self.config, "trace_export", None):
            # router forward/retry spans join the stitched tree: each
            # agent exports its ring into its run dir each poll tick
            command = command + [
                "--trace_export",
                os.path.join(router.router_dir, "router.trace.json")]
        env = child_env(os.environ)
        env[FLEET_ROUTER_ENV] = router.id
        # a router agent never builds a model: keep its startup at
        # subprocess speed (same gate the chaos children use)
        env.setdefault("C2V_HOST_WORKER", "1")
        try:
            router.proc = self.router_launcher.launch(
                command, env, router.log_path)
        except OSError as e:
            router.proc = None
            router.spawned_at = time.monotonic()
            self._handle_router_death(router, f"launch failed ({e})")
            return
        router.spawned_at = time.monotonic()
        router.restart_at = None
        self.log(f"Edge router {router.id} spawned "
                 f"(pid {router.proc.pid})")

    def start(self) -> None:
        for host in self.hosts:
            self._spawn(host)
        for router in self.routers:
            self._spawn_router(router)
        self._write_heartbeat("controlling")

    # ------------------------------------------------------------- http

    def _fetch(self, host: _Host, path: str,
               timeout: float = 3.0) -> Optional[bytes]:
        if host.telemetry_port is None:
            return None
        try:
            with urllib.request.urlopen(
                    f"http://{host.address}:{host.telemetry_port}"
                    f"{path}", timeout=timeout) as r:
                return r.read()
        except (OSError, ValueError):
            return None

    def _post(self, host: _Host, path: str, payload: dict,
              timeout: float = 10.0) -> Tuple[bool, str]:
        if host.telemetry_port is None:
            return False, "telemetry port unknown"
        req = urllib.request.Request(
            f"http://{host.address}:{host.telemetry_port}{path}",
            data=json.dumps(payload).encode(), method="POST",
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=timeout) as r:
                return True, r.read().decode("utf-8", errors="replace")
        except urllib.error.HTTPError as e:
            return False, f"HTTP {e.code}: " + e.read().decode(
                "utf-8", errors="replace")[:200]
        except (OSError, ValueError) as e:
            return False, str(e)

    # ------------------------------------------------------------- poll

    def _stale_after_s(self) -> float:
        # supervisors rewrite their heartbeat ~every second; three
        # missed writes (plus poll slack) = a hung host
        return max(5.0, 3.0 * self.config.fleet_poll_interval_s + 2.0)

    def poll_once(self) -> None:
        now = time.monotonic()
        hosts = list(self.hosts)
        if len(hosts) > 1:
            # concurrent: each check blocks on up to two 3s HTTP
            # fetches — serialized, ONE wedged host would stall health
            # derivation, restart detection and scaling for the fleet.
            # The pool lives for the run (not per tick).
            if self._poll_pool is None:
                from concurrent.futures import ThreadPoolExecutor
                self._poll_pool = ThreadPoolExecutor(
                    max_workers=min(8, len(hosts)),
                    thread_name_prefix="fleet-poll")
            list(self._poll_pool.map(
                lambda h: self._check_host(h, now), hosts))
        elif hosts:
            self._check_host(hosts[0], now)
        for router in self.routers:
            if self._stop.is_set():
                break
            self._check_router(router, now)
        # ONE history tick per poll: the same pre-merge snapshot set
        # merged_fleet_metrics reads — per-source, so the autoscaler
        # can query one host's window and a host restart resets only
        # that host's series
        snapshots: Dict[str, object] = {
            f"host:{h.id}": h.metrics_text
            for h in hosts if h.metrics_text}
        snapshots["control"] = (
            obs.default_registry().render_prometheus())
        try:
            self.tsdb.append(snapshots, now=time.time())
        except OSError as e:
            self.log(f"tsdb append failed ({e}); history tick lost")
        # scaling decisions read the freshly-appended window
        for host in hosts:
            if self._stop.is_set():
                break
            self._scale_tick(host, now)
        self.slo.evaluate(self.tsdb)
        tracer = obs.default_tracer()
        if tracer.enabled and len(tracer):
            try:
                tracer.export_chrome_trace(self._trace_path)
            except OSError:
                pass
        self._update_host_gauges()
        self._write_heartbeat("controlling")

    def _check_host(self, host: _Host, now: float) -> None:
        if host.retired:
            host.state, host.weight = "draining", 0.0
            return
        if host.draining:
            host.state, host.weight = "draining", 0.0
            if host.proc is not None and host.proc.poll() is not None:
                host.proc.wait()
                host.retired = True
                self.flight.event("host_retired", host=host.id,
                                  rc=host.proc.returncode)
                self.log(f"Fleet host {host.id} drained and retired "
                         f"(rc={host.proc.returncode})")
            return
        if host.restart_at is not None:
            host.state, host.weight = "down", 0.0
            if now >= host.restart_at:
                self._spawn(host)
            return
        rc = host.proc.poll() if host.proc is not None else 0
        if rc is not None:
            self._handle_host_death(host, f"exited rc={rc}")
            return
        hb = host.heartbeat()
        if hb is None:
            host.state, host.weight = "down", 0.0
            if now - host.spawned_at > HOST_STARTUP_GRACE_S:
                self._kill(host)
                self._handle_host_death(
                    host, "no heartbeat within the startup grace "
                          "(hung startup; killed)")
            return
        host.port = hb.get("port") or host.port
        host.telemetry_port = (hb.get("telemetry_port")
                               or host.telemetry_port)
        hb_age = time.time() - float(hb.get("wall_time", 0.0))
        if hb_age > self._stale_after_s():
            self._kill(host)
            self._handle_host_death(
                host, f"host heartbeat stale ({hb_age:.1f}s; hung; "
                      f"killed)")
            return
        # health off the host's own telemetry plane
        raw = self._fetch(host, "/fleet")
        if raw is not None:
            try:
                host.view = json.loads(raw)
            except ValueError:
                pass
        raw = self._fetch(host, "/metrics")
        if raw is not None:
            host.metrics_text = raw.decode("utf-8", errors="replace")
        if host.needs_reconcile and host.view is not None:
            self._reconcile_host(host)
        breaker_open = False
        replicas_serving = 0
        if host.view:
            for replica in host.view.get("replicas", []):
                breakers = replica.get("breakers") or {}
                if "open" in breakers.values():
                    breaker_open = True
                if replica.get("status") == "serving":
                    replicas_serving += 1
            view_desired = host.view.get("desired_replicas")
            if host.desired_replicas is None:
                host.desired_replicas = view_desired
            elif (view_desired is not None
                    and view_desired != host.desired_replicas
                    and now >= host.cooldown_until):
                # a restarted host supervisor boots its command-line
                # replica count: re-assert the scaled count so a crash
                # does not silently shed the capacity the autoscaler
                # (or an operator) added. Cooldown-gated — right after
                # a scale action the cached view lags one tick.
                ok, _ = self._post(host, "/admin/scale",
                                   {"replicas":
                                    host.desired_replicas})
                if ok:
                    host.cooldown_until = (
                        now + self.config.fleet_scale_cooldown_s)
                    self.log(f"Re-asserted host {host.id} replica "
                             f"count {host.desired_replicas} after "
                             f"restart (was {view_desired})")
        if (host.view is None or host.port is None
                or replicas_serving == 0):
            # zero serving replicas = the host cannot answer a predict
            # no matter how healthy its SUPERVISOR looks (proxy mode
            # would answer well-formed 503s the router does not retry;
            # weight 0 routes around the whole replica-restart window)
            host.state, host.weight = "down", 0.0
        elif breaker_open:
            host.state, host.weight = "degraded", UNHEALTHY_WEIGHT
        else:
            host.state, host.weight = "healthy", 1.0

    def _reconcile_host(self, host: _Host) -> None:
        """First-heartbeat desired-state reconcile of a (re)spawned
        host onto the committed (artifact, retrieval_index) PAIR.

        The reload-target file _spawn writes only reaches hosts
        launched on the control plane's own filesystem; a
        RemoteHostLauncher host boots on another machine, and a
        supervisor that restarted its own replicas never re-reads the
        file. So the control plane checks what the host itself
        REPORTS — its last fanned-out reload (artifact + index) or,
        absent one, its boot artifact — against the committed pair at
        the first healthy view after every spawn, and re-issues
        /admin/reload with the full pair on any disagreement. Skipped
        while a coordinated swap is in flight (the swap driver owns
        convergence then; the flag stays set, so the check re-runs on
        the next tick)."""
        desired_artifact = self._artifacts.get(host.model)
        desired_index = self._retrieval_indexes.get(host.model)
        if not desired_artifact:
            host.needs_reconcile = False
            return
        if self.swap.status().get("state") in ("canary", "rolling"):
            return
        last = (host.view or {}).get("last_reload") or {}
        if last.get("artifact"):
            have_artifact = last["artifact"]
            have_index = last.get("retrieval_index")
        else:
            # no fan-out processed yet: the host serves what its boot
            # command mounted
            have_artifact = host.spec.boot_artifact
            have_index = host.spec.boot_retrieval_index
        if (have_artifact == desired_artifact
                and (have_index or None) == (desired_index or None)):
            host.needs_reconcile = False
            return
        ok, body = self.host_reload(host, desired_artifact,
                                    retrieval_index=desired_index)
        if ok:
            host.needs_reconcile = False
            self.flight.event("host_reconciled", host=host.id,
                              artifact=desired_artifact,
                              retrieval_index=desired_index)
            self.log(
                f"Reconciled host {host.id} onto committed pair "
                f"(artifact {desired_artifact}, index "
                f"{desired_index or 'none'}; host reported "
                f"{have_artifact}/{have_index or 'none'})")
        else:
            # retried at the next poll tick; the host is freshly up,
            # so a transient refusal here is common
            self.log(f"Host {host.id} reconcile reload refused: "
                     f"{body[:200]}")

    def _check_router(self, router: _Router, now: float) -> None:
        """Same supervision shape as _check_host, minus health/scaling:
        a router is either routing (fresh heartbeat) or down."""
        if router.restart_at is not None:
            router.state = "down"
            if now >= router.restart_at:
                self._spawn_router(router)
            return
        rc = router.proc.poll() if router.proc is not None else 0
        if rc is not None:
            self._handle_router_death(router, f"exited rc={rc}")
            return
        hb = router.heartbeat()
        if hb is None:
            router.state = "down"
            if now - router.spawned_at > HOST_STARTUP_GRACE_S:
                self._kill(router)
                self._handle_router_death(
                    router, "no heartbeat within the startup grace "
                            "(hung startup; killed)")
            return
        router.port = hb.get("port") or router.port
        hb_age = time.time() - float(hb.get("wall_time", 0.0))
        if hb_age > self._stale_after_s():
            self._kill(router)
            self._handle_router_death(
                router, f"router heartbeat stale ({hb_age:.1f}s; "
                        f"hung; killed)")
            return
        router.state = "routing"

    def _kill(self, host, sig=signal.SIGKILL) -> None:
        if host.proc is not None and host.proc.poll() is None:
            try:
                host.proc.send_signal(sig)
            except OSError:
                pass

    def _handle_host_death(self, host: _Host, why: str) -> None:
        if host.proc is not None:
            host.proc.wait()
        host.state, host.weight = "down", 0.0
        if host.restarts >= self.config.fleet_max_host_restarts:
            self.log(f"Fleet host {host.id} {why}; restart budget "
                     f"({self.config.fleet_max_host_restarts}) "
                     f"exhausted — escalating")
            self.flight.incident("host_escalation", immediate=True,
                                 host=host.id, why=why,
                                 restarts=host.restarts)
            self._escalated = True
            self._stop.set()
            return
        host.restarts += 1
        _C_HOST_RESTARTS.inc()
        self.flight.incident("host_down", host=host.id, why=why,
                             restart=host.restarts)
        backoff = min(0.5 * (2 ** (host.restarts - 1)), 10.0)
        host.restart_at = time.monotonic() + backoff
        self.log(f"Fleet host {host.id} {why}; restart "
                 f"{host.restarts}/"
                 f"{self.config.fleet_max_host_restarts} in "
                 f"{backoff:.1f}s")

    def _handle_router_death(self, router: _Router, why: str) -> None:
        """The host backoff/escalation policy, applied to a router: a
        SIGKILLed router under load is absorbed by the survivors and
        respawned here; a router that cannot stay up exhausts the same
        restart budget and escalates the same way."""
        if router.proc is not None:
            router.proc.wait()
        router.state = "down"
        if router.restarts >= self.config.fleet_max_host_restarts:
            self.log(f"Edge router {router.id} {why}; restart budget "
                     f"({self.config.fleet_max_host_restarts}) "
                     f"exhausted — escalating")
            self.flight.incident("router_escalation", immediate=True,
                                 router=router.id, why=why,
                                 restarts=router.restarts)
            self._escalated = True
            self._stop.set()
            return
        router.restarts += 1
        _C_ROUTER_RESTARTS.inc()
        self.flight.incident("router_down", router=router.id, why=why,
                             restart=router.restarts)
        backoff = min(0.5 * (2 ** (router.restarts - 1)), 10.0)
        router.restart_at = time.monotonic() + backoff
        self.log(f"Edge router {router.id} {why}; restart "
                 f"{router.restarts}/"
                 f"{self.config.fleet_max_host_restarts} in "
                 f"{backoff:.1f}s")

    # ---------------------------------------------------------- scaling

    def _scale_tick(self, host: _Host, now: float) -> None:
        """One hysteresis-damped scaling decision for one host, over
        the last-two-ticks window of the telemetry history store —
        the tsdb owns reset detection (telemetry.counter_delta), so a
        replica restart zeroing counters reads as the post-restart
        growth, never a negative delta or a phantom idle tick."""
        cfg = self.config
        view = host.view
        if not view or host.state == "down":
            host.up_ticks = host.idle_ticks = 0
            return
        source = f"host:{host.id}"
        if self.tsdb.series_len("serving_requests_total", ticks=2,
                                source=source) < 2:
            # first tick after (re)spawn: no window yet, decide next
            # tick — boot must not read as idle
            host.up_ticks = host.idle_ticks = 0
            return
        d_req = self.tsdb.increase("serving_requests_total", ticks=2,
                                   source=source)
        d_shed = self.tsdb.increase("serving_requests_shed_total",
                                    ticks=2, source=source)
        shed_rate = (d_shed / d_req) if d_req > 0 else 0.0
        p95_ms = None
        if cfg.fleet_scale_up_p95_ms > 0:
            p95 = self.tsdb.quantile(
                "serving_request_seconds", 0.95, ticks=2,
                source=source, phase="total")
            p95_ms = None if p95 is None else p95 * 1000.0
        up = (shed_rate > cfg.fleet_scale_up_shed_rate
              or (p95_ms is not None
                  and p95_ms > cfg.fleet_scale_up_p95_ms))
        idle = d_req == 0
        host.up_ticks = host.up_ticks + 1 if up else 0
        host.idle_ticks = host.idle_ticks + 1 if idle else 0
        if now < host.cooldown_until:
            return
        desired = host.desired_replicas or view.get(
            "desired_replicas") or len(view.get("replicas", ())) or 1
        if (host.up_ticks >= cfg.fleet_scale_up_ticks
                and desired < cfg.fleet_scale_max):
            self._apply_scale(host, desired + 1, "up",
                              f"shed_rate={shed_rate:.3f} "
                              f"p95_ms={p95_ms}", now)
        elif (host.idle_ticks >= cfg.fleet_scale_down_ticks
                and desired > cfg.fleet_scale_min):
            self._apply_scale(host, desired - 1, "down",
                              f"idle for {host.idle_ticks} tick(s)",
                              now)

    def _apply_scale(self, host: _Host, n: int, direction: str,
                     why: str, now: Optional[float] = None) -> None:
        ok, detail = self._post(host, "/admin/scale", {"replicas": n})
        if not ok:
            self.log(f"Scale {direction} of host {host.id} to {n} "
                     f"FAILED ({detail})")
            return
        host.desired_replicas = n
        host.up_ticks = host.idle_ticks = 0
        host.cooldown_until = ((now if now is not None
                                else time.monotonic())
                               + self.config.fleet_scale_cooldown_s)
        _c_scale_actions(direction).inc()
        self.flight.event("fleet_scale", host=host.id,
                          direction=direction, replicas=n, why=why)
        self.log(f"Scaled host {host.id} {direction} to {n} "
                 f"replica(s): {why}")

    def _update_host_gauges(self) -> None:
        counts: Dict[Tuple[str, str], int] = {}
        for host in self.hosts:
            counts[(host.model, host.state)] = counts.get(
                (host.model, host.state), 0) + 1
        for model in self.models:
            for state in _HOST_STATES:
                _g_hosts(model, state).set(
                    counts.get((model, state), 0))
        if self.routers:
            routing = sum(1 for r in self.routers
                          if r.state == "routing")
            _g_routers("routing").set(routing)
            _g_routers("down").set(len(self.routers) - routing)

    # --------------------------------------------------- router surface

    def hosts_for(self, model: str):
        """Router candidates: None for an unknown model, else
        [(weight, host_id, (address, port))] — zero-weight hosts
        included (the router drops them) so callers can see why."""
        if model not in self.models:
            return None
        return [(host.weight, host.id, (host.address, host.port))
                for host in self.hosts
                if host.model == model and host.port is not None]

    def merged_fleet_metrics(self) -> str:
        """Fleet-wide /metrics: every host's (already replica-merged)
        snapshot merged again — counters/histograms summed across
        hosts, gauges labeled host="<id>" on top of their replica
        labels — plus the control plane's own registry."""
        snapshots = {f"host:{h.id}": h.metrics_text
                     for h in self.hosts if h.metrics_text}
        snapshots["control"] = obs.default_registry().render_prometheus()
        return telemetry.merge_prometheus_snapshots(snapshots,
                                                    gauge_label="host")

    def fleet_view(self) -> dict:
        now = time.time()
        hosts = []
        fingerprints: Dict[str, set] = {m: set() for m in self.models}
        for host in self.hosts:
            hb = host.heartbeat()
            view = host.view or {}
            fps = view.get("fingerprints") or []
            fingerprints[host.model].update(fps)
            hosts.append({
                "host": host.id,
                "model": host.model,
                "address": host.address,
                "state": host.state,
                "weight": host.weight,
                "alive": host.alive,
                "draining": host.draining,
                "retired": host.retired,
                "pid": host.proc.pid if host.proc is not None else None,
                "port": host.port,
                "telemetry_port": host.telemetry_port,
                "restarts": host.restarts,
                "desired_replicas": host.desired_replicas,
                "replica_count": view.get("replica_count"),
                # replicas that have written a "serving" heartbeat —
                # under SO_REUSEPORT a replica's port exists before its
                # listener does, so THIS is the readiness signal
                "replicas_serving": sum(
                    1 for r in view.get("replicas", [])
                    if r.get("status") == "serving"),
                "fingerprints": sorted(fps),
                "heartbeat_age_s": (
                    None if not hb else round(max(
                        now - float(hb.get("wall_time", 0.0)), 0.0), 3)),
            })
        return {
            "role": "fleet-control",
            "router_port": self.router.port if self.router else None,
            "router_ports": sorted(r.port for r in self.routers
                                   if r.port is not None),
            "routers": [{
                "router": r.id,
                "state": r.state,
                "alive": r.alive,
                "pid": r.proc.pid if r.proc is not None else None,
                "port": r.port,
                "restarts": r.restarts,
            } for r in self.routers],
            "models": {m: {
                "hosts": sum(1 for h in self.hosts if h.model == m),
                "routable": sum(1 for h in self.hosts
                                if h.model == m and h.weight > 0),
                "artifact": self._artifacts.get(m),
                "retrieval_index": self._retrieval_indexes.get(m),
                # >1 fingerprint = a swap window (or a wedged rollout):
                # observable, and bounded by the canary-first driver
                "fingerprints": sorted(fingerprints[m]),
                "mixed_fingerprints": len(fingerprints[m]) > 1,
            } for m in self.models},
            "escalated": self._escalated,
            "swap": self.swap.status(),
            "hosts": hosts,
        }

    # ------------------------------------------------- history surface

    def query_range(self, params: Dict[str, str]) -> dict:
        """GET /query body: a tsdb range query (op=rate | increase |
        quantile | stats). ValueError maps to 400 at the HTTP layer."""
        return self.tsdb.query_range(params)

    def slo_status(self) -> dict:
        """GET /slo body: the SLO engine's last evaluation plus the
        history depth it judged from."""
        status = self.slo.status()
        status["tsdb"] = self.tsdb.stats()
        return status

    def trace_spans(self, trace_id: str) -> dict:
        """GET /trace?id= body: every process's span files under the
        run dir, stitched into one Chrome trace for `trace_id`. The
        control plane's own ring is exported first so spans recorded
        since the last poll tick are included."""
        from code2vec_tpu.obs import stitch
        tracer = obs.default_tracer()
        if tracer.enabled and len(tracer):
            try:
                tracer.export_chrome_trace(self._trace_path)
            except OSError:
                pass
        return stitch.stitch_dir(self.run_dir, str(trace_id))

    # ---------------------------------------------------- admin surface

    def request_swap(self, payload: dict) -> Tuple[int, dict]:
        model = str(payload.get("model") or DEFAULT_MODEL)
        status = self.swap.request(
            payload.get("artifact"), model=model,
            rollback_to=payload.get("rollback"),
            retrieval_index=payload.get("retrieval_index"),
            traceparent=payload.get("traceparent"))
        return 202, {"accepted": True, "swap": status}

    def request_scale(self, host_id, n) -> Tuple[int, dict]:
        host = self._host_by_id(host_id)
        try:
            n = int(n)
        except (TypeError, ValueError):
            raise ValueError('body must be {"host": ID, "replicas": N}')
        cfg = self.config
        if not (cfg.fleet_scale_min <= n <= cfg.fleet_scale_max):
            # the configured bounds gate MANUAL overrides too — an
            # operator typo must not fork a host past its capacity
            raise ValueError(
                f"replicas must be in [{cfg.fleet_scale_min}, "
                f"{cfg.fleet_scale_max}] (--fleet_scale_min/max); "
                f"got {n}")
        ok, detail = self._post(host, "/admin/scale", {"replicas": n})
        if not ok:
            raise ValueError(f"scale request to host {host.id} "
                             f"failed: {detail}")
        host.desired_replicas = n
        host.cooldown_until = (time.monotonic()
                               + self.config.fleet_scale_cooldown_s)
        return 200, {"host": host.id, "desired_replicas": int(n)}

    def drain_host(self, host_id) -> Tuple[int, dict]:
        """Coordinated host removal: stop routing to it NOW, let its
        supervisor drain the replicas' in-flight work, retire the
        process when it exits."""
        host = self._host_by_id(host_id)
        if not host.draining:
            host.draining = True
            host.state, host.weight = "draining", 0.0
            host.restart_at = None
            self._kill(host, signal.SIGTERM)
            self.flight.event("host_drain", host=host.id)
            self.log(f"Fleet host {host.id} draining (no new work; "
                     f"supervisor coordinates the replica drain)")
        return 202, {"host": host.id, "draining": True}

    def _host_by_id(self, host_id) -> _Host:
        for host in self.hosts:
            if host.id == host_id:
                return host
        raise KeyError(str(host_id))

    # ------------------------------------------------ swap-driver seams

    def swap_hosts(self, model: str):
        if model not in self.models:
            return None
        return [h for h in self.hosts
                if h.model == model and h.alive and not h.draining]

    def host_reload(self, host: _Host, artifact: str,
                    retrieval_index: Optional[str] = None,
                    traceparent: Optional[str] = None):
        payload = {"artifact": artifact}
        if retrieval_index:
            payload["retrieval_index"] = str(retrieval_index)
        if traceparent:
            # rides INSIDE the body: the host telemetry listener's
            # post handlers never see HTTP headers (supervisor
            # _admin_reload parents its fan-out span under this)
            payload["traceparent"] = traceparent
        return self._post(host, "/admin/reload", payload)

    def host_fleet(self, host: _Host) -> Optional[dict]:
        raw = self._fetch(host, "/fleet")
        if raw is None:
            return None
        try:
            return json.loads(raw)
        except ValueError:
            return None

    def rollback_target(self, model: str) -> Optional[str]:
        return self._artifacts.get(model)

    def set_artifact(self, model: str, artifact: str,
                     retrieval_index: Optional[str] = None) -> None:
        """Record the committed (artifact, retrieval_index) pair —
        what a (re)spawned host reconciles onto. A plain model promote
        clears the index: the rollout either refused or detached any
        fingerprint-mismatched index, so reviving the old one on a
        restart would serve stale vectors."""
        self._artifacts[model] = artifact
        self._retrieval_indexes[model] = retrieval_index

    # -------------------------------------------------------------- run

    def _write_heartbeat(self, status: str, **extra) -> None:
        obs.exporters.write_heartbeat(
            self.heartbeat_path, status=status, role="fleet-control",
            router_port=self.router.port if self.router else None,
            router_ports=sorted(r.port for r in self.routers
                                if r.port is not None),
            escalated=self._escalated,
            routers=[{"router": r.id, "state": r.state,
                      "pid": r.proc.pid if r.proc is not None
                      else None,
                      "port": r.port, "restarts": r.restarts}
                     for r in self.routers],
            hosts=[{"host": h.id, "model": h.model, "state": h.state,
                    "pid": h.proc.pid if h.proc is not None else None,
                    "port": h.port, "telemetry_port": h.telemetry_port,
                    "restarts": h.restarts}
                   for h in self.hosts], **extra)

    def run(self) -> int:
        self.start()
        try:
            while not self._stop.is_set():
                self._stop.wait(self.config.fleet_poll_interval_s)
                if self._stop.is_set():
                    break
                self.poll_once()
        finally:
            rc = self._shutdown()
        return rc

    def stop(self) -> None:
        self._stop.set()

    def _shutdown(self) -> int:
        escalated = self._escalated
        self.log("Fleet shutdown: "
                 + ("host restart budget exhausted — killing hosts"
                    if escalated else
                    "draining the router and every host"))
        if self.router is not None:
            self.router.drain()
        # public intake stops FIRST: routers drain on SIGTERM (503 with
        # Retry-After), then the hosts behind them
        for router in self.routers:
            self._kill(router, signal.SIGKILL if escalated
                       else signal.SIGTERM)
        for host in self.hosts:
            self._kill(host, signal.SIGKILL if escalated
                       else signal.SIGTERM)
        budget = self.config.serve_drain_timeout_s + 20.0
        deadline = time.monotonic() + budget
        clean = not escalated
        for router in self.routers:
            if router.proc is None or router.restart_at is not None:
                continue  # dead + reaped, waiting out backoff
            try:
                rc = router.proc.wait(
                    timeout=max(deadline - time.monotonic(), 0.1))
            except subprocess.TimeoutExpired:
                self._kill(router)
                router.proc.wait()
                rc = router.proc.returncode
            if rc != 0:
                clean = False
                self.log(f"Edge router {router.id} exited rc={rc}")
        for host in self.hosts:
            if host.proc is None or host.retired:
                continue
            if host.restart_at is not None:
                continue  # already dead + reaped, waiting out backoff
            try:
                rc = host.proc.wait(
                    timeout=max(deadline - time.monotonic(), 0.1))
            except subprocess.TimeoutExpired:
                self._kill(host)
                host.proc.wait()
                rc = host.proc.returncode
            if rc != 0:
                clean = False
                self.log(f"Fleet host {host.id} exited rc={rc}")
        if self.router is not None:
            self.router.close()
        if self._poll_pool is not None:
            self._poll_pool.shutdown(wait=False)
        self._write_heartbeat(
            "error" if (escalated or not clean) else "done")
        self.log(f"Fleet exit: "
                 f"{'clean' if clean and not escalated else 'FAILED'}")
        return 0 if clean and not escalated else 1


# -------------------------------------------------------------- CLI body


_FLEET_VALUE_FLAGS = (
    "--fleet_hosts", "--fleet_port", "--fleet_models",
    "--fleet_routers", "--fleet_control", "--fleet_launcher",
    "--fleet_addresses",
    "--fleet_poll_interval", "--fleet_scale_min", "--fleet_scale_max",
    "--fleet_scale_up_shed_rate", "--fleet_scale_up_p95_ms",
    "--fleet_scale_up_ticks", "--fleet_scale_down_ticks",
    "--fleet_scale_cooldown", "--fleet_swap_timeout",
    "--fleet_max_host_restarts",
    "--fleet_tsdb_retention", "--fleet_tsdb_max_mb",
    "--fleet_slo_availability", "--fleet_slo_latency_ms",
    "--fleet_slo_latency_target", "--fleet_slo_period",
    "--fleet_slo_window_scale",
    "--fleet_trace_id", "--fleet_trace_dir",
    # run files + ports are per host, owned by the control plane
    "--heartbeat_file", "--metrics_file", "--trace_export",
    "--serve_port", "--serve_telemetry_port",
)
# valueless fleet flags (argparse store_true) stripped the same way
_FLEET_BOOL_FLAGS = ("--fleet_no_affinity",)

# Router agents re-exec the SAME argv (keeping the `fleet` subcommand
# — dispatch keys on C2V_FLEET_ROUTER) so they inherit the operator's
# serve_*/fleet_* knobs, including the affinity toggle; only the
# per-process run-file/port/topology flags are stripped.
_ROUTER_STRIP_FLAGS = (
    "--fleet_routers", "--fleet_control", "--fleet_port",
    "--fleet_launcher", "--fleet_addresses",
    "--fleet_trace_id", "--fleet_trace_dir",
    "--heartbeat_file", "--metrics_file", "--trace_export",
    "--serve_port", "--serve_telemetry_port",
)


def _host_base_command(argv: List[str], strip_artifact: bool
                       ) -> List[str]:
    from code2vec_tpu.serving.supervisor import strip_flag
    argv = list(argv)
    if argv and argv[0] == "fleet":
        argv[0] = "serve"
    for flag in _FLEET_VALUE_FLAGS:
        argv = strip_flag(argv, flag)
    for flag in _FLEET_BOOL_FLAGS:
        argv = strip_flag(argv, flag, has_value=False)
    if strip_artifact:
        argv = strip_flag(argv, "--artifact")
    return [sys.executable, "-m", "code2vec_tpu.cli"] + argv


def _router_base_command(argv: List[str]) -> List[str]:
    from code2vec_tpu.serving.supervisor import strip_flag
    argv = list(argv)
    for flag in _ROUTER_STRIP_FLAGS:
        argv = strip_flag(argv, flag)
    return [sys.executable, "-m", "code2vec_tpu.cli"] + argv


def fleet_main(config, argv: Optional[List[str]] = None,
               host_command: Optional[List[str]] = None,
               launcher: Optional[HostLauncher] = None) -> int:
    """`fleet` CLI subcommand body (cli.main dispatches here before
    building any model). Each host re-execs this CLI as `serve` with
    the fleet flags stripped and its own run files/ports —
    `host_command` overrides the re-exec (the chaos suite points it at
    a lightweight fake-model host)."""
    models = parse_fleet_models(getattr(config, "fleet_models", ""))
    single = not models
    if single:
        models = {DEFAULT_MODEL: config.serve_artifact}
    if launcher is None and getattr(config, "fleet_launcher", ""):
        launcher = RemoteHostLauncher(config.fleet_launcher)
    addresses = [a.strip() for a in
                 (getattr(config, "fleet_addresses", "") or "")
                 .split(",") if a.strip()]
    specs: List[HostSpec] = []
    for model, artifact in models.items():
        base = (list(host_command) if host_command is not None
                else _host_base_command(list(argv or []),
                                        strip_artifact=not single))
        cmd = base + ["--serve_port", "0", "--serve_telemetry_port",
                      "0"]
        if not single and artifact:
            cmd = cmd + ["--artifact", artifact]
        for i in range(config.fleet_hosts):
            # remote fleets place hosts round-robin over the address
            # list; the launcher template reaches each host at its own
            # {address} and its reported ports are reachable there
            address = (addresses[len(specs) % len(addresses)]
                       if addresses else config.serve_host)
            specs.append(HostSpec(
                f"{model}-{i}", cmd, model=model, address=address,
                boot_artifact=artifact,
                boot_retrieval_index=getattr(config, "retrieval_index",
                                             None)))
    control = ControlPlane(config, specs, launcher=launcher,
                           log=config.log)
    for model, artifact in models.items():
        # the boot pair includes any --retrieval_index: a host that
        # dies before the first promote must come back with the index
        # it was launched to serve, not none
        control.set_initial_artifact(
            model, artifact,
            retrieval_index=getattr(config, "retrieval_index", None))
    router_port = (config.fleet_port if config.fleet_port is not None
                   else config.serve_port)
    n_routers = max(1, int(getattr(config, "fleet_routers", 1) or 1))
    if n_routers > 1:
        # Edge tier: N stateless router processes on consecutive
        # public ports (VIP convention: ONE DNS name, A-records /
        # L4 VIP members at base..base+N-1 — README "Edge"). The
        # embedded router demotes to the PRIVATE control listener the
        # agents poll for the shared fleet view and relay admin verbs
        # to; it binds loopback so the only public addresses are the
        # agents'.
        control.router = FleetRouter(config, control,
                                     host="127.0.0.1", port=0,
                                     log=config.log)
        base = _router_base_command(list(argv or []))
        control_address = f"127.0.0.1:{control.router.port}"
        for i in range(n_routers):
            port = router_port + i if router_port else 0
            control.add_router(RouterSpec(
                f"router-{i}",
                base + ["--serve_port", str(port),
                        "--fleet_control", control_address]))
    else:
        control.router = FleetRouter(config, control,
                                     host=config.serve_host,
                                     port=router_port, log=config.log)
    installed = threading.current_thread() is threading.main_thread()
    prev = {}
    if installed:
        for sig in (signal.SIGTERM, signal.SIGINT):
            prev[sig] = signal.signal(sig,
                                      lambda s, f: control.stop())
        if hasattr(signal, "SIGHUP"):
            prev[signal.SIGHUP] = signal.signal(
                signal.SIGHUP,
                lambda s, f: config.log(
                    "SIGHUP ignored at the fleet level: drive "
                    "coordinated swaps via POST /admin/reload on the "
                    "router (canary-first, rollback on failure)"))
    config.log(f"Fleet: {len(specs)} host(s) x "
               f"{max(config.serve_replicas, 1)} replica(s), models "
               f"{sorted(models)}, "
               + (f"{n_routers} edge router(s) from port "
                  f"{router_port or 'auto'} (control listener "
                  f"127.0.0.1:{control.router.port})"
                  if n_routers > 1
                  else f"router port {control.router.port}"))
    try:
        return control.run()
    finally:
        for sig, handler in prev.items():
            signal.signal(sig, handler)
