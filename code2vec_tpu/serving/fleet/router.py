"""Health-gated cross-host router: the fleet's one public address.

Generalizes the supervisor's proxy fallback (serving/supervisor.py)
from "round-robin over my own replicas" to "weighted routing over a
fleet of hosts", consuming the health the control plane derives from
each host's `/fleet` + heartbeat staleness:

- **Weighted away from sick hosts**: a healthy host weighs 1.0; a host
  with an open breaker or a stale heartbeat is down-weighted (not
  excluded — a degraded host still serves cache hits and may be the
  only capacity left); a dead or draining host weighs 0 and receives
  nothing. Selection is weighted sampling WITHOUT replacement
  (Efraimidis–Spirakis keys), so retries walk the remaining hosts in
  weight-biased order.
- **Deadline-bounded retry**: a connection failure (SIGKILLed host,
  mid-restart listener) retries the next candidate, but never past the
  request's remaining `X-Deadline-Ms` budget — a retry dispatched after
  budget exhaustion can only produce a late 504, so it is answered as
  an honest 504 instead. The remaining budget also bounds each
  attempt's socket timeout.
- **Contract preservation**: the PR-9 503-honesty and PR-12
  trace-propagation contracts hold end to end — inbound `traceparent`
  is forwarded, replica trace headers ride back, and every
  ROUTER-generated terminal status (no host, budget exhausted, all
  unreachable) carries `X-Trace-Id` + `traceparent` + a `trace_id`
  body field, with a JITTERED `Retry-After` on 503s.
- **Multi-model**: hosts are grouped by model (one release artifact per
  group); the `X-Model` request header (default "default") picks the
  group. Cache and fingerprint isolation is structural — a request can
  only ever reach a host mounting its model — and every response still
  carries the `model_fingerprint` of the exact weights that served it.
- **Consistent-hash cache affinity** (`--fleet_no_affinity` to
  disable): the replicas' LRU prediction caches are per-host, so under
  pure weighted sampling a repeated request warms EVERY host before it
  reliably hits — fleet-level hit rate decays as 1/N. Affinity hashes
  the request's normalized source (the same normalization the cache
  key uses, serving/cache.py) onto a consistent-hash ring of the
  FULLY-HEALTHY hosts and tries that host first; retries (and the
  whole selection when the preferred host is unhealthy/draining, i.e.
  off the ring) fall back to the weighted order. Affinity only picks
  WHICH host answers — response bytes are a host-local function of
  (normalized source, knobs, model fingerprint), so the byte-equality
  and fingerprint-keying cache invariants are untouched (pinned in
  tests/test_edge.py).

Fleet views are answered HERE, never forwarded: `GET /fleet` is the
control plane's fleet JSON, `GET /metrics` the fleet-wide merge of
every host's (already replica-merged) snapshot. `GET /query` relays a
telemetry-history range query (obs/tsdb.py), `GET /slo` the SLO
engine's burn-rate status, and `GET /trace?id=` the stitched
cross-process trace — all answered by the control plane's embedded
store, so history survives any single router. `POST /admin/reload`
starts the canary-first coordinated hot-swap (serving/fleet/swap.py),
`POST /admin/scale {"host": ..., "replicas": N}` overrides one host's
replica count, `POST /admin/drain {"host": ...}` starts a coordinated
host drain.
"""

from __future__ import annotations

import bisect
import hashlib
import http.server
import json
import random
import threading
import time
import urllib.parse
from typing import List, Optional, Tuple

from code2vec_tpu import obs
from code2vec_tpu.obs.reqtrace import RequestTrace
from code2vec_tpu.serving.admission import (
    deadline_from_request, retry_after_seconds,
)
from code2vec_tpu.serving.cache import normalize_source
from code2vec_tpu.serving.forwarding import (
    REQUEST_FORWARD_HEADERS, forward_with_retry, handle_admin_post,
)

DEFAULT_MODEL = "default"
FORWARD_ENDPOINTS = ("/predict", "/embed", "/neighbors")
# Virtual nodes per host on the affinity ring: enough that removing a
# host spreads its keyspace ~evenly over the survivors, small enough
# that rebuilding the ring on a health transition is trivial.
AFFINITY_VNODES = 64

_C_RETRIES = obs.counter(
    "fleet_router_retries_total",
    "forward attempts the fleet router retried on another host after "
    "a connection failure")


def _c_affinity(outcome: str):
    return obs.counter(
        "fleet_router_affinity_total",
        "cache-affinity routing decisions: preferred (the request's "
        "consistent-hash host was healthy and tried first), fallback "
        "(no fully-healthy host on the ring — pure weighted sampling)",
        outcome=outcome)


def _ring_point(value) -> int:
    data = value if isinstance(value, bytes) else str(value).encode()
    return int.from_bytes(
        hashlib.blake2b(data, digest_size=8).digest(), "big")


def affinity_ring(host_ids) -> List[Tuple[int, str]]:
    """Consistent-hash ring over host ids: each host owns
    AFFINITY_VNODES points on a 64-bit circle. Ring membership is the
    FULLY-HEALTHY host set, so a host leaving (death, open breaker,
    drain) remaps only its own arcs — every other host keeps its keys
    (and its warm cache entries)."""
    return sorted((_ring_point(f"{host_id}#{i}"), host_id)
                  for host_id in host_ids
                  for i in range(AFFINITY_VNODES))


def affinity_host(key: bytes, ring: List[Tuple[int, str]]
                  ) -> Optional[str]:
    """First ring point clockwise of the key's hash (wrapping)."""
    if not ring:
        return None
    idx = bisect.bisect_left(ring, (_ring_point(key), ""))
    return ring[idx % len(ring)][1]


def _c_requests(endpoint: str, outcome: str):
    return obs.counter(
        "fleet_router_requests_total",
        "fleet-router requests by endpoint and routing outcome: "
        "forwarded (a host answered), no_host (no routable host for "
        "the model), unknown_model (no such model group), expired "
        "(deadline budget exhausted before/while retrying), "
        "unreachable (every candidate host refused the connection), "
        "draining (fleet-wide drain refused intake)",
        endpoint=endpoint, outcome=outcome)


def weighted_order(candidates, rng=random):
    """Weighted shuffle (Efraimidis–Spirakis): each candidate keyed by
    random()^(1/weight), descending — higher weight, earlier position,
    zero cross-candidate coordination. `candidates` is a list of
    (weight, payload); zero/negative weights are dropped."""
    keyed = [(rng.random() ** (1.0 / w), payload)
             for w, payload in candidates if w > 0]
    keyed.sort(reverse=True, key=lambda kv: kv[0])
    return [payload for _, payload in keyed]


class FleetRouter:
    """One public HTTP listener over a `control` object exposing:
    `hosts_for(model) -> Optional[List[(weight, host_id, (addr,
    port))]]` (None = unknown model), `fleet_view()`,
    `merged_fleet_metrics()`, `query_range(params)`, `slo_status()`,
    `trace_spans(trace_id)`, `request_swap(payload)`,
    `request_scale(host_id, n)`, `drain_host(host_id)` — duck-typed so
    tests drive the router on a stub control plane."""

    def __init__(self, config, control, host: Optional[str] = None,
                 port: Optional[int] = None, log=None):
        self.config = config
        self.control = control
        self.log = log or config.log
        self._draining = False
        self.affinity = bool(getattr(config, "fleet_cache_affinity",
                                     True))
        # memoized ring keyed by the healthy-host id tuple: health
        # transitions are rare relative to requests
        self._ring: Tuple[Tuple[str, ...], List[Tuple[int, str]]] = \
            ((), [])
        router = self

        class Handler(http.server.BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *args):
                pass

            def _reply(self, code, payload, headers=None,
                       ctype="application/json"):
                body = (payload if isinstance(payload, bytes)
                        else json.dumps(payload,
                                        sort_keys=True).encode() + b"\n")
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802 (stdlib API name)
                path = self.path.split("?", 1)[0]
                try:
                    if path == "/healthz":
                        hz = router.healthz()
                        self._reply(
                            503 if hz["status"] == "draining" else 200,
                            hz)
                    elif path == "/fleet":
                        self._reply(200, router.control.fleet_view())
                    elif path in ("/metrics", "/"):
                        self._reply(
                            200,
                            router.control.merged_fleet_metrics()
                            .encode(),
                            ctype="text/plain; version=0.0.4; "
                                  "charset=utf-8")
                    elif path == "/query":
                        try:
                            self._reply(200,
                                        router.control.query_range(
                                            self._params()))
                        except ValueError as e:
                            self._reply(400, {"error": str(e)})
                    elif path == "/slo":
                        self._reply(200, router.control.slo_status())
                    elif path == "/trace":
                        tid = (self._params().get("id") or "").strip()
                        if not tid:
                            self._reply(400, {
                                "error": "missing ?id=<trace id>"})
                        else:
                            self._reply(
                                200, router.control.trace_spans(tid))
                    else:
                        self._reply(404, {"error":
                                          f"no such endpoint: {path}"})
                except Exception as e:  # noqa: BLE001 — a probe must
                    # get an HTTP error, never a torn connection
                    self._reply(500, {"error":
                                      f"{type(e).__name__}: {e}"})

            def _params(self) -> dict:
                return dict(urllib.parse.parse_qsl(
                    urllib.parse.urlsplit(self.path).query))

            def do_POST(self):  # noqa: N802 (stdlib API name)
                path = self.path.split("?", 1)[0]
                if path.startswith("/admin/"):
                    router._admin(self, path)
                    return
                if path not in FORWARD_ENDPOINTS:
                    self._reply(404, {"error":
                                      f"no such endpoint: {path}"})
                    return
                router._forward(self, path)

        class _Listener(http.server.ThreadingHTTPServer):
            # match the replica listeners: a burst must reach the
            # hosts' admission gates, not be refused at the kernel
            request_queue_size = 128

        self._httpd = _Listener(
            (host if host is not None else config.serve_host,
             port if port is not None else config.serve_port),
            Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        threading.Thread(target=self._httpd.serve_forever,
                         name="fleet-router", daemon=True).start()
        self.log(f"Fleet router on http://{self._httpd.server_address[0]}"
                 f":{self.port} (POST /predict /embed /neighbors "
                 f"routed by X-Model; GET /fleet /metrics /healthz "
                 f"/query /slo /trace; "
                 f"POST /admin/reload /admin/scale /admin/drain)")

    # ---------------------------------------------------------- forward

    def _forward(self, handler, path: str) -> None:
        endpoint = path.lstrip("/")
        trace = RequestTrace.from_headers(
            handler.headers.get("traceparent"))
        # Shim the reply to capture the terminal status: the router
        # tier records every forwarded request into the flight
        # recorder, so an SLO-burn dump at this process holds the
        # offending requests' trace ids, not just the burn numbers.
        t0 = time.monotonic()
        terminal = {}
        orig_reply = handler._reply

        def reply(code, payload, headers=None,
                  ctype="application/json"):
            terminal["status"] = code
            orig_reply(code, payload, headers, ctype=ctype)

        handler._reply = reply
        # The forward span opens BEFORE any traceparent is serialized:
        # the parent id propagated to the host must name a span this
        # router actually records, or the stitched trace breaks at the
        # router hop.
        fwd_span = None
        try:
            with trace.span(f"router.forward {endpoint}",
                            endpoint=endpoint) as fwd_span:
                self._forward_in_span(handler, path, endpoint, trace,
                                      fwd_span)
        finally:
            obs.default_flight_recorder().record_request(
                trace_id=trace.trace_id, endpoint="/" + endpoint,
                status=int(terminal.get("status", 0)),
                duration_s=time.monotonic() - t0,
                reason=(fwd_span.attrs.get("outcome")
                        if fwd_span is not None else None))

    def _forward_in_span(self, handler, path: str, endpoint: str,
                         trace, fwd_span) -> None:
        length = int(handler.headers.get("Content-Length", 0))
        body = handler.rfile.read(length) if length else b""
        trace_headers = {"X-Trace-Id": trace.trace_id,
                         "traceparent": trace.traceparent()}
        deadline = deadline_from_request(
            self.config, handler.headers.get("X-Deadline-Ms"))
        model = (handler.headers.get("X-Model") or "").strip() \
            or DEFAULT_MODEL
        fwd_span.attrs["model"] = model
        fwd_headers = {"traceparent": trace.traceparent()}
        for name in REQUEST_FORWARD_HEADERS:
            if handler.headers.get(name):
                fwd_headers[name] = handler.headers[name]

        def outcome(kind: str) -> None:
            fwd_span.attrs["outcome"] = kind
            _c_requests(endpoint, kind).inc()

        if self._draining:
            outcome("draining")
            handler._reply(503, {"error": "fleet is draining",
                                 "trace_id": trace.trace_id},
                           dict(trace_headers, **{
                               "Retry-After":
                               str(retry_after_seconds(1.0))}))
            return
        candidates = self.control.hosts_for(model)
        if candidates is None:
            outcome("unknown_model")
            handler._reply(404, {
                "error": f"no such model: {model!r} (X-Model header; "
                         f"see GET /fleet for the mounted models)",
                "trace_id": trace.trace_id}, trace_headers)
            return
        ordered = weighted_order([(w, (host_id, addr))
                                  for w, host_id, addr in candidates])
        if self.affinity and ordered:
            self._apply_affinity(body, candidates, ordered)
        if not ordered:
            outcome("no_host")
            handler._reply(503, {
                "error": f"no routable host for model {model!r}",
                "trace_id": trace.trace_id},
                dict(trace_headers, **{
                    "Retry-After": str(retry_after_seconds(1.0))}))
            return
        # One forward/retry loop for the whole serving tier
        # (serving/forwarding.py; the supervisor proxy is the
        # single-host degenerate case of this call). handler.path keeps
        # the query string (`path` was stripped for dispatch):
        # ?debug=trace must reach the replica.
        forward_with_retry(
            method="POST", path=handler.path, body=body,
            fwd_headers=fwd_headers,
            targets=[(host_id, addr, port)
                     for host_id, (addr, port) in ordered],
            deadline=deadline, trace=trace,
            reply=lambda code, payload, headers, ctype:
                handler._reply(code, payload, headers, ctype=ctype),
            what="hosts",
            unreachable_error=f"no host reachable for model {model!r}",
            retry_after=str(retry_after_seconds(1.0)),
            retry_counter=_C_RETRIES,
            on_outcome=outcome)

    def _apply_affinity(self, body: bytes, candidates,
                        ordered) -> None:
        """Move the request's consistent-hash host to the front of the
        weighted order (in place). The affinity key is the NORMALIZED
        source — whitespace variants of one snippet hash identically,
        exactly as they share a cache entry on the host. The ring holds
        only fully-healthy hosts; with none (or the preferred id gone
        from the routable order) the weighted order stands."""
        healthy = tuple(sorted(
            host_id for w, host_id, _addr in candidates if w >= 1.0))
        if not healthy:
            _c_affinity("fallback").inc()
            return
        if self._ring[0] != healthy:
            self._ring = (healthy, affinity_ring(healthy))
        preferred = affinity_host(
            normalize_source(body.decode("utf-8", errors="replace")),
            self._ring[1])
        for i, payload in enumerate(ordered):
            if payload[0] == preferred:
                ordered.insert(0, ordered.pop(i))
                _c_affinity("preferred").inc()
                return
        _c_affinity("fallback").inc()

    # ------------------------------------------------------------ admin

    def _admin(self, handler, path: str) -> None:
        trace = RequestTrace.from_headers(
            handler.headers.get("traceparent"))

        def dispatch(payload: dict):
            with trace.span(f"router.admin {path}", endpoint=path):
                if path == "/admin/reload":
                    # the rollout's spans parent under this admin
                    # request: `fleet trace` shows operator -> router
                    # -> swap driver -> every host as one tree
                    payload.setdefault("traceparent",
                                       trace.traceparent())
                    return self.control.request_swap(payload)
                if path == "/admin/scale":
                    return self.control.request_scale(
                        payload.get("host"), payload.get("replicas"))
                if path == "/admin/drain":
                    return self.control.drain_host(payload.get("host"))
                return 404, {"error": f"no such endpoint: {path}"}

        handle_admin_post(
            handler, dispatch,
            lambda code, out: handler._reply(code, out),
            conflict_409=True, keyerror_is_missing_host=True)

    # ------------------------------------------------------------- misc

    def healthz(self) -> dict:
        view = self.control.fleet_view()
        return {
            "status": "draining" if self._draining else "routing",
            "port": self.port,
            "hosts": len(view.get("hosts", [])),
            "routable_hosts": sum(
                1 for h in view.get("hosts", [])
                if h.get("weight", 0) > 0),
            "models": sorted(view.get("models", {})),
        }

    def drain(self) -> None:
        """Stop intake: every new request is an honest 503 shed while
        the hosts behind finish their own drains."""
        self._draining = True

    def close(self) -> None:
        try:
            self._httpd.shutdown()
            self._httpd.server_close()
        except Exception:
            pass  # teardown must never mask the fleet exit path
