"""Edge router agent: one stateless public router of the N-router tier.

`fleet --fleet_routers N` (N >= 2) turns the fleet's single public
router into N of THESE processes on consecutive ports — the VIP
convention (README "Edge"): one DNS name / L4 VIP fronting ports
base..base+N-1, any member serving any request, clients (or the VIP's
health checks) retrying a refused connection against the next member.
A router holds no state a poll cannot rebuild:

- **Shared fleet view**: a `SharedFleetView` polls the control plane's
  PRIVATE control listener (`--fleet_control HOST:PORT`) every
  `--fleet_poll_interval` for the `/fleet` JSON and derives routing
  candidates from it — weights, addresses, ports, per the control
  plane's health derivation. Between polls the router serves from its
  cached view; a stale-but-recent view mis-weights at worst (the
  forward/retry loop still walks every candidate), it never blocks
  intake. The staleness is observable (`view_age_s` in /healthz and
  /fleet).
- **Admin relay**: POST /admin/reload|scale|drain on ANY router is
  relayed verbatim to the control listener, so the coordinated-swap /
  scale / drain surface keeps working whichever member the VIP picks;
  status codes (202 accepted, 409 swap-in-flight, 400/404) pass
  through.
- **Telemetry**: GET /metrics re-merges the control listener's
  fleet-wide snapshot with this router's own registry (affinity and
  routing counters) — counters sum, gauges pick up a `source` label on
  top of their host/replica labels.
- **Supervision contract**: the agent rewrites `--heartbeat_file`
  every poll tick (port + status + view age); the control plane
  restarts a dead or heartbeat-stale router with the SAME
  backoff/escalation policy it applies to hosts, and a SIGTERM drains
  (honest 503s with Retry-After) before exit 0.

The routing logic itself — weighted sampling, deadline-bounded retry,
consistent-hash cache affinity — is FleetRouter (serving/fleet/
router.py), unchanged: this module only swaps its `control` surface
for a polled remote one.
"""

from __future__ import annotations

import json
import os
import signal
import tempfile
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import List, Optional, Tuple

from code2vec_tpu import obs
from code2vec_tpu.serving import telemetry
from code2vec_tpu.serving.fleet.router import FleetRouter

# re-exported for callers that only know the agent module
from code2vec_tpu.serving.fleet.control import FLEET_ROUTER_ENV  # noqa: F401


def _c_view_refresh(outcome: str):
    return obs.counter(
        "edge_view_refresh_total",
        "fleet-view poll attempts by an edge router agent against the "
        "control listener (ok | error — on error the router keeps "
        "serving from its cached view)",
        outcome=outcome)


class SharedFleetView:
    """The router agent's `control` surface, duck-typed against
    FleetRouter's contract: hosts_for / fleet_view /
    merged_fleet_metrics / query_range / slo_status / trace_spans /
    request_swap / request_scale / drain_host,
    all derived from (or relayed to) the control listener. This is the
    WHOLE per-router state — a SIGKILLed router loses nothing the next
    poll does not rebuild, which is what makes the tier stateless."""

    def __init__(self, config, control_address: str, router_id: str,
                 log=None):
        host, _, port = control_address.rpartition(":")
        if not host or not port.isdigit():
            raise ValueError(
                f"--fleet_control must be HOST:PORT, got "
                f"{control_address!r}")
        self.config = config
        self.base = f"http://{host}:{int(port)}"
        self.router_id = router_id
        self.log = log or config.log
        self._view: dict = {}
        self._fetched_at: Optional[float] = None
        self._lock = threading.Lock()

    # ------------------------------------------------------------- poll

    def refresh(self) -> bool:
        try:
            with urllib.request.urlopen(self.base + "/fleet",
                                        timeout=3.0) as r:
                view = json.loads(r.read())
        except (OSError, ValueError):
            _c_view_refresh("error").inc()
            return False
        with self._lock:
            self._view = view
            self._fetched_at = time.monotonic()
        _c_view_refresh("ok").inc()
        return True

    def view_age_s(self) -> Optional[float]:
        with self._lock:
            if self._fetched_at is None:
                return None
            return round(time.monotonic() - self._fetched_at, 3)

    # --------------------------------------------- FleetRouter contract

    def hosts_for(self, model: str
                  ) -> Optional[List[Tuple[float, str, tuple]]]:
        with self._lock:
            view = self._view
        models = view.get("models") or {}
        if not models:
            # no view yet (control listener unreachable at boot): an
            # empty candidate list is an honest retryable 503; a None
            # would 404 a model that exists
            return []
        if model not in models:
            return None
        return [(float(h.get("weight") or 0.0), h["host"],
                 (h.get("address") or "127.0.0.1", h.get("port")))
                for h in view.get("hosts", [])
                if h.get("model") == model and h.get("port")]

    def fleet_view(self) -> dict:
        with self._lock:
            view = dict(self._view)
        view["role"] = "fleet-router"
        view["router"] = self.router_id
        view["view_age_s"] = self.view_age_s()
        return view

    def merged_fleet_metrics(self) -> str:
        own = obs.default_registry().render_prometheus()
        try:
            with urllib.request.urlopen(self.base + "/metrics",
                                        timeout=3.0) as r:
                fleet_text = r.read().decode("utf-8", errors="replace")
        except (OSError, ValueError):
            return own
        return telemetry.merge_prometheus_snapshots(
            {"fleet": fleet_text,
             f"router:{self.router_id}": own},
            gauge_label="source")

    def _relay_get(self, path: str) -> dict:
        """GET relay for the telemetry-history surface (/query /slo
        /trace): the history lives in the control plane's embedded
        tsdb, so any router answers from the same store. A control 400
        re-raises as ValueError (the router handler's bad-query
        mapping); unreachable control is a 503-shaped error body."""
        try:
            with urllib.request.urlopen(self.base + path,
                                        timeout=10.0) as r:
                return json.loads(r.read() or b"{}")
        except urllib.error.HTTPError as e:
            try:
                body = json.loads(e.read() or b"{}")
            except ValueError:
                body = {"error": f"control listener HTTP {e.code}"}
            if e.code == 400:
                raise ValueError(body.get("error", "bad query"))
            return body
        except (OSError, ValueError) as e:
            return {"error": f"control plane unreachable from "
                             f"router {self.router_id}: {e}"}

    def query_range(self, params: dict) -> dict:
        return self._relay_get(
            "/query?" + urllib.parse.urlencode(params))

    def slo_status(self) -> dict:
        return self._relay_get("/slo")

    def trace_spans(self, trace_id: str) -> dict:
        return self._relay_get(
            "/trace?" + urllib.parse.urlencode({"id": trace_id}))

    def _relay(self, path: str, payload: dict) -> Tuple[int, dict]:
        req = urllib.request.Request(
            self.base + path, data=json.dumps(payload).encode(),
            method="POST",
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=30.0) as r:
                return r.getcode(), json.loads(r.read() or b"{}")
        except urllib.error.HTTPError as e:
            try:
                body = json.loads(e.read() or b"{}")
            except ValueError:
                body = {"error": f"control listener HTTP {e.code}"}
            return e.code, body
        except (OSError, ValueError) as e:
            return 503, {"error": f"control plane unreachable from "
                                  f"router {self.router_id}: {e}"}

    def request_swap(self, payload: dict) -> Tuple[int, dict]:
        return self._relay("/admin/reload", payload)

    def request_scale(self, host_id, n) -> Tuple[int, dict]:
        return self._relay("/admin/scale",
                           {"host": host_id, "replicas": n})

    def drain_host(self, host_id) -> Tuple[int, dict]:
        return self._relay("/admin/drain", {"host": host_id})


def router_main(config) -> int:
    """`fleet` CLI re-exec body for a router child (cli.main dispatches
    here when C2V_FLEET_ROUTER is set, before any model work). Parks
    on a poll/heartbeat loop until SIGTERM/SIGINT, then drains."""
    router_id = os.environ.get(FLEET_ROUTER_ENV, "router")
    control_address = getattr(config, "fleet_control", "") or ""
    if not control_address:
        config.log("fleet router child started without "
                   "--fleet_control HOST:PORT — nothing to route for")
        return 2
    view = SharedFleetView(config, control_address, router_id,
                           log=config.log)
    trace_path = getattr(config, "trace_export", None)
    if trace_path:
        # span ring on: forward/retry/admin spans export per poll tick
        # into the run dir the control plane assigned, where `fleet
        # trace` / GET /trace?id= stitches them with every other
        # process's file
        obs.default_tracer().enable()
    view.refresh()  # best effort before the public port opens
    router = FleetRouter(config, view, host=config.serve_host,
                         port=config.serve_port, log=config.log)
    heartbeat_path = config.heartbeat_file or os.path.join(
        tempfile.mkdtemp(prefix="c2v-router-"),
        "router.heartbeat.json")
    stop = threading.Event()
    if threading.current_thread() is threading.main_thread():
        for sig in (signal.SIGTERM, signal.SIGINT):
            signal.signal(sig, lambda s, f: stop.set())
        if hasattr(signal, "SIGHUP"):
            signal.signal(
                signal.SIGHUP,
                lambda s, f: config.log(
                    "SIGHUP ignored by the edge router: drive "
                    "coordinated swaps via POST /admin/reload"))

    def _heartbeat(status: str) -> None:
        obs.exporters.write_heartbeat(
            heartbeat_path, status=status, role="fleet-router",
            router=router_id, port=router.port,
            control=control_address, view_age_s=view.view_age_s())

    config.log(f"Edge router {router_id} on port {router.port} "
               f"(control listener {control_address})")
    _heartbeat("routing")
    while not stop.is_set():
        # heartbeat cadence == view-poll cadence: the control plane's
        # staleness threshold scales off the same knob
        stop.wait(config.fleet_poll_interval_s)
        if stop.is_set():
            break
        view.refresh()
        _heartbeat("routing")
        if trace_path and len(obs.default_tracer()):
            try:
                obs.default_tracer().export_chrome_trace(trace_path)
            except OSError as e:
                config.log(f"Edge router {router_id}: trace export "
                           f"failed: {e}")
    # drain: stop intake (honest 503 + Retry-After) and give in-flight
    # forwards a moment before the listener closes under them
    router.drain()
    _heartbeat("draining")
    time.sleep(min(2.0, getattr(config, "serve_drain_timeout_s", 2.0)))
    router.close()
    if trace_path and len(obs.default_tracer()):
        try:
            obs.default_tracer().export_chrome_trace(trace_path)
        except OSError:
            pass  # exiting anyway; the per-tick export is recent
    _heartbeat("done")
    config.log(f"Edge router {router_id} drained and exiting")
    return 0
