"""Fleet-wide coordinated hot-swap: canary-first, halt on failure,
roll back rather than leave a mixed fleet.

PR 9's recorded gap: SIGHUP fan-out stops at ONE node — a deploy
touching N hosts had no coordinator, so "which fingerprint is the
fleet serving" was unanswerable mid-rollout. This driver closes it:

1. **Canary**: one host (the first live host of the target model
   group) receives the reload first. Its supervisor fans the swap out
   to its replicas (serving/supervisor.py reload_all); the driver
   polls the host's `/fleet` until every replica lands ONE new
   fingerprint with `swap_state == ready` — that fingerprint becomes
   the fleet TARGET. A canary that fails (any replica `swap_state ==
   failed`, or no convergence inside `--fleet_swap_timeout`) halts the
   rollout with zero non-canary hosts touched.
2. **Rollout**: remaining hosts swap sequentially; each must land
   exactly the canary's fingerprint. First failure halts the rollout.
3. **Rollback**: on a post-canary failure the already-committed hosts
   (and the failed one) are driven back to the previous artifact —
   the fleet converges back to ONE fingerprint instead of serving a
   permanently mixed window. No rollback target (the fleet was started
   without a known artifact) degrades to halt-and-report.

The mixed-fingerprint window is deliberately OBSERVABLE and BOUNDED:
`status()` (surfaced in the router's `GET /fleet` under `"swap"`)
carries the per-host outcomes and the target fingerprint while the
control plane's fleet view carries every host's live fingerprint set.
`fleet_swap_total{outcome}` counts committed / failed / rolled_back
rollouts; every transition is a flight-recorder event.
"""

from __future__ import annotations

import threading
import time
from typing import List, Optional

from code2vec_tpu import obs
from code2vec_tpu.obs.reqtrace import RequestTrace


def _c_swaps(outcome: str):
    return obs.counter(
        "fleet_swap_total",
        "fleet-wide coordinated hot-swap rollouts by outcome: "
        "committed (every host landed the canary's fingerprint), "
        "failed (halted with no rollback target or rollback failure), "
        "rolled_back (a post-canary failure was rolled back to the "
        "previous artifact fleet-wide)",
        outcome=outcome)


class FleetSwapBusy(ValueError):
    """A rollout is already in flight — maps to HTTP 409 (the router
    matches on the message, like SwapManager's reload conflict)."""

    def __init__(self, state: str, target):
        super().__init__(
            f"a fleet swap is already in flight (state={state}, "
            f"target={target}); poll GET /fleet `swap` and retry")


class FleetSwapDriver:
    """Owns the rollout worker thread + the status the router surfaces.
    `control` is the ControlPlane (duck-typed in tests): provides
    `swap_hosts(model)` (live hosts of the group, canary first),
    `host_reload(host, artifact)`, `host_fleet(host)` (fresh `/fleet`
    JSON or None), `rollback_target(model)` / `set_artifact(model,
    artifact, retrieval_index=None)`, `flight` and `log`."""

    def __init__(self, control, poll_interval_s: float = 0.25):
        self.control = control
        self.poll_interval_s = poll_interval_s
        self._lock = threading.Lock()
        self._worker: Optional[threading.Thread] = None
        self._status = {"state": "idle", "target": None, "model": None,
                        "target_fingerprint": None, "error": None,
                        "hosts": [], "started_at": None,
                        "completed_at": None, "trace_id": None}

    def status(self) -> dict:
        with self._lock:
            return dict(self._status, hosts=list(self._status["hosts"]))

    def _set(self, **fields) -> None:
        with self._lock:
            self._status.update(fields)

    def _host_outcome(self, host_id: str, outcome: str) -> None:
        with self._lock:
            self._status["hosts"].append({"host": host_id,
                                          "outcome": outcome})

    # ------------------------------------------------------------- start

    def request(self, artifact, model: str = "default",
                rollback_to: Optional[str] = None,
                retrieval_index: Optional[str] = None,
                traceparent: Optional[str] = None) -> dict:
        """Kick off an async rollout; returns the fresh status. Raises
        ValueError on a bad request, FleetSwapBusy while one runs.
        `retrieval_index` rides the reload to every replica, which
        mounts it atomically with its model flip (the pipeline's
        retrieval-refresh rollout; rollbacks never carry one).
        `traceparent` adopts the caller's trace (the router's admin
        span, the pipeline's run trace); absent, the rollout mints its
        own trace id — either way every per-host reload span carries
        ONE id `fleet trace` can stitch, surfaced as `trace_id` in
        status()."""
        if not artifact:
            raise ValueError('no artifact: body must be '
                             '{"artifact": DIR[, "model": NAME]}')
        with self._lock:
            if self._worker is not None and self._worker.is_alive():
                raise FleetSwapBusy(self._status["state"],
                                    self._status["target"])
            hosts = self.control.swap_hosts(model)
            if hosts is None:
                # a pipeline promoting for a group the router does not
                # map (--fleet_models) must be refused HERE — loudly,
                # before any host is touched — not discovered as an
                # ambiguous non-convergence at canary time
                known = getattr(self.control, "models", None) or []
                raise ValueError(
                    f"no such model: {model!r}; this fleet serves "
                    f"model group(s) {sorted(known)!r} — check the "
                    f"pipeline's --pipeline_model against the "
                    f"router's --fleet_models map")
            if not hosts:
                raise ValueError(
                    f"no live host in model group {model!r} to swap")
            rollback = (rollback_to
                        or self.control.rollback_target(model))
            trace = RequestTrace.from_headers(traceparent)
            self._status.update(
                state="canary", target=str(artifact), model=model,
                target_fingerprint=None, error=None, hosts=[],
                started_at=time.time(), completed_at=None,
                trace_id=trace.trace_id)
            self._worker = threading.Thread(
                target=self._run,
                args=(str(artifact), model, hosts, rollback,
                      retrieval_index, trace),
                name="fleet-swap", daemon=True)
            self._worker.start()
        return self.status()

    # ----------------------------------------------------------- rollout

    def _run(self, artifact: str, model: str, hosts: List,
             rollback: Optional[str],
             retrieval_index: Optional[str] = None,
             trace: Optional[RequestTrace] = None) -> None:
        control = self.control
        trace = trace or RequestTrace.from_headers(None)
        control.flight.event("fleet_swap_start", target=artifact,
                             model=model, hosts=len(hosts),
                             retrieval_index=retrieval_index,
                             canary=hosts[0].id,
                             trace_id=trace.trace_id)
        with trace.span(f"fleet.rollout {model}", artifact=artifact,
                        model=model, hosts=len(hosts)):
            self._run_in_span(artifact, model, hosts, rollback,
                              retrieval_index, trace)

    def _run_in_span(self, artifact: str, model: str, hosts: List,
                     rollback: Optional[str],
                     retrieval_index: Optional[str],
                     trace: RequestTrace) -> None:
        control = self.control
        target_fp: Optional[str] = None
        committed: List = []
        for i, host in enumerate(hosts):
            ok, result = self._swap_host(host, artifact,
                                         expect_fp=target_fp,
                                         retrieval_index=retrieval_index,
                                         trace=trace)
            if not ok:
                self._host_outcome(host.id, f"failed: {result}")
                control.flight.event("fleet_swap_halt", host=host.id,
                                     error=result,
                                     committed=len(committed))
                if i == 0:
                    # canary failure: nothing committed, nothing mixed
                    # — halt-and-report IS the safe terminal state
                    _c_swaps("failed").inc()
                    self._set(state="failed", completed_at=time.time(),
                              error=f"canary {host.id}: {result}")
                    control.log(f"Fleet swap to {artifact} HALTED at "
                                f"canary {host.id}: {result}")
                    return
                self._rollback(committed + [host], rollback, model,
                               first_error=f"{host.id}: {result}",
                               trace=trace)
                return
            self._host_outcome(host.id, "committed")
            committed.append(host)
            if i == 0:
                target_fp = result
                self._set(state="rolling", target_fingerprint=result)
                control.log(f"Fleet swap canary {host.id} committed "
                            f"fingerprint {result}; rolling out to "
                            f"{len(hosts) - 1} more host(s)")
        # commit the PAIR: a host (re)spawned after this rollout must
        # reconcile onto (artifact, retrieval_index), not the artifact
        # alone — a retrieval_refresh survivor with no index would
        # 503 every /neighbors until the next refresh
        control.set_artifact(model, artifact,
                             retrieval_index=retrieval_index)
        _c_swaps("committed").inc()
        self._set(state="committed", completed_at=time.time())
        control.flight.event("fleet_swap_committed", target=artifact,
                             model=model, fingerprint=target_fp,
                             hosts=len(hosts))
        control.log(f"Fleet swap committed: {len(hosts)} host(s) on "
                    f"fingerprint {target_fp} ({artifact})")

    def _rollback(self, touched: List, rollback: Optional[str],
                  model: str, first_error: str,
                  trace: Optional[RequestTrace] = None) -> None:
        control = self.control
        if not rollback:
            _c_swaps("failed").inc()
            self._set(state="failed", completed_at=time.time(),
                      error=f"{first_error}; NO rollback target known "
                            f"— fleet left mixed, operator action "
                            f"required (see /fleet fingerprints)")
            control.log(f"Fleet swap FAILED mid-rollout with no "
                        f"rollback target: {first_error}")
            return
        self._set(state="rolling_back")
        control.flight.event("fleet_swap_rollback", target=rollback,
                             hosts=len(touched))
        control.log(f"Fleet swap failed ({first_error}); rolling "
                    f"{len(touched)} host(s) back to {rollback}")
        clean = True
        for host in touched:
            ok, result = self._swap_host(host, rollback, expect_fp=None,
                                         trace=trace)
            self._host_outcome(
                host.id, "rolled_back" if ok
                else f"rollback_failed: {result}")
            clean = clean and ok
        if clean:
            _c_swaps("rolled_back").inc()
            self._set(state="rolled_back", completed_at=time.time(),
                      error=first_error)
            control.log(f"Fleet rollback to {rollback} complete")
        else:
            _c_swaps("failed").inc()
            self._set(state="failed", completed_at=time.time(),
                      error=f"{first_error}; rollback to {rollback} "
                            f"also failed on some hosts — see hosts[]")
            control.log("Fleet rollback FAILED on some hosts")

    # ---------------------------------------------------------- one host

    def _swap_host(self, host, artifact: str,
                   expect_fp: Optional[str],
                   retrieval_index: Optional[str] = None,
                   trace: Optional[RequestTrace] = None):
        """Drive one host's supervisor reload fan-out and poll its
        /fleet until every replica lands one converged fingerprint with
        swap_state ready. Returns (True, fingerprint) or (False, why).
        `expect_fp` (post-canary) additionally pins WHICH fingerprint —
        a host converging on anything else is a failure (two artifacts
        claiming one dir, a stale cache on one host)."""
        if trace is None:
            return self._swap_host_in_span(host, artifact, expect_fp,
                                           retrieval_index, None)
        with trace.span(f"rollout.host {host.id}", host=host.id,
                        artifact=artifact) as host_span:
            ok, result = self._swap_host_in_span(
                host, artifact, expect_fp, retrieval_index, trace)
            host_span.attrs["outcome"] = \
                "committed" if ok else f"failed: {result}"
            return ok, result

    def _swap_host_in_span(self, host, artifact, expect_fp,
                           retrieval_index, trace):
        control = self.control
        ok, why = control.host_reload(
            host, artifact, retrieval_index=retrieval_index,
            traceparent=trace.traceparent() if trace else None)
        if not ok:
            return False, f"reload request failed: {why}"
        timeout = float(getattr(control.config, "fleet_swap_timeout_s",
                                120.0))
        deadline = time.monotonic() + timeout
        last = None
        while time.monotonic() < deadline:
            time.sleep(self.poll_interval_s)
            view = control.host_fleet(host)
            if view is None:
                continue  # transiently unreachable; keep polling
            last = view
            replicas = [r for r in view.get("replicas", [])
                        if not r.get("draining")]
            if not replicas:
                continue
            # convergence is keyed on (swap_target, swap_retrieval_
            # index) == THIS rollout's: a replica still showing a
            # PREVIOUS rollout's "ready" (or a stale "failed" from an
            # old target) can neither satisfy nor abort this one —
            # including a retrieval-refresh rollout re-targeting the
            # SAME artifact the promote rollout just landed
            on_target = [r for r in replicas
                         if r.get("swap_target") == artifact
                         and r.get("swap_retrieval_index")
                         == retrieval_index]
            if any(r.get("swap_state") == "failed"
                   for r in on_target):
                return False, ("a replica rejected the candidate "
                               "(swap_state=failed)")
            if len(on_target) != len(replicas):
                continue  # a replica has not seen the reload yet
            if {r.get("swap_state") for r in on_target} != {"ready"}:
                continue  # a replica has not landed its swap yet
            fps = {r.get("model_fingerprint") for r in on_target}
            if None in fps or len(fps) != 1:
                continue
            fp = fps.pop()
            if expect_fp is not None and fp != expect_fp:
                continue  # converged on the WRONG weights; keep
                # waiting (a slow replica may still flip) until timeout
            return True, fp
        return False, (f"no convergence within {timeout:g}s "
                       f"(last fingerprints="
                       f"{sorted(f or '?' for f in (self._host_fingerprints(last) or []))})")

    @staticmethod
    def _host_fingerprints(view) -> Optional[set]:
        if not view:
            return None
        return {r.get("model_fingerprint")
                for r in view.get("replicas", [])}
