"""Fleet telemetry aggregation: the supervisor's merged /metrics + /fleet.

THE PROBLEM (PR 9's documented gap): under `serve --replicas N` with
SO_REUSEPORT, every replica binds the SAME port and the kernel picks
which one answers a connection — so a Prometheus scrape of `/metrics`
(and a probe of `/healthz`) reaches ONE kernel-chosen replica. Fleet
signals — shed rate, breaker state, phase p99s — were sampled from a
random shard of the truth, and the ROADMAP's cross-host fleet item
plans to autoscale and route off exactly those signals.

THE FIX: each replica already publishes an atomic Prometheus snapshot
(`--metrics_file`, the PR-2 file exporter, rewritten every heartbeat
interval — the supervisor appends a per-replica path to every child
command). This module parses those snapshots and merges them:

- **counters** and **histograms** (bucket counts, `_sum`, `_count`) are
  SUMMED across replicas — `serving_requests_total` on the merged
  endpoint equals the sum of the per-replica counters (pinned in
  tests/test_telemetry.py). Merging keys on the FULL label set, so
  the tenant label (serving/tenancy.py) sums per tenant through this
  merge — and through the router's fleet-wide merge above it — with
  no tenancy-specific code here;
- **gauges** are NOT summable (the mean of two breaker states is
  nonsense) — each replica's gauge exports with an added
  `replica="<i>"` label.

The supervisor serves the merge at `GET /metrics` on its telemetry
listener (`--serve_telemetry_port`, default public port + 1) — the
documented scrape address for a replicated deployment — plus
`GET /fleet`: a JSON view of per-replica breaker state, shed rate,
heartbeat staleness, restart count and model fingerprint (read from the
replica heartbeats the supervisor already monitors). In proxy mode the
public port intercepts `/metrics` and `/fleet` too, so the old scrape
address keeps working there.
"""

from __future__ import annotations

import http.server
import json
import math
import re
import threading
from typing import Callable, Dict, List, Optional, Tuple

# One exposition-format sample line: name{labels} value
_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{(.*)\})?\s+(\S+)$")
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')

LabelsKey = Tuple[Tuple[str, str], ...]

# label-string -> parsed key: snapshots repeat the same label strings
# on every scrape, and the telemetry history parses every host
# snapshot once per poll tick — each distinct label set pays the
# findall/sort/unescape once per process, not once per sample line.
# Bounded so a pathological high-cardinality exporter cannot grow it
# without limit; reads/writes are atomic under the GIL.
_LABELS_CACHE: Dict[str, LabelsKey] = {}
_LABELS_CACHE_MAX = 8192


def _labels_key(labels_raw: str) -> LabelsKey:
    key = _LABELS_CACHE.get(labels_raw)
    if key is None:
        key = tuple(sorted(
            (k, _unescape(v))
            for k, v in _LABEL_RE.findall(labels_raw)))
        if len(_LABELS_CACHE) < _LABELS_CACHE_MAX:
            _LABELS_CACHE[labels_raw] = key
    return key


def _unescape(v: str) -> str:
    return (v.replace("\\n", "\n").replace('\\"', '"')
            .replace("\\\\", "\\"))


def _escape(v: str) -> str:
    return (v.replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _parse_value(raw: str) -> float:
    if raw == "+Inf":
        return float("inf")
    if raw == "-Inf":
        return float("-inf")
    return float(raw)


def _format_value(v: float) -> str:
    if v == float("inf"):
        return "+Inf"
    if v == float("-inf"):
        return "-Inf"
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


class Family:
    """One parsed metric family: kind, help, and (labels -> value)
    samples. `base_name` strips the _bucket/_sum/_count suffix a
    histogram sample line carries."""

    __slots__ = ("name", "kind", "help", "samples")

    def __init__(self, name: str, kind: str = "untyped", help: str = ""):
        self.name = name
        self.kind = kind
        self.help = help
        # sample "sub-name" (e.g. foo_bucket) -> {labels_key: value}
        self.samples: Dict[str, Dict[LabelsKey, float]] = {}


def parse_prometheus_text(text: str) -> Dict[str, Family]:
    """Parse exposition-format text (what obs.render_prometheus and any
    conformant exporter emit) into families. Histogram sample lines
    (`x_bucket`/`x_sum`/`x_count`) attach to the `x` family declared by
    the TYPE line. Unparsable lines are skipped, not fatal — a merge
    must survive one torn/foreign snapshot."""
    families: Dict[str, Family] = {}
    # histogram/summary sample names map back to the declaring family
    subname_to_family: Dict[str, str] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("# HELP "):
            rest = line[len("# HELP "):]
            name, _, help_text = rest.partition(" ")
            fam = families.setdefault(name, Family(name))
            fam.help = help_text
            continue
        if line.startswith("# TYPE "):
            rest = line[len("# TYPE "):]
            name, _, kind = rest.partition(" ")
            fam = families.setdefault(name, Family(name))
            fam.kind = kind.strip() or "untyped"
            if fam.kind == "histogram":
                for suffix in ("_bucket", "_sum", "_count"):
                    subname_to_family[name + suffix] = name
            continue
        if line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            continue
        sample_name, _, labels_raw, value_raw = m.groups()
        fam_name = subname_to_family.get(sample_name, sample_name)
        fam = families.setdefault(fam_name, Family(fam_name))
        labels: LabelsKey = (_labels_key(labels_raw)
                             if labels_raw else ())
        try:
            value = _parse_value(value_raw)
        except ValueError:
            continue
        fam.samples.setdefault(sample_name, {})[labels] = value
    return families


def merge_prometheus_snapshots(snapshots: Dict[str, object],
                               gauge_label: str = "replica") -> str:
    """Merge per-replica snapshots into ONE exposition text: counter +
    histogram samples summed across replicas by (sample name, labels);
    gauge/untyped samples kept per replica with an added
    `replica="<id>"` label. Snapshot values may be exposition TEXT or
    already-parsed families (a caller that validated a snapshot first
    must not pay a second parse on the scrape path). Returns
    render-ready text."""
    merged: Dict[str, Family] = {}
    for replica_id in sorted(snapshots):
        snap = snapshots[replica_id]
        families = (snap if isinstance(snap, dict)
                    else parse_prometheus_text(snap))
        for name, fam in families.items():
            out = merged.setdefault(name, Family(name, fam.kind,
                                                 fam.help))
            if out.kind == "untyped" and fam.kind != "untyped":
                out.kind = fam.kind
            if not out.help:
                out.help = fam.help
            summable = fam.kind in ("counter", "histogram")
            for sample_name, by_labels in fam.samples.items():
                dest = out.samples.setdefault(sample_name, {})
                for labels, value in by_labels.items():
                    if summable:
                        dest[labels] = dest.get(labels, 0.0) + value
                    else:
                        key = tuple(sorted(
                            labels + ((gauge_label, str(replica_id)),)))
                        dest[key] = value
    lines: List[str] = []
    for name in sorted(merged):
        fam = merged[name]
        if fam.help:
            lines.append(f"# HELP {name} {fam.help}")
        lines.append(f"# TYPE {name} {fam.kind}")
        for sample_name in sorted(fam.samples):
            by_labels = fam.samples[sample_name]
            for labels in sorted(by_labels):
                label_str = ""
                if labels:
                    inner = ",".join(f'{k}="{_escape(v)}"'
                                     for k, v in labels)
                    label_str = "{" + inner + "}"
                lines.append(f"{sample_name}{label_str} "
                             f"{_format_value(by_labels[labels])}")
    return "\n".join(lines) + "\n"


def sum_family(text_or_families, name: str,
               **label_filter) -> float:
    """Sum one family's samples (optionally filtered by labels) from
    exposition text — the supervisor's /fleet shed-rate math and the
    tests both use it."""
    families = (parse_prometheus_text(text_or_families)
                if isinstance(text_or_families, str) else text_or_families)
    fam = families.get(name)
    if fam is None:
        return 0.0
    total = 0.0
    for labels in fam.samples.get(name, {}):
        d = dict(labels)
        if all(d.get(k) == str(v) for k, v in label_filter.items()):
            total += fam.samples[name][labels]
    return total


def histogram_buckets(text_or_families, name: str,
                      **label_filter) -> Dict[str, float]:
    """{le: cumulative count} for one histogram family, summed over
    every label set matching `label_filter` — the raw material for
    `quantile_from_buckets`. Empty dict when the family is absent."""
    families = (parse_prometheus_text(text_or_families)
                if isinstance(text_or_families, str)
                else text_or_families)
    fam = families.get(name)
    if fam is None:
        return {}
    out: Dict[str, float] = {}
    for labels, value in fam.samples.get(name + "_bucket", {}).items():
        d = dict(labels)
        if not all(d.get(k) == str(v) for k, v in label_filter.items()):
            continue
        le = d.get("le")
        if le is None:
            continue
        out[le] = out.get(le, 0.0) + value
    return out


def counter_delta(cur: float, prev: Optional[float]) -> float:
    """Reset-aware window delta for ONE monotonic counter reading.

    Counters are lifetime-cumulative and reset to zero when their
    process restarts, so a raw `cur - prev` can go NEGATIVE mid-window
    — and a negative delta silently corrupts every rate/ratio derived
    from it. THE one reset policy, shared by the autoscaler
    (fleet/control.py), the SLO engine (obs/slo.py) and the tsdb range
    queries (obs/tsdb.py): a decrease means the counter restarted near
    zero, so the new reading counts IN FULL (Prometheus `increase`
    semantics, without the extrapolation). `prev=None` means "no
    window yet" and yields 0.0 — never a lifetime-sized spike."""
    if prev is None:
        return 0.0
    cur = float(cur)
    prev = float(prev)
    if cur >= prev:
        return cur - prev
    return max(0.0, cur)  # reset: restarted from ~0


def counter_increase(points) -> float:
    """Reset-aware increase over a SERIES of monotonic counter
    readings (oldest first): the sum of `counter_delta` steps, so a
    mid-window restart contributes the post-restart growth instead of
    poisoning the whole window. Fewer than two points = no window =
    0.0."""
    total = 0.0
    prev: Optional[float] = None
    for value in points:
        if prev is not None:
            total += counter_delta(value, prev)
        prev = float(value)
    return total


def quantile_from_buckets(cur: Dict[str, float],
                          prev: Optional[Dict[str, float]],
                          q: float) -> Optional[float]:
    """Quantile estimate (seconds) from cumulative histogram buckets,
    optionally as a WINDOW: `prev` is an earlier scrape of the same
    buckets and the quantile is computed over the delta — counters are
    lifetime-cumulative, and an autoscaler steering off the lifetime
    p95 would never see a regression fade. Linear interpolation inside
    the bucket (Prometheus histogram_quantile semantics).

    Every input shape yields a DEFINED value (never NaN, never a
    negative bound): a quantile landing in the +Inf bucket returns the
    largest finite bound (a conservative floor) — or +inf when +Inf is
    the ONLY bucket (mass exists but no finite bound does; +inf trips
    any latency threshold, which is the honest reading). A mid-window
    counter reset (cur < prev, a replica restart) falls back to the
    reset-aware `counter_delta` per bucket and the cumulative counts
    are re-monotonized, so the interpolation never sees a negative
    bucket width. None when the window holds no samples (an empty
    window is data ABSENCE, not a zero latency)."""
    prev = prev or {}
    deltas = []
    for le, count in cur.items():
        bound = math.inf if le == "+Inf" else float(le)
        deltas.append((bound,
                       counter_delta(count, prev.get(le, 0.0))))
    if not deltas:
        return None
    deltas.sort()
    # re-monotonize: per-bucket reset corrections (or a torn scrape)
    # can leave cumulative counts locally decreasing
    running = 0.0
    for i, (bound, cum) in enumerate(deltas):
        running = max(running, cum)
        deltas[i] = (bound, running)
    total = deltas[-1][1]  # the +Inf (or widest) cumulative count
    if total <= 0:
        return None
    rank = q * total
    lower = 0.0
    for bound, cum in deltas:
        if cum >= rank:
            if math.isinf(bound):
                finite = [b for b, _ in deltas if not math.isinf(b)]
                # +Inf-only histogram with mass: no finite bound
                # exists; +inf trips any threshold (honest reading)
                return finite[-1] if finite else math.inf
            prev_cum = 0.0
            for b2, c2 in deltas:
                if b2 >= bound:
                    break
                lower, prev_cum = b2, c2
            span = cum - prev_cum
            if span <= 0:
                return bound
            return lower + (bound - lower) * (rank - prev_cum) / span
    return None


def fleet_replica_view(heartbeat: Optional[dict], now: float) -> dict:
    """The per-replica slice of GET /fleet, derived from one replica
    heartbeat (serving/server.py _heartbeat_fields). None-tolerant: a
    replica that has not written a heartbeat yet reports nulls, not a
    crash."""
    if not heartbeat:
        return {"status": None, "heartbeat_age_s": None,
                "model_fingerprint": None, "breakers": None,
                "requests_total": None, "requests_shed_total": None,
                "requests_expired_total": None,
                "shed_rate": None, "swap_state": None,
                "swap_target": None, "swap_retrieval_index": None,
                "inflight": None, "spans_dropped": None,
                "span_ring_high_water": None}
    total = heartbeat.get("requests_total")
    shed = heartbeat.get("requests_shed_total")
    shed_rate = None
    if isinstance(total, (int, float)) and total:
        shed_rate = round(float(shed or 0) / float(total), 6)
    elif isinstance(total, (int, float)):
        shed_rate = 0.0
    return {
        "status": heartbeat.get("status"),
        "heartbeat_age_s": round(
            max(now - float(heartbeat.get("wall_time", 0.0)), 0.0), 3),
        "model_fingerprint": heartbeat.get("model_fingerprint"),
        "breakers": heartbeat.get("breakers"),
        "requests_total": total,
        "requests_shed_total": shed,
        "requests_expired_total": heartbeat.get(
            "requests_expired_total"),
        "shed_rate": shed_rate,
        "swap_state": heartbeat.get("swap_state"),
        "swap_target": heartbeat.get("swap_target"),
        "swap_retrieval_index": heartbeat.get("swap_retrieval_index"),
        "inflight": heartbeat.get("inflight"),
        # span-ring pressure: a stitched trace missing spans is
        # diagnosable only if drops are visible per replica
        "spans_dropped": heartbeat.get("spans_dropped"),
        "span_ring_high_water": heartbeat.get("span_ring_high_water"),
    }


class TelemetryServer:
    """The supervisor's telemetry listener: GET /metrics (merged
    exposition text), GET /fleet (JSON). Callback-driven so the
    supervisor owns the data and this stays a framing shim, exactly
    like PredictionServer's HTTP layer.

    `post_handlers` maps a path to a callable taking the request's JSON
    body (a dict) and returning `(http_status, payload_dict)` — the
    control-plane verbs (`/admin/scale`, `/admin/reload`) ride the same
    listener, so one port per host is both the scrape address and the
    fleet control address. A handler raising ValueError maps to 400."""

    def __init__(self, merged_metrics_fn, fleet_fn,
                 host: str = "127.0.0.1", port: int = 0,
                 post_handlers: Optional[Dict[str, Callable[
                     [dict], Tuple[int, dict]]]] = None):
        telem = self
        self.post_handlers = dict(post_handlers or {})

        class Handler(http.server.BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *args):
                pass

            def _respond(self, code: int, body: bytes,
                         ctype: str = "application/json") -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802 (stdlib API name)
                path = self.path.split("?", 1)[0]
                try:
                    if path in ("/metrics", "/"):
                        self._respond(
                            200, telem.merged_metrics_fn().encode(),
                            ctype="text/plain; version=0.0.4; "
                                  "charset=utf-8")
                    elif path == "/fleet":
                        self._respond(200, json.dumps(
                            telem.fleet_fn(),
                            sort_keys=True).encode() + b"\n")
                    else:
                        self._respond(404, json.dumps(
                            {"error": f"no such endpoint: {path}"}
                        ).encode() + b"\n")
                except Exception as e:  # noqa: BLE001 — a scraper must
                    # get an HTTP error, never a torn connection
                    self._respond(500, json.dumps(
                        {"error": f"{type(e).__name__}: {e}"}
                    ).encode() + b"\n")

            def do_POST(self):  # noqa: N802 (stdlib API name)
                path = self.path.split("?", 1)[0]
                handler = telem.post_handlers.get(path)
                if handler is None:
                    self._respond(404, json.dumps(
                        {"error": f"no such endpoint: {path}"}
                    ).encode() + b"\n")
                    return
                # shared admin-POST skeleton (serving/forwarding.py):
                # parse/dispatch/error-map — the control plane must get
                # an HTTP error, never a torn connection it would
                # misread as a dead host
                from code2vec_tpu.serving.forwarding import (
                    handle_admin_post,
                )
                handle_admin_post(
                    self, handler,
                    lambda code, body: self._respond(code, json.dumps(
                        body, sort_keys=True).encode() + b"\n"))

        self.merged_metrics_fn = merged_metrics_fn
        self.fleet_fn = fleet_fn
        self._httpd = http.server.ThreadingHTTPServer((host, port),
                                                      Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        threading.Thread(target=self._httpd.serve_forever,
                         name="serving-telemetry", daemon=True).start()

    def close(self) -> None:
        try:
            self._httpd.shutdown()
            self._httpd.server_close()
        except Exception:
            pass  # teardown must never mask the supervisor exit path
