"""Shared HTTP forwarding + admin-dispatch core for the serving tier.

The fleet router (serving/fleet/router.py) and the supervisor's proxy
fallback (serving/supervisor.py) grew near-copies of the same
forward-with-retry loop — walk an ordered candidate list, bound every
attempt's socket timeout by the request's remaining X-Deadline-Ms
budget, answer a guaranteed-late retry as an honest 504 instead of
dispatching it, retry connection failures INCLUDING a backend that died
mid-response (IncompleteRead/BadStatusLine are HTTPException, not
OSError), and relay trace headers on every terminal status — plus three
copies of the admin-POST body parse/dispatch/error-mapping. This module
is the single implementation; the supervisor proxy is the single-host
degenerate case of the router's loop (PR-13 recorded follow-on).

Metric registrations stay at the call sites (scripts/check_metrics_doc
walks literal registrations): callers pass counter OBJECTS in.
"""

from __future__ import annotations

import http.client
import json
from typing import Callable, List, Optional, Sequence, Tuple

# Socket-timeout ceiling for an unbounded-deadline forward (the
# pre-refactor literal in both loops).
_UNBOUNDED_TIMEOUT_S = 300.0

# Response headers relayed from a backend to the client: the retry hint
# and the PR-12 trace-correlation contract.
_RELAY_HEADERS = ("Retry-After", "X-Trace-Id", "traceparent")

# Request headers the routing tier forwards verbatim to backends: the
# body framing, the client's deadline budget, the model-group selector,
# and the tenant identity (serving/tenancy.py). ONE tuple shared by the
# fleet router and the supervisor proxy so a header added to the
# serving contract can never silently stop at one hop — pinned in
# tests/test_tenancy.py.
REQUEST_FORWARD_HEADERS = ("Content-Type", "X-Deadline-Ms", "X-Model",
                           "X-Tenant")


def forward_with_retry(
    *,
    method: str,
    path: str,
    body: bytes,
    fwd_headers: dict,
    targets: Sequence[Tuple[str, str, int]],   # (label, address, port)
    deadline,                                   # admission.Deadline
    trace,                                      # reqtrace.RequestTrace
    reply: Callable[[int, bytes, dict, str], None],
    what: str,                                  # "hosts" / "replicas"
    unreachable_error: str,
    retry_after: Optional[str] = None,
    retry_counter=None,
    on_outcome: Optional[Callable[[str], None]] = None,
) -> None:
    """Forward one request along `targets`, retrying connection
    failures within the deadline budget; answers the client through
    `reply(status, payload_bytes, headers, content_type)` exactly once.

    Outcomes reported through `on_outcome`: "forwarded" (a backend
    answered — any status), "expired" (budget died retrying),
    "unreachable" (every candidate refused/tore the connection).
    Every locally-generated terminal status carries the trace headers
    + a trace_id body field; the unreachable 503 adds `retry_after`
    when given."""
    trace_headers = {"X-Trace-Id": trace.trace_id,
                     "traceparent": trace.traceparent()}

    def json_reply(code: int, error: str, extra: Optional[dict] = None):
        payload = json.dumps(
            {"error": error, "trace_id": trace.trace_id},
            sort_keys=True).encode() + b"\n"
        reply(code, payload, dict(trace_headers, **(extra or {})),
              "application/json")

    last_err = None
    for attempt, (label, addr, port) in enumerate(targets):
        remaining = deadline.remaining()
        if attempt and deadline.bounded and remaining <= 0:
            # the budget died with the previous attempt: a retry
            # dispatched now can only produce a LATE 504 — answer it
            # honestly instead
            if on_outcome:
                on_outcome("expired")
            json_reply(504, f"deadline exhausted retrying {what} "
                            f"({last_err})")
            return
        if attempt and retry_counter is not None:
            retry_counter.inc()
        timeout = (min(_UNBOUNDED_TIMEOUT_S, max(remaining, 0.05))
                   if deadline.bounded else _UNBOUNDED_TIMEOUT_S)
        # per-attempt span: retries are visible as siblings in the
        # stitched trace (parented under the caller's forward span)
        with trace.span(f"{what}.attempt {label}", target=label,
                        attempt=attempt) as attempt_span:
            try:
                conn = http.client.HTTPConnection(addr, port,
                                                  timeout=timeout)
                try:
                    conn.request(method, path, body=body,
                                 headers=fwd_headers)
                    resp = conn.getresponse()
                    payload = resp.read()
                    attempt_span.attrs["status"] = resp.status
                    out_headers = {}
                    for name in _RELAY_HEADERS:
                        if resp.getheader(name):
                            out_headers[name] = resp.getheader(name)
                    # a backend always stamps these; belt-and-braces
                    # for any terminal status that somehow lacks them
                    out_headers.setdefault("X-Trace-Id",
                                           trace.trace_id)
                    out_headers.setdefault("traceparent",
                                           trace.traceparent())
                    if on_outcome:
                        on_outcome("forwarded")
                    reply(resp.status, payload, out_headers,
                          resp.getheader("Content-Type",
                                         "application/json"))
                    return
                finally:
                    conn.close()
            except (OSError, http.client.HTTPException) as e:
                # dead / draining / mid-restart backend — including
                # one that died MID-RESPONSE (IncompleteRead/
                # BadStatusLine are HTTPException, not OSError): the
                # client never sees a torn response — retry the next
                # candidate
                attempt_span.attrs["error"] = type(e).__name__
                last_err = f"{label}: {type(e).__name__}: {e}"
                continue
    if on_outcome:
        on_outcome("unreachable")
    json_reply(503, f"{unreachable_error} ({last_err})",
               {"Retry-After": retry_after} if retry_after else None)


def read_json_object(handler) -> dict:
    """Read + parse an HTTP request body as a JSON object (empty body =
    {}); raises ValueError on anything that is not a dict."""
    length = int(handler.headers.get("Content-Length", 0))
    raw = handler.rfile.read(length) if length else b"{}"
    payload = json.loads(raw.decode("utf-8", errors="replace") or "{}")
    if not isinstance(payload, dict):
        raise ValueError("body must be a JSON object")
    return payload


def handle_admin_post(
    handler,
    dispatch: Callable[[dict], Tuple[int, dict]],
    reply: Callable[[int, dict], None],
    *,
    conflict_409: bool = False,
    keyerror_is_missing_host: bool = False,
) -> None:
    """The admin-POST skeleton shared by the fleet router, the
    supervisor proxy and the TelemetryServer: parse the JSON body,
    dispatch, map errors (ValueError -> 400; with `conflict_409`, an
    "in flight" ValueError -> 409; with `keyerror_is_missing_host`, a
    KeyError -> 404 naming the host; anything else -> 500 as an HTTP
    error — the control plane must never see a torn connection it
    would misread as a dead backend)."""
    try:
        code, out = dispatch(read_json_object(handler))
    except (ValueError, json.JSONDecodeError) as e:
        code = (409 if conflict_409 and "in flight" in str(e) else 400)
        out = {"error": str(e)}
    except KeyError as e:
        if keyerror_is_missing_host:
            code, out = 404, {"error": f"no such host: {e}"}
        else:
            code, out = 500, {"error": f"KeyError: {e}"}
    except Exception as e:  # noqa: BLE001
        code, out = 500, {"error": f"{type(e).__name__}: {e}"}
    reply(code, out)
