"""Live-traffic sampling for shadow evaluation (README "Continuous
training").

The pipeline's shadow-eval stage replays a slice of what production
actually asked the model, so a candidate that matches the frozen
accuracy harness but diverges on real traffic is still caught. This
sampler records the EXTRACTED predict lines (the post-extractor
`name ctx,ctx,ctx ...` rows) — the exact input both sides of the
shadow replay consume — on every Nth cache-miss request, into a
bounded ring that is atomically rewritten on a small cadence
(`--serve_traffic_sample`, `--serve_traffic_sample_every`,
`--serve_traffic_sample_cap`).

Deliberately OFF the hot path: a sampled request pays one deque
extend; the file rewrite happens once per `_FLUSH_EVERY` sampled
requests and at drain. Raw source never lands on disk — only the
extractor's tokenized context lines (method names + path contexts),
the same data the .c2v corpus format already carries.
"""

from __future__ import annotations

import collections
import threading
from typing import List, Optional

from code2vec_tpu import obs
from code2vec_tpu.obs import exporters

_C_SAMPLED = obs.counter(
    "serving_traffic_sampled_total",
    "extractor lines recorded into the live-traffic sample ring for "
    "shadow evaluation")

_FLUSH_EVERY = 32


class TrafficSampler:
    """Thread-safe bounded sample of predict-path extractor lines."""

    def __init__(self, path: str, every: int = 10, cap: int = 4096,
                 log=None):
        self.path = path
        self.every = max(1, int(every))
        self._ring: collections.deque = collections.deque(
            maxlen=max(1, int(cap)))
        self._lock = threading.Lock()
        self._requests = 0
        self._sampled_since_flush = 0
        self._log = log or (lambda msg: None)

    def record(self, lines: List[str]) -> None:
        """Offer one request's extracted lines; every Nth request is
        kept. Never raises into the request path."""
        try:
            with self._lock:
                self._requests += 1
                if self._requests % self.every:
                    return
                clean = [ln.strip() for ln in lines if ln.strip()]
                if not clean:
                    return
                self._ring.extend(clean)
                _C_SAMPLED.inc(len(clean))
                self._sampled_since_flush += 1
                flush = self._sampled_since_flush >= _FLUSH_EVERY
                if flush:
                    self._sampled_since_flush = 0
                    snapshot = list(self._ring)
            if flush:
                self._write(snapshot)
        except Exception as e:  # noqa: BLE001 — sampling must never
            # fail a serving request
            self._log(f"Traffic sampler record failed ({e})")

    def flush(self) -> None:
        with self._lock:
            snapshot = list(self._ring)
            self._sampled_since_flush = 0
        if snapshot:
            self._write(snapshot)

    def _write(self, snapshot: List[str]) -> None:
        try:
            exporters._atomic_write(self.path,
                                    "\n".join(snapshot) + "\n")
        except OSError as e:
            self._log(f"Traffic sampler write failed ({e})")

    def status(self) -> dict:
        with self._lock:
            return {"path": self.path, "every": self.every,
                    "entries": len(self._ring),
                    "requests_seen": self._requests}


def sampler_for(config, log=None) -> Optional[TrafficSampler]:
    path = getattr(config, "serve_traffic_sample_file", None)
    if not path:
        return None
    return TrafficSampler(
        path,
        every=getattr(config, "serve_traffic_sample_every", 10),
        cap=getattr(config, "serve_traffic_sample_cap", 4096),
        log=log)
