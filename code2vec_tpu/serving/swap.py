"""Health-gated live model hot-swap: change weights without dropping
traffic.

Before this, changing the model a replica serves meant killing the
process — a full drain, cold start, and cache loss per deploy. The
SwapManager loads a NEW release bundle entirely off the request path,
validates it, and only then swaps the server's model reference between
batches:

    POST /admin/reload {"artifact": DIR}     (or SIGHUP: re-read
                                              --artifact from config)
      -> state "loading":    release/artifact.py load_artifact — every
         field-validated table/meta check PR 8 does at startup runs
         here, on a worker thread, while the OLD model keeps serving
      -> state "validating": a golden-prediction smoke batch through
         the new model (BucketedPredictMixin.smoke_schema) compared
         against the RUNNING model's output schema — top-k width, code
         vector size, finite scores. A bundle that loads but predicts
         garbage shapes is rejected here.
      -> state "ready":      PredictionServer.swap_model flips the
         model reference under its lock. The batcher reads the
         reference once per dispatched batch, so every response is
         attributable to exactly one fingerprint (old or new, never a
         mix within a response), and the PR-8 fingerprint cache keying
         guarantees no stale cache hits.
      -> state "failed":     the OLD model is still serving, untouched;
         the failure reason is surfaced in /healthz
         (model.swap_status) and `serving_swap_total{outcome=failed}`.

Fault point `swap_validate` (utils/faults.py) fires at the top of the
load+validate worker so the chaos suite can prove a mid-swap fault
leaves the old model serving and the failure visible — never a torn
half-swapped server.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Callable, Optional

from code2vec_tpu import obs
from code2vec_tpu.utils.faults import fault_point


def _swap_counter(outcome: str):
    return obs.counter("serving_swap_total",
                       "live model hot-swap attempts by outcome",
                       outcome=outcome)


class SwapError(ValueError):
    """A reload request that cannot even be attempted (busy, bad
    target); maps to an HTTP 4xx, distinct from an async validation
    failure surfaced in swap status."""


class SwapManager:
    """Owns the reload worker thread and the swap status surfaced in
    /healthz. One swap in flight at a time; a second reload while one
    is loading/validating is rejected (409) rather than queued —
    deploy tooling should poll `model.swap_status` and re-issue."""

    def __init__(self, server, build_model: Optional[Callable] = None,
                 mount_index: Optional[Callable] = None):
        self.server = server
        self.config = server.config
        self.log = server.log
        # Injection seams: tests swap between in-process models (and
        # mount scripted index handles); the defaults build a
        # ReleaseModel from an artifact dir with the PR-8 load-time
        # validation and mount a fingerprint-checked RetrievalHandle.
        self._build_model = build_model or self._build_release_model
        self._mount_index = mount_index or self._mount_retrieval_index
        self._lock = threading.Lock()
        self._worker: Optional[threading.Thread] = None
        self._status = {"state": "idle", "target": None,
                        "retrieval_index": None, "error": None,
                        "completed_at": None, "swapped_fingerprint": None}

    # ------------------------------------------------------------ state

    def status(self) -> dict:
        with self._lock:
            return dict(self._status)

    def _set(self, **fields) -> None:
        with self._lock:
            self._status.update(fields)

    # -------------------------------------------------------------- API

    def request_reload(self, artifact_dir: Optional[str],
                       retrieval_index: Optional[str] = None) -> dict:
        """Kick off an async reload; returns the (new) status. Raises
        SwapError when no target is given or a swap is in flight.
        `retrieval_index` additionally mounts a rebuilt /neighbors
        index ATOMICALLY with the model flip (the index is
        fingerprint-checked against the NEW model before anything
        swaps; a mismatch fails the whole swap, old model + old index
        untouched)."""
        if not artifact_dir:
            raise SwapError(
                "no artifact to reload: POST /admin/reload with "
                '{"artifact": DIR} (SIGHUP re-reads --artifact, which '
                "this replica was not started with)")
        with self._lock:
            if self._worker is not None and self._worker.is_alive():
                raise SwapError(
                    f"a swap is already in flight "
                    f"(state={self._status['state']}, "
                    f"target={self._status['target']}); poll "
                    f"/healthz model.swap_status and retry")
            self._status.update(state="loading", target=artifact_dir,
                                retrieval_index=retrieval_index,
                                error=None, completed_at=None)
            self._worker = threading.Thread(
                target=self._reload_worker,
                args=(artifact_dir, retrieval_index),
                name="serving-swap", daemon=True)
            self._worker.start()
        return self.status()

    # ----------------------------------------------------------- worker

    def _build_release_model(self, artifact_dir: str):
        from code2vec_tpu.release.runtime import ReleaseModel
        # A COPY of the config: ReleaseModel asserts artifact authority
        # by mutating max_contexts/topk/serve_batch_size on its config,
        # and the live server's config must keep describing the model
        # actually serving until the swap commits.
        config = dataclasses.replace(self.config,
                                     serve_artifact=artifact_dir)
        return ReleaseModel(config, log=self.log)

    def _reload_worker(self, artifact_dir: str,
                       retrieval_index: Optional[str] = None) -> None:
        from code2vec_tpu.obs.flight import default_flight_recorder
        flight = default_flight_recorder()
        old_model = self.server.model
        flight.event("swap_start", target=artifact_dir,
                     retrieval_index=retrieval_index,
                     old_fingerprint=self.server.model_fingerprint)
        try:
            fault_point("swap_validate")
            new_model = self._build_model(artifact_dir)
            self._set(state="validating")
            self._validate(old_model, new_model,
                           mounting_index=retrieval_index is not None)
            # the riding index mounts (and fingerprint-checks against
            # the NEW model) BEFORE anything swaps: a bad index fails
            # the whole reload with old model + old index untouched
            handle = (self._mount_index(retrieval_index, new_model)
                      if retrieval_index else None)
        except BaseException as e:  # noqa: BLE001 — ANY load/validate
            # failure must leave the old model serving and be visible.
            _swap_counter("failed").inc()
            self._set(state="failed",
                      error=f"{type(e).__name__}: {e}",
                      completed_at=time.time())
            flight.event("swap_failed", target=artifact_dir,
                         error=f"{type(e).__name__}: {e}")
            self.log(f"Model swap to {artifact_dir} REJECTED "
                     f"({type(e).__name__}: {e}); old model "
                     f"{self.server.model_fingerprint} keeps serving")
            return
        fp = self.server.swap_model(new_model, retrieval_handle=handle)
        _swap_counter("success").inc()
        self._set(state="ready", completed_at=time.time(),
                  swapped_fingerprint=fp)
        flight.event("swap_committed", target=artifact_dir,
                     fingerprint=fp)
        self.log(f"Model swapped live to {artifact_dir} "
                 f"(fingerprint {fp})")

    def _mount_retrieval_index(self, path: str, new_model):
        from code2vec_tpu.retrieval.api import RetrievalHandle
        return RetrievalHandle.mount(
            path, new_model.model_fingerprint(),
            default_topk=getattr(self.config, "retrieval_topk", 10),
            log=self.log)

    def _validate(self, old_model, new_model,
                  mounting_index: bool = False) -> None:
        """Golden-prediction smoke batch: the new model must produce the
        same OUTPUT SCHEMA the running one does — same top-k width (a
        narrower k would silently truncate every client's list), same
        code-vector size (/embed consumers index into it), finite
        scores (a corrupt table predicts NaN, not an exception)."""
        old = old_model.smoke_schema()
        new = new_model.smoke_schema()
        if not new["scores_finite"]:
            raise SwapError(
                "smoke batch produced non-finite prediction scores "
                "(corrupt or incompatible tables)")
        for field in ("topk", "code_vector_size"):
            if new[field] != old[field]:
                raise SwapError(
                    f"output schema mismatch: new model {field}="
                    f"{new[field]} vs running model's {old[field]} — "
                    f"clients depend on the running schema; re-export "
                    f"the artifact to match or deploy as a new service")
        if not mounting_index:
            # a reload that CARRIES a new index replaces the mounted
            # one — the stale-index policy below only governs swaps
            # that would leave the old index behind
            self._validate_retrieval(new_model)

    def _validate_retrieval(self, new_model) -> None:
        """Embedding-space gate for a mounted retrieval index: a swap to
        weights whose vectors the index does not hold would have
        /neighbors comparing apples to oranges. Policy `refuse`
        (default) rejects the swap — the index is part of the serving
        contract, deploy a matching one first; policy `detach` lets the
        weights swap and PredictionServer.swap_model detaches the index
        atomically with the flip (reason in /healthz retrieval)."""
        r = getattr(self.server, "retrieval", None)
        if r is None or not r.attached:
            return
        new_fp = new_model.model_fingerprint()
        if new_fp == r.fingerprint:
            return
        policy = getattr(self.config, "retrieval_swap_policy", "refuse")
        if policy == "refuse":
            raise SwapError(
                f"mounted retrieval index holds vectors from "
                f"{r.fingerprint!r}; swapping to {new_fp!r} would serve "
                f"/neighbors from a stale embedding space. Rebuild the "
                f"index against the new model (embed + index-build) and "
                f"restart with it, or run with "
                f"--retrieval_swap_policy detach to trade /neighbors "
                f"availability for the swap")
        self.log(f"Swap to {new_fp} diverges from the mounted retrieval "
                 f"index ({r.fingerprint}); policy=detach — the index "
                 f"will detach when the swap commits")
