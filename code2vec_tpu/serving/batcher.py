"""Dynamic request batcher: coalesce concurrent predicts into device
batches under a latency budget, with bucketed context counts.

Why this shape: the device side runs ~41.3K examples/s (BENCH_EVAL.json)
but only if it is fed BATCHES — a per-request jitted call wastes the
chip on dispatch overhead, and letting every request shape hit pjit
would recompile per distinct (rows, contexts) pair. So:

- Requests (groups of extracted method lines) enqueue; a single
  dispatcher thread collects until either `max_batch_rows` rows are
  pending or the OLDEST request has waited `max_delay_s`, then runs one
  model call over the coalesced rows. A lone request on an idle server
  therefore pays at most `max_delay_s` extra latency; a busy server
  fills batches and pays none.
- The model call itself buckets the context axis (model_facade.predict
  `context_buckets`): rows are padded to the smallest configured bucket
  that fits their deepest valid context, so the number of compiled
  shapes is bounded by len(buckets) — shared with offline predict,
  which routes through the same compiled-step cache.

`submit()` returns a concurrent.futures.Future resolving to the list of
per-line results; an optional `phases` dict receives the `batch_wait`
(submit -> dispatch) and `device` SLO phases. `device` is the FULL
duration of the coalesced model call the request rode in — that is the
latency the request actually experienced (phases sum to ~total); the
per-batch cost lives in `serving_device_seconds`, and amortized
per-row cost is that divided by `serving_batch_rows`. `drain()` stops intake, flushes everything pending, and joins
the dispatcher — the SIGTERM-grace path.

Deadline propagation (serving/admission.py): `submit()` takes the
request's Deadline. A request whose remaining budget cannot cover its
context bucket's observed p95 device time is REFUSED up front
(`DeadlineInfeasible`, an honest 503 shed — coalescing it would only
burn a device slot on a guaranteed 504); a request that expires while
waiting for batch-mates settles as `DeadlineExceeded` (504) and never
reaches the device; and a request running out of coalescing slack
(remaining budget approaching its bucket's p95) forces an early
dispatch instead of waiting out the full delay budget. Per-bucket
device times come from a small rolling window of dispatched-batch
durations — no estimate, no refusal (a cold batcher never sheds on a
bogus p95).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from code2vec_tpu import obs
from code2vec_tpu.serving.admission import (
    Deadline, DeadlineExceeded, DeadlineInfeasible, expired_counter,
)

_H_BATCH_ROWS = obs.histogram(
    "serving_batch_rows",
    "rows per dispatched device batch (coalescing effectiveness)",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024))
_H_BATCH_WAIT = obs.histogram(
    "serving_batch_wait_seconds",
    "request submit to device-batch dispatch (coalescing delay)")
_H_DEVICE = obs.histogram(
    "serving_device_seconds",
    "one coalesced model call: parse + pad + device step + unpack")
_C_BATCHES = obs.counter("serving_batches_total",
                         "device batches dispatched by the batcher")
_C_ROWS = obs.counter("serving_batch_rows_total",
                      "method rows pushed through the batcher")


def parse_buckets(spec, max_contexts: int, cp: int = 1) -> Tuple[int, ...]:
    """Normalize a bucket spec ("32,64,128" string or int sequence) into
    a sorted tuple capped at `max_contexts` (always included, so every
    legal row fits some bucket) and filtered to multiples of the
    context-parallel degree (a cp-sharded step needs the context axis
    divisible by cp)."""
    if isinstance(spec, str):
        vals = [int(v) for v in spec.replace(" ", "").split(",") if v]
    else:
        vals = [int(v) for v in (spec or ())]
    vals = sorted({v for v in vals if 0 < v < max_contexts
                   and v % max(cp, 1) == 0})
    return tuple(vals) + (max_contexts,)


def bucket_for(n_contexts: int, buckets: Sequence[int]) -> int:
    """Smallest bucket holding a row whose deepest valid context sits at
    index n_contexts-1. Callers guarantee buckets[-1] == max_contexts."""
    for b in buckets:
        if b >= n_contexts:
            return b
    return buckets[-1]


class _Pending:
    __slots__ = ("lines", "future", "t_submit", "phases", "deadline",
                 "bucket", "trace")

    def __init__(self, lines: List[str], phases: Optional[dict],
                 deadline: Optional[Deadline] = None,
                 bucket: Optional[int] = None,
                 trace=None):
        self.lines = lines
        self.future: Future = Future()
        self.t_submit = time.perf_counter()
        self.phases = phases
        self.deadline = deadline
        self.bucket = bucket
        self.trace = trace


class _DeviceTimeTracker:
    """Rolling per-bucket device-call durations -> p95 estimate. Small
    fixed windows (32 samples) so the estimate tracks the CURRENT
    device behavior — a transient slowdown ages out in 32 batches."""

    MIN_SAMPLES = 4

    def __init__(self, window: int = 32):
        self._window = window
        self._lock = threading.Lock()
        self._samples: Dict[Optional[int], deque] = {}

    def record(self, bucket: Optional[int], duration_s: float) -> None:
        with self._lock:
            d = self._samples.get(bucket)
            if d is None:
                d = self._samples[bucket] = deque(maxlen=self._window)
            d.append(float(duration_s))

    def p95(self, bucket: Optional[int]) -> Optional[float]:
        with self._lock:
            d = self._samples.get(bucket)
            if d is None or len(d) < self.MIN_SAMPLES:
                return None
            ordered = sorted(d)
            return ordered[min(int(round(0.95 * (len(ordered) - 1))),
                               len(ordered) - 1)]


class DynamicBatcher:
    """Single dispatcher thread over a condition-guarded pending queue.

    `predict_fn(lines) -> List[result]` is the facade's batched predict:
    it must return exactly one result per input line, in order. All
    pending groups are dispatched together in FIFO order up to
    `max_batch_rows` rows; one oversized group (a file with more methods
    than the cap) dispatches alone — predict_fn chunks internally, so
    correctness never depends on the cap.
    """

    def __init__(self, predict_fn: Callable[[List[str]], List],
                 max_batch_rows: int = 64, max_delay_s: float = 0.01,
                 buckets: Optional[Sequence[int]] = None):
        self.predict_fn = predict_fn
        self.max_batch_rows = max(1, int(max_batch_rows))
        self.max_delay_s = max(0.0, float(max_delay_s))
        # Context-bucket list (model.context_buckets) for per-bucket
        # device-time estimates; None = one global estimate (the
        # standalone/unit-test construction).
        self.buckets = tuple(buckets) if buckets else None
        self.device_times = _DeviceTimeTracker()
        self._cond = threading.Condition()
        self._pending: List[_Pending] = []
        self._pending_rows = 0
        self._draining = False
        self._closed = False
        self.batches_dispatched = 0
        self._thread = threading.Thread(target=self._run,
                                        name="serving-batcher", daemon=True)
        self._thread.start()

    # -------------------------------------------------------------- API

    def _bucket_of(self, lines: Sequence[str]) -> Optional[int]:
        """Context bucket this request's rows would pad to (the deepest
        line decides, exactly as model_facade._predict_chunk buckets a
        chunk). Extractor lines are space-separated `name ctx ctx ...`
        padded with trailing blanks, so a whitespace split counts the
        real contexts."""
        if self.buckets is None:
            return None
        deepest = max((len(line.split()) - 1 for line in lines),
                      default=1)
        return bucket_for(max(deepest, 1), self.buckets)

    def submit(self, lines: Sequence[str],
               phases: Optional[dict] = None,
               deadline: Optional[Deadline] = None,
               trace=None) -> Future:
        item = _Pending(list(lines), phases, deadline, trace=trace)
        if not item.lines:
            item.future.set_result([])
            return item.future
        if deadline is not None and deadline.bounded:
            if deadline.expired():
                expired_counter("batch_wait").inc()
                item.future.set_exception(DeadlineExceeded(
                    "request deadline expired before batching"))
                return item.future
            item.bucket = self._bucket_of(item.lines)
            p95 = self.device_times.p95(item.bucket)
            if p95 is not None and deadline.remaining() < p95:
                # Fail-fast refusal: even an immediate solo dispatch
                # cannot finish inside the budget, so coalescing this
                # request would spend a device slot on a sure 504.
                item.future.set_exception(DeadlineInfeasible(
                    f"remaining deadline budget "
                    f"{deadline.remaining() * 1e3:.0f}ms is below the "
                    f"bucket's observed p95 device time "
                    f"{p95 * 1e3:.0f}ms", retry_after_s=p95))
                return item.future
        elif self.buckets is not None:
            item.bucket = self._bucket_of(item.lines)
        with self._cond:
            if self._draining:
                item.future.set_exception(
                    RuntimeError("batcher is draining; not accepting "
                                 "new requests"))
                return item.future
            self._pending.append(item)
            self._pending_rows += len(item.lines)
            self._cond.notify_all()
        return item.future

    def rebucket(self, buckets: Optional[Sequence[int]]) -> None:
        """Hot-swap support: adopt a new model's context-bucket grid
        and drop the device-time samples keyed to the old one (a cold
        tracker refuses nothing until it has real samples; stale p95s
        on a changed grid would misprice every feasibility check)."""
        self.buckets = tuple(buckets) if buckets else None
        self.device_times = _DeviceTimeTracker()

    def drain(self, timeout: Optional[float] = None) -> None:
        """Stop intake, flush every pending request, join the thread.
        Idempotent; safe from signal-handler-adjacent threads."""
        with self._cond:
            self._draining = True
            self._cond.notify_all()
        self._thread.join(timeout)

    # -------------------------------------------------------- dispatcher

    def _run(self) -> None:
        while True:
            batch = self._collect()
            if batch is None:
                return
            self._dispatch(batch)

    def _collect(self) -> Optional[List[_Pending]]:
        """Block until a batch is due: rows >= cap, oldest item older
        than max_delay_s, any pending item out of coalescing slack
        (its remaining deadline budget is down to its bucket's p95
        device time), or draining (flush everything). Expired items are
        settled as 504 here, before they can occupy a device slot."""
        with self._cond:
            while True:
                if self._pending:
                    self._expire_locked()
                    if not self._pending:
                        continue
                    if (self._draining
                            or self._pending_rows >= self.max_batch_rows):
                        return self._take_locked()
                    age = time.perf_counter() - self._pending[0].t_submit
                    wait = self.max_delay_s - age
                    for item in self._pending:
                        if item.deadline is None \
                                or not item.deadline.bounded:
                            continue
                        remaining = item.deadline.remaining()
                        p95 = self.device_times.p95(item.bucket) or 0.0
                        # slack = budget left after the device call;
                        # once it's gone, waiting for batch-mates turns
                        # a servable request into a 504.
                        wait = min(wait, remaining - p95, remaining)
                    if wait <= 0:
                        return self._take_locked()
                    self._cond.wait(timeout=wait)
                elif self._draining:
                    self._closed = True
                    return None
                else:
                    self._cond.wait()

    def _expire_locked(self) -> None:
        alive: List[_Pending] = []
        for item in self._pending:
            if item.deadline is not None and item.deadline.expired():
                self._pending_rows -= len(item.lines)
                expired_counter("batch_wait").inc()
                if item.future.set_running_or_notify_cancel():
                    item.future.set_exception(DeadlineExceeded(
                        "request deadline expired while waiting for "
                        "batch-mates"))
            else:
                alive.append(item)
        self._pending = alive

    def _take_locked(self) -> List[_Pending]:
        take: List[_Pending] = []
        rows = 0
        while self._pending:
            nxt = self._pending[0]
            if take and rows + len(nxt.lines) > self.max_batch_rows:
                break
            take.append(self._pending.pop(0))
            rows += len(nxt.lines)
        self._pending_rows -= rows
        return take

    def _dispatch(self, batch: List[_Pending]) -> None:
        t_dispatch = time.perf_counter()
        # Last expiry check before device work: an item that ran out of
        # budget between collection and dispatch settles as 504 here
        # rather than burning rows in the device batch.
        live: List[_Pending] = []
        for item in batch:
            if item.deadline is not None and item.deadline.expired():
                expired_counter("batch_wait").inc()
                if item.future.set_running_or_notify_cancel():
                    item.future.set_exception(DeadlineExceeded(
                        "request deadline expired at dispatch"))
            else:
                live.append(item)
        batch = live
        if not batch:
            return
        all_lines: List[str] = []
        for item in batch:
            wait = t_dispatch - item.t_submit
            _H_BATCH_WAIT.observe(wait)
            if item.phases is not None:
                item.phases["batch_wait"] = wait
            if item.trace is not None:
                item.trace.add_span("batch_wait", item.t_submit, wait)
            all_lines.extend(item.lines)
        _C_BATCHES.inc()
        self.batches_dispatched += 1
        batch_id = self.batches_dispatched
        _C_ROWS.inc(len(all_lines))
        _H_BATCH_ROWS.observe(len(all_lines))
        try:
            results = self.predict_fn(all_lines)
            if len(results) != len(all_lines):
                raise RuntimeError(
                    f"predict_fn returned {len(results)} results for "
                    f"{len(all_lines)} lines")
        except BaseException as e:  # noqa: BLE001 — futures must settle
            for item in batch:
                if not item.future.set_running_or_notify_cancel():
                    continue
                item.future.set_exception(e)
            return
        dur = time.perf_counter() - t_dispatch
        _H_DEVICE.observe(dur)
        # The deepest bucket in the batch is the shape the device call
        # compiled/ran at — that is the bucket this duration informs.
        batch_bucket = max((i.bucket for i in batch
                            if i.bucket is not None), default=None)
        self.device_times.record(batch_bucket, dur)
        self._record_batch_spans(batch, batch_id, batch_bucket,
                                 len(all_lines), t_dispatch, dur)
        off = 0
        for item in batch:
            n = len(item.lines)
            if item.phases is not None:
                item.phases["device"] = dur
            if item.future.set_running_or_notify_cancel():
                item.future.set_result(results[off:off + n])
            off += n

    def _record_batch_spans(self, batch: List[_Pending], batch_id: int,
                            bucket: Optional[int], rows: int,
                            t_dispatch: float, dur: float) -> None:
        """Fan the coalesced device call into the member traces: ONE
        shared batch span id is stamped into every member request's
        trace (the batch node N request trees share), each member's
        `device` span hangs under it, and the process tracer records the
        batch exactly once — tagged with every member trace id so the
        bulk Chrome trace links batch to requests."""
        traced = [item for item in batch if item.trace is not None]
        if not traced:
            return
        from code2vec_tpu.obs import reqtrace, tracer
        batch_span_id = reqtrace.mint_span_id()
        members = [item.trace.trace_id for item in traced]
        attrs = {"batch_id": batch_id, "rows": rows,
                 "requests": len(batch)}
        if bucket is not None:
            attrs["bucket"] = bucket
        for item in traced:
            # every member's batch-span attrs hold a REFERENCE to the
            # one shared members list (O(rows) per batch, not O(rows^2));
            # it only gets serialized per response on the
            # --serve_debug_trace + ?debug=trace path
            item.trace.add_span("batch", t_dispatch, dur,
                                span_id=batch_span_id,
                                attrs=dict(attrs, members=members),
                                forward=False)
            item.trace.add_span("device", t_dispatch, dur,
                                parent_id=batch_span_id)
        tracer.default_tracer().maybe_record(
            "serving_batch", t_dispatch, dur, span_id=batch_span_id,
            attrs=dict(attrs, member_trace_ids=members))
