"""Dynamic request batcher: coalesce concurrent predicts into device
batches under a latency budget, with bucketed context counts.

Why this shape: the device side runs ~41.3K examples/s (BENCH_EVAL.json)
but only if it is fed BATCHES — a per-request jitted call wastes the
chip on dispatch overhead, and letting every request shape hit pjit
would recompile per distinct (rows, contexts) pair. So:

- Requests (groups of extracted method lines) enqueue; a single
  dispatcher thread collects until either `max_batch_rows` rows are
  pending or the OLDEST request has waited `max_delay_s`, then runs one
  model call over the coalesced rows. A lone request on an idle server
  therefore pays at most `max_delay_s` extra latency; a busy server
  fills batches and pays none.
- The model call itself buckets the context axis (model_facade.predict
  `context_buckets`): rows are padded to the smallest configured bucket
  that fits their deepest valid context, so the number of compiled
  shapes is bounded by len(buckets) — shared with offline predict,
  which routes through the same compiled-step cache.

`submit()` returns a concurrent.futures.Future resolving to the list of
per-line results; an optional `phases` dict receives the `batch_wait`
(submit -> dispatch) and `device` SLO phases. `device` is the FULL
duration of the coalesced model call the request rode in — that is the
latency the request actually experienced (phases sum to ~total); the
per-batch cost lives in `serving_device_seconds`, and amortized
per-row cost is that divided by `serving_batch_rows`. `drain()` stops intake, flushes everything pending, and joins
the dispatcher — the SIGTERM-grace path.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future
from typing import Callable, List, Optional, Sequence, Tuple

from code2vec_tpu import obs

_H_BATCH_ROWS = obs.histogram(
    "serving_batch_rows",
    "rows per dispatched device batch (coalescing effectiveness)",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024))
_H_BATCH_WAIT = obs.histogram(
    "serving_batch_wait_seconds",
    "request submit to device-batch dispatch (coalescing delay)")
_H_DEVICE = obs.histogram(
    "serving_device_seconds",
    "one coalesced model call: parse + pad + device step + unpack")
_C_BATCHES = obs.counter("serving_batches_total",
                         "device batches dispatched by the batcher")
_C_ROWS = obs.counter("serving_batch_rows_total",
                      "method rows pushed through the batcher")


def parse_buckets(spec, max_contexts: int, cp: int = 1) -> Tuple[int, ...]:
    """Normalize a bucket spec ("32,64,128" string or int sequence) into
    a sorted tuple capped at `max_contexts` (always included, so every
    legal row fits some bucket) and filtered to multiples of the
    context-parallel degree (a cp-sharded step needs the context axis
    divisible by cp)."""
    if isinstance(spec, str):
        vals = [int(v) for v in spec.replace(" ", "").split(",") if v]
    else:
        vals = [int(v) for v in (spec or ())]
    vals = sorted({v for v in vals if 0 < v < max_contexts
                   and v % max(cp, 1) == 0})
    return tuple(vals) + (max_contexts,)


def bucket_for(n_contexts: int, buckets: Sequence[int]) -> int:
    """Smallest bucket holding a row whose deepest valid context sits at
    index n_contexts-1. Callers guarantee buckets[-1] == max_contexts."""
    for b in buckets:
        if b >= n_contexts:
            return b
    return buckets[-1]


class _Pending:
    __slots__ = ("lines", "future", "t_submit", "phases")

    def __init__(self, lines: List[str], phases: Optional[dict]):
        self.lines = lines
        self.future: Future = Future()
        self.t_submit = time.perf_counter()
        self.phases = phases


class DynamicBatcher:
    """Single dispatcher thread over a condition-guarded pending queue.

    `predict_fn(lines) -> List[result]` is the facade's batched predict:
    it must return exactly one result per input line, in order. All
    pending groups are dispatched together in FIFO order up to
    `max_batch_rows` rows; one oversized group (a file with more methods
    than the cap) dispatches alone — predict_fn chunks internally, so
    correctness never depends on the cap.
    """

    def __init__(self, predict_fn: Callable[[List[str]], List],
                 max_batch_rows: int = 64, max_delay_s: float = 0.01):
        self.predict_fn = predict_fn
        self.max_batch_rows = max(1, int(max_batch_rows))
        self.max_delay_s = max(0.0, float(max_delay_s))
        self._cond = threading.Condition()
        self._pending: List[_Pending] = []
        self._pending_rows = 0
        self._draining = False
        self._closed = False
        self.batches_dispatched = 0
        self._thread = threading.Thread(target=self._run,
                                        name="serving-batcher", daemon=True)
        self._thread.start()

    # -------------------------------------------------------------- API

    def submit(self, lines: Sequence[str],
               phases: Optional[dict] = None) -> Future:
        item = _Pending(list(lines), phases)
        if not item.lines:
            item.future.set_result([])
            return item.future
        with self._cond:
            if self._draining:
                item.future.set_exception(
                    RuntimeError("batcher is draining; not accepting "
                                 "new requests"))
                return item.future
            self._pending.append(item)
            self._pending_rows += len(item.lines)
            self._cond.notify_all()
        return item.future

    def drain(self, timeout: Optional[float] = None) -> None:
        """Stop intake, flush every pending request, join the thread.
        Idempotent; safe from signal-handler-adjacent threads."""
        with self._cond:
            self._draining = True
            self._cond.notify_all()
        self._thread.join(timeout)

    # -------------------------------------------------------- dispatcher

    def _run(self) -> None:
        while True:
            batch = self._collect()
            if batch is None:
                return
            self._dispatch(batch)

    def _collect(self) -> Optional[List[_Pending]]:
        """Block until a batch is due: rows >= cap, oldest item older
        than max_delay_s, or draining (flush everything)."""
        with self._cond:
            while True:
                if self._pending:
                    if (self._draining
                            or self._pending_rows >= self.max_batch_rows):
                        return self._take_locked()
                    age = time.perf_counter() - self._pending[0].t_submit
                    remaining = self.max_delay_s - age
                    if remaining <= 0:
                        return self._take_locked()
                    self._cond.wait(timeout=remaining)
                elif self._draining:
                    self._closed = True
                    return None
                else:
                    self._cond.wait()

    def _take_locked(self) -> List[_Pending]:
        take: List[_Pending] = []
        rows = 0
        while self._pending:
            nxt = self._pending[0]
            if take and rows + len(nxt.lines) > self.max_batch_rows:
                break
            take.append(self._pending.pop(0))
            rows += len(nxt.lines)
        self._pending_rows -= rows
        return take

    def _dispatch(self, batch: List[_Pending]) -> None:
        t_dispatch = time.perf_counter()
        all_lines: List[str] = []
        for item in batch:
            wait = t_dispatch - item.t_submit
            _H_BATCH_WAIT.observe(wait)
            if item.phases is not None:
                item.phases["batch_wait"] = wait
            all_lines.extend(item.lines)
        _C_BATCHES.inc()
        self.batches_dispatched += 1
        _C_ROWS.inc(len(all_lines))
        _H_BATCH_ROWS.observe(len(all_lines))
        try:
            results = self.predict_fn(all_lines)
            if len(results) != len(all_lines):
                raise RuntimeError(
                    f"predict_fn returned {len(results)} results for "
                    f"{len(all_lines)} lines")
        except BaseException as e:  # noqa: BLE001 — futures must settle
            for item in batch:
                if not item.future.set_running_or_notify_cancel():
                    continue
                item.future.set_exception(e)
            return
        dur = time.perf_counter() - t_dispatch
        _H_DEVICE.observe(dur)
        off = 0
        for item in batch:
            n = len(item.lines)
            if item.phases is not None:
                item.phases["device"] = dur
            if item.future.set_running_or_notify_cancel():
                item.future.set_result(results[off:off + n])
            off += n
