"""Dynamic request batcher: coalesce concurrent predicts into device
batches under a latency budget, with bucketed context counts.

Why this shape: the device side runs ~41.3K examples/s (BENCH_EVAL.json)
but only if it is fed BATCHES — a per-request jitted call wastes the
chip on dispatch overhead, and letting every request shape hit pjit
would recompile per distinct (rows, contexts) pair. So:

- Requests (groups of extracted method lines) enqueue; a single
  dispatcher thread collects until either `max_batch_rows` rows are
  pending or the OLDEST request has waited `max_delay_s`, then runs one
  model call over the coalesced rows. A lone request on an idle server
  therefore pays at most `max_delay_s` extra latency; a busy server
  fills batches and pays none.
- The model call itself buckets the context axis (model_facade.predict
  `context_buckets`): rows are padded to the smallest configured bucket
  that fits their deepest valid context, so the number of compiled
  shapes is bounded by len(buckets) — shared with offline predict,
  which routes through the same compiled-step cache.

`submit()` returns a concurrent.futures.Future resolving to the list of
per-line results; an optional `phases` dict receives the `batch_wait`
(submit -> dispatch) and `device` SLO phases. `device` is the FULL
duration of the coalesced model call the request rode in — that is the
latency the request actually experienced (phases sum to ~total); the
per-batch cost lives in `serving_device_seconds`, and amortized
per-row cost is that divided by `serving_batch_rows`. `drain()` stops intake, flushes everything pending, and joins
the dispatcher — the SIGTERM-grace path.

Deadline propagation (serving/admission.py): `submit()` takes the
request's Deadline. A request whose remaining budget cannot cover its
context bucket's observed p95 device time is REFUSED up front
(`DeadlineInfeasible`, an honest 503 shed — coalescing it would only
burn a device slot on a guaranteed 504); a request that expires while
waiting for batch-mates settles as `DeadlineExceeded` (504) and never
reaches the device; and a request running out of coalescing slack
(remaining budget approaching its bucket's p95) forces an early
dispatch instead of waiting out the full delay budget. Per-bucket
device times come from a small rolling window of dispatched-batch
durations — no estimate, no refusal (a cold batcher never sheds on a
bogus p95).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from code2vec_tpu import obs
from code2vec_tpu.serving.admission import (
    Deadline, DeadlineExceeded, DeadlineInfeasible, expired_counter,
)

_H_BATCH_ROWS = obs.histogram(
    "serving_batch_rows",
    "rows per dispatched device batch (coalescing effectiveness)",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024))
_H_BATCH_WAIT = obs.histogram(
    "serving_batch_wait_seconds",
    "request submit to device-batch dispatch (coalescing delay)")
_H_DEVICE = obs.histogram(
    "serving_device_seconds",
    "one coalesced model call: parse + pad + device step + unpack")
_C_BATCHES = obs.counter("serving_batches_total",
                         "device batches dispatched by the batcher")
_C_ROWS = obs.counter("serving_batch_rows_total",
                      "method rows pushed through the batcher")
_G_INFLIGHT = obs.gauge(
    "serving_batch_inflight_steps",
    "device steps currently in flight (continuous batching)")
_C_RIDES = obs.counter(
    "serving_batch_inflight_rides_total",
    "admissions that rode an in-flight dispatch window instead of "
    "opening a fresh delay window (continuous batching)")


def parse_buckets(spec, max_contexts: int, cp: int = 1) -> Tuple[int, ...]:
    """Normalize a bucket spec ("32,64,128" string or int sequence) into
    a sorted tuple capped at `max_contexts` (always included, so every
    legal row fits some bucket) and filtered to multiples of the
    context-parallel degree (a cp-sharded step needs the context axis
    divisible by cp)."""
    if isinstance(spec, str):
        vals = [int(v) for v in spec.replace(" ", "").split(",") if v]
    else:
        vals = [int(v) for v in (spec or ())]
    vals = sorted({v for v in vals if 0 < v < max_contexts
                   and v % max(cp, 1) == 0})
    return tuple(vals) + (max_contexts,)


def bucket_for(n_contexts: int, buckets: Sequence[int]) -> int:
    """Smallest bucket holding a row whose deepest valid context sits at
    index n_contexts-1. Callers guarantee buckets[-1] == max_contexts."""
    for b in buckets:
        if b >= n_contexts:
            return b
    return buckets[-1]


class _Pending:
    __slots__ = ("lines", "future", "t_submit", "phases", "deadline",
                 "bucket", "trace", "settled", "tenant")

    def __init__(self, lines: List[str], phases: Optional[dict],
                 deadline: Optional[Deadline] = None,
                 bucket: Optional[int] = None,
                 trace=None, tenant: Optional[str] = None):
        self.lines = lines
        self.future: Future = Future()
        self.t_submit = time.perf_counter()
        self.phases = phases
        self.deadline = deadline
        self.bucket = bucket
        self.trace = trace
        # collapsed tenant label (serving/tenancy.py) — the batchers'
        # DWRR fill and per-slot share caps key on it; None when the
        # tenancy layer is off
        self.tenant = tenant
        # continuous batcher: an item settled early (504 / parse error)
        # stays in its slot (its rows are reserved in the fixed-shape
        # buffer, mask-zeroed) but is skipped at result fan-out
        self.settled = False


class _DeviceTimeTracker:
    """Rolling per-bucket device-call durations -> p95 estimate. Small
    fixed windows (32 samples) so the estimate tracks the CURRENT
    device behavior — a transient slowdown ages out in 32 batches."""

    MIN_SAMPLES = 4

    def __init__(self, window: int = 32):
        self._window = window
        self._lock = threading.Lock()
        self._samples: Dict[Optional[int], deque] = {}
        # p95 runs on EVERY bounded-deadline admission but samples only
        # arrive once per dispatched batch, so the sorted view is cached
        # per bucket and invalidated on record() — the admission path is
        # O(1) dict lookups unless a new sample landed since last read.
        self._sorted: Dict[Optional[int], List[float]] = {}

    def record(self, bucket: Optional[int], duration_s: float) -> None:
        with self._lock:
            d = self._samples.get(bucket)
            if d is None:
                d = self._samples[bucket] = deque(maxlen=self._window)
            d.append(float(duration_s))
            self._sorted.pop(bucket, None)

    def p95(self, bucket: Optional[int]) -> Optional[float]:
        with self._lock:
            d = self._samples.get(bucket)
            if d is None or len(d) < self.MIN_SAMPLES:
                return None
            ordered = self._sorted.get(bucket)
            if ordered is None:
                ordered = self._sorted[bucket] = sorted(d)
            return ordered[min(int(round(0.95 * (len(ordered) - 1))),
                               len(ordered) - 1)]


class DynamicBatcher:
    """Single dispatcher thread over a condition-guarded pending queue.

    `predict_fn(lines) -> List[result]` is the facade's batched predict:
    it must return exactly one result per input line, in order. All
    pending groups are dispatched together in FIFO order up to
    `max_batch_rows` rows; one oversized group (a file with more methods
    than the cap) dispatches alone — predict_fn chunks internally, so
    correctness never depends on the cap.

    With `tenancy` (serving/tenancy.TenantPolicy) a batch with MORE
    than one tenant pending fills in deficit-weighted-round-robin
    order across per-tenant sub-queues (tenancy.dwrr_take) instead of
    global FIFO, so one tenant's backlog cannot monopolize a device
    batch; a single tenant (or no policy) keeps the exact FIFO path.
    """

    def __init__(self, predict_fn: Callable[[List[str]], List],
                 max_batch_rows: int = 64, max_delay_s: float = 0.01,
                 buckets: Optional[Sequence[int]] = None,
                 tenancy=None):
        self.predict_fn = predict_fn
        self.max_batch_rows = max(1, int(max_batch_rows))
        self.max_delay_s = max(0.0, float(max_delay_s))
        self.tenancy = tenancy
        self._dwrr_state: dict = {}
        # Context-bucket list (model.context_buckets) for per-bucket
        # device-time estimates; None = one global estimate (the
        # standalone/unit-test construction).
        self.buckets = tuple(buckets) if buckets else None
        self.device_times = _DeviceTimeTracker()
        self._cond = threading.Condition()
        self._pending: List[_Pending] = []
        self._pending_rows = 0
        self._draining = False
        self._closed = False
        self.batches_dispatched = 0
        self._thread = threading.Thread(target=self._run,
                                        name="serving-batcher", daemon=True)
        self._thread.start()

    # -------------------------------------------------------------- API

    def _bucket_of(self, lines: Sequence[str]) -> Optional[int]:
        """Context bucket this request's rows would pad to (the deepest
        line decides, exactly as model_facade._predict_chunk buckets a
        chunk). Extractor lines are space-separated `name ctx ctx ...`
        padded with trailing blanks, so a whitespace split counts the
        real contexts."""
        if self.buckets is None:
            return None
        deepest = max((len(line.split()) - 1 for line in lines),
                      default=1)
        return bucket_for(max(deepest, 1), self.buckets)

    def submit(self, lines: Sequence[str],
               phases: Optional[dict] = None,
               deadline: Optional[Deadline] = None,
               trace=None, tenant: Optional[str] = None) -> Future:
        item = _Pending(list(lines), phases, deadline, trace=trace,
                        tenant=tenant)
        if not item.lines:
            item.future.set_result([])
            return item.future
        if deadline is not None and deadline.bounded:
            if deadline.expired():
                expired_counter("batch_wait").inc()
                item.future.set_exception(DeadlineExceeded(
                    "request deadline expired before batching"))
                return item.future
            item.bucket = self._bucket_of(item.lines)
            p95 = self.device_times.p95(item.bucket)
            if p95 is not None and deadline.remaining() < p95:
                # Fail-fast refusal: even an immediate solo dispatch
                # cannot finish inside the budget, so coalescing this
                # request would spend a device slot on a sure 504.
                item.future.set_exception(DeadlineInfeasible(
                    f"remaining deadline budget "
                    f"{deadline.remaining() * 1e3:.0f}ms is below the "
                    f"bucket's observed p95 device time "
                    f"{p95 * 1e3:.0f}ms", retry_after_s=p95))
                return item.future
        elif self.buckets is not None:
            item.bucket = self._bucket_of(item.lines)
        with self._cond:
            if self._draining:
                item.future.set_exception(
                    RuntimeError("batcher is draining; not accepting "
                                 "new requests"))
                return item.future
            self._pending.append(item)
            self._pending_rows += len(item.lines)
            self._cond.notify_all()
        return item.future

    def rebucket(self, buckets: Optional[Sequence[int]]) -> None:
        """Hot-swap support: adopt a new model's context-bucket grid
        and drop the device-time samples keyed to the old one (a cold
        tracker refuses nothing until it has real samples; stale p95s
        on a changed grid would misprice every feasibility check)."""
        self.buckets = tuple(buckets) if buckets else None
        self.device_times = _DeviceTimeTracker()

    def drain(self, timeout: Optional[float] = None) -> None:
        """Stop intake, flush every pending request, join the thread.
        Idempotent; safe from signal-handler-adjacent threads."""
        with self._cond:
            self._draining = True
            self._cond.notify_all()
        self._thread.join(timeout)

    # -------------------------------------------------------- dispatcher

    def _run(self) -> None:
        while True:
            batch = self._collect()
            if batch is None:
                return
            self._dispatch(batch)

    def _collect(self) -> Optional[List[_Pending]]:
        """Block until a batch is due: rows >= cap, oldest item older
        than max_delay_s, any pending item out of coalescing slack
        (its remaining deadline budget is down to its bucket's p95
        device time), or draining (flush everything). Expired items are
        settled as 504 here, before they can occupy a device slot."""
        with self._cond:
            while True:
                if self._pending:
                    self._expire_locked()
                    if not self._pending:
                        continue
                    if (self._draining
                            or self._pending_rows >= self.max_batch_rows):
                        return self._take_locked()
                    age = time.perf_counter() - self._pending[0].t_submit
                    wait = self.max_delay_s - age
                    for item in self._pending:
                        if item.deadline is None \
                                or not item.deadline.bounded:
                            continue
                        remaining = item.deadline.remaining()
                        p95 = self.device_times.p95(item.bucket) or 0.0
                        # slack = budget left after the device call;
                        # once it's gone, waiting for batch-mates turns
                        # a servable request into a 504.
                        wait = min(wait, remaining - p95, remaining)
                    if wait <= 0:
                        return self._take_locked()
                    self._cond.wait(timeout=wait)
                elif self._draining:
                    self._closed = True
                    return None
                else:
                    self._cond.wait()

    def _expire_locked(self) -> None:
        alive: List[_Pending] = []
        for item in self._pending:
            if item.deadline is not None and item.deadline.expired():
                self._pending_rows -= len(item.lines)
                expired_counter("batch_wait").inc()
                if item.future.set_running_or_notify_cancel():
                    item.future.set_exception(DeadlineExceeded(
                        "request deadline expired while waiting for "
                        "batch-mates"))
            else:
                alive.append(item)
        self._pending = alive

    def _take_locked(self) -> List[_Pending]:
        if self.tenancy is not None:
            from code2vec_tpu.serving.tenancy import dwrr_take
            picked = dwrr_take(self._pending, self.max_batch_rows,
                               self.tenancy.weight, self._dwrr_state)
            if picked is not None:
                # >1 tenant pending: weighted-fair interleave. None ⇒
                # a single tenant's queue — the FIFO loop below is
                # byte-identical to the tenancy-free batcher.
                chosen = set(picked)
                take = [self._pending[i] for i in picked]
                self._pending = [item for j, item
                                 in enumerate(self._pending)
                                 if j not in chosen]
                self._pending_rows -= sum(len(i.lines) for i in take)
                return take
        take: List[_Pending] = []
        rows = 0
        while self._pending:
            nxt = self._pending[0]
            if take and rows + len(nxt.lines) > self.max_batch_rows:
                break
            take.append(self._pending.pop(0))
            rows += len(nxt.lines)
        self._pending_rows -= rows
        return take

    def _dispatch(self, batch: List[_Pending]) -> None:
        t_dispatch = time.perf_counter()
        # Last expiry check before device work: an item that ran out of
        # budget between collection and dispatch settles as 504 here
        # rather than burning rows in the device batch.
        live: List[_Pending] = []
        for item in batch:
            if item.deadline is not None and item.deadline.expired():
                expired_counter("batch_wait").inc()
                if item.future.set_running_or_notify_cancel():
                    item.future.set_exception(DeadlineExceeded(
                        "request deadline expired at dispatch"))
            else:
                live.append(item)
        batch = live
        if not batch:
            return
        all_lines: List[str] = []
        for item in batch:
            wait = t_dispatch - item.t_submit
            _H_BATCH_WAIT.observe(wait)
            if item.phases is not None:
                item.phases["batch_wait"] = wait
            if item.trace is not None:
                item.trace.add_span("batch_wait", item.t_submit, wait)
            all_lines.extend(item.lines)
        _C_BATCHES.inc()
        self.batches_dispatched += 1
        batch_id = self.batches_dispatched
        _C_ROWS.inc(len(all_lines))
        _H_BATCH_ROWS.observe(len(all_lines))
        try:
            results = self.predict_fn(all_lines)
            if len(results) != len(all_lines):
                raise RuntimeError(
                    f"predict_fn returned {len(results)} results for "
                    f"{len(all_lines)} lines")
        except BaseException as e:  # noqa: BLE001 — futures must settle
            for item in batch:
                if not item.future.set_running_or_notify_cancel():
                    continue
                item.future.set_exception(e)
            return
        dur = time.perf_counter() - t_dispatch
        _H_DEVICE.observe(dur)
        # The deepest bucket in the batch is the shape the device call
        # compiled/ran at — that is the bucket this duration informs.
        batch_bucket = max((i.bucket for i in batch
                            if i.bucket is not None), default=None)
        self.device_times.record(batch_bucket, dur)
        self._record_batch_spans(batch, batch_id, batch_bucket,
                                 len(all_lines), t_dispatch, dur)
        off = 0
        for item in batch:
            n = len(item.lines)
            if item.phases is not None:
                item.phases["device"] = dur
            if item.future.set_running_or_notify_cancel():
                item.future.set_result(results[off:off + n])
            off += n

    def _record_batch_spans(self, batch: List[_Pending], batch_id: int,
                            bucket: Optional[int], rows: int,
                            t_dispatch: float, dur: float) -> None:
        _record_batch_spans(batch, batch_id, bucket, rows, t_dispatch,
                            dur)


def _record_batch_spans(batch: List[_Pending], batch_id: int,
                        bucket: Optional[int], rows: int,
                        t_dispatch: float, dur: float) -> None:
    """Fan the coalesced device call into the member traces: ONE
    shared batch span id is stamped into every member request's
    trace (the batch node N request trees share), each member's
    `device` span hangs under it, and the process tracer records the
    batch exactly once — tagged with every member trace id so the
    bulk Chrome trace links batch to requests."""
    traced = [item for item in batch if item.trace is not None]
    if not traced:
        return
    from code2vec_tpu.obs import reqtrace, tracer
    batch_span_id = reqtrace.mint_span_id()
    members = [item.trace.trace_id for item in traced]
    attrs = {"batch_id": batch_id, "rows": rows,
             "requests": len(batch)}
    if bucket is not None:
        attrs["bucket"] = bucket
    # reqtrace stores attrs BY REFERENCE, so the whole batch shares ONE
    # attrs dict built here on the dispatch thread (N spans, one dict +
    # one members list — not N dict constructions; same memoization as
    # the tracer-export fix). It only gets serialized per response on
    # the --serve_debug_trace + ?debug=trace path.
    span_attrs = dict(attrs, members=members)
    for item in traced:
        item.trace.add_span("batch", t_dispatch, dur,
                            span_id=batch_span_id,
                            attrs=span_attrs,
                            forward=False)
        item.trace.add_span("device", t_dispatch, dur,
                            parent_id=batch_span_id)
    tracer.default_tracer().maybe_record(
        "serving_batch", t_dispatch, dur, span_id=batch_span_id,
        attrs=dict(attrs, member_trace_ids=members))


class StaleParse(RuntimeError):
    """Raised by a backend's `predict_rows` when the live model's
    fingerprint no longer matches the slot's parse-time fingerprint (a
    hot-swap landed between parse and dispatch): the slot's int rows
    were built against the OLD vocab tables and must not run under the
    new weights. The worker falls back to the lines path, re-parsing
    under the current model — so the batch still answers with exactly
    one fingerprint."""


class _Slot:
    """One forming/in-flight device batch of the continuous batcher.

    `rows` rows of the fixed-shape buffer are reserved (parse writes
    land in disjoint row ranges, so only the RESERVATION is locked —
    the parse itself runs on the submitter thread outside the lock,
    tracked by `pending_writes`)."""

    __slots__ = ("kind", "items", "offsets", "rows", "buffer",
                 "pending_writes", "sealed", "chained", "t_open", "fps")

    def __init__(self, kind: str, buffer=None):
        self.kind = kind              # "rows" (zero-copy) | "lines"
        self.items: List[_Pending] = []
        self.offsets: List[Tuple[int, int]] = []   # (row_offset, n)
        self.rows = 0
        self.buffer = buffer
        self.pending_writes = 0
        self.sealed = False
        self.chained = False
        self.t_open = time.perf_counter()
        self.fps: set = set()         # model fingerprints seen at parse


class ContinuousBatcher:
    """Slot-reservation dispatcher: continuous batching for the serve
    path (--serve_continuous).

    The collect-then-dispatch DynamicBatcher holds every batch until it
    fills or ages out, so a row arriving just after a dispatch starts a
    FRESH delay window behind a device step it cannot join. Here the
    next batch is always forming: `submit()` reserves rows in the tail
    slot under the lock, parses the extractor lines straight into the
    slot's padded (rows, contexts) buffer OUTSIDE the lock (zero-copy:
    reader.parse_context_lines(out=...) — no per-request RowBatch
    between extractor_pool and the device step), and up to
    `inflight_steps` worker threads launch a device step as soon as the
    previous one's dispatch returns. A slot any of whose rows arrived
    while a step was on device is CHAINED: it dispatches the moment a
    worker frees (riding step N+1) instead of waiting out max_delay_s.
    An idle server degrades exactly to the classic behavior — one slot,
    one delay window, byte-identical responses for a serial client.

    Admission control is re-expressed against the in-flight step's ETA:
    a bounded-deadline request is refused (`DeadlineInfeasible`) when
    `remaining < eta + p95(bucket)` where eta is 0 if a worker is free,
    else the soonest in-flight step's expected completion; the
    slack-aware early dispatch uses the same per-bucket p95s. Cold
    tracker => no refusal, as in the classic batcher.

    `backend` is the model adapter (serving/server.py) with:
    alloc(rows), parse_into(lines, buffer, row_offset) -> fingerprint,
    predict_rows(buffer, n_rows, fingerprint) -> results (raising
    StaleParse when `fingerprint` is no longer the live model's), and
    predict_lines(lines) -> results. Without a backend (unit tests)
    every slot is a "lines" slot dispatched through `predict_fn`,
    exercising the continuous machinery alone. Oversized requests
    (> max_batch_rows) and slots whose parse-time fingerprint no longer
    matches the live model (mid-batch hot-swap) fall back to the lines
    path — predict_lines re-parses under the CURRENT model, so every
    response batch still carries exactly one fingerprint.
    """

    def __init__(self, predict_fn: Optional[Callable[[List[str]], List]]
                 = None,
                 max_batch_rows: int = 64, max_delay_s: float = 0.01,
                 buckets: Optional[Sequence[int]] = None,
                 inflight_steps: int = 2, backend=None, tenancy=None):
        if predict_fn is None and backend is None:
            raise ValueError("ContinuousBatcher needs a predict_fn or "
                             "a backend")
        self.predict_fn = predict_fn
        self.backend = backend
        self.tenancy = tenancy
        self.max_batch_rows = max(1, int(max_batch_rows))
        self.max_delay_s = max(0.0, float(max_delay_s))
        self.buckets = tuple(buckets) if buckets else None
        self.inflight_steps = max(1, int(inflight_steps))
        self.device_times = _DeviceTimeTracker()
        self._cond = threading.Condition()
        self._slots: deque = deque()
        self._pool: List = []
        self._pool_cap = self.inflight_steps + 2
        self._inflight = 0
        self._inflight_meta: List[List] = []   # [t_launch, bucket]
        self._draining = False
        self.batches_dispatched = 0
        self.rides = 0
        self._workers = [
            threading.Thread(target=self._worker,
                             name=f"serving-batcher-{i}", daemon=True)
            for i in range(self.inflight_steps)]
        for t in self._workers:
            t.start()

    # -------------------------------------------------------------- API

    _bucket_of = DynamicBatcher._bucket_of

    def _tenant_cap_hit_locked(self, slot: "_Slot",
                               tenant: Optional[str], n: int) -> bool:
        """Per-slot share cap: in a slot already SHARED by other
        tenants, one tenant may reserve at most its weighted share of
        the slot's rows — overflow opens the next slot instead of
        squeezing batch-mates out. A slot holding a single tenant (the
        common case, and every tenancy-off run) is never capped, so
        the classic fill behavior is untouched."""
        if self.tenancy is None or not slot.items:
            return False
        tenants = {i.tenant for i in slot.items}
        if tenants == {tenant}:
            return False
        held = sum(len(i.lines) for i in slot.items
                   if i.tenant == tenant)
        total_w = sum(self.tenancy.weight(t)
                      for t in tenants | {tenant})
        cap = max(1, int(self.max_batch_rows
                         * self.tenancy.weight(tenant)
                         / (total_w or 1.0)))
        return held + n > cap

    def submit(self, lines: Sequence[str],
               phases: Optional[dict] = None,
               deadline: Optional[Deadline] = None,
               trace=None, tenant: Optional[str] = None) -> Future:
        item = _Pending(list(lines), phases, deadline, trace=trace,
                        tenant=tenant)
        if not item.lines:
            item.future.set_result([])
            return item.future
        item.bucket = self._bucket_of(item.lines)
        if deadline is not None and deadline.bounded:
            if deadline.expired():
                expired_counter("batch_wait").inc()
                item.future.set_exception(DeadlineExceeded(
                    "request deadline expired before batching"))
                return item.future
            p95 = self.device_times.p95(item.bucket)
            if p95 is not None:
                eta = self._inflight_eta()
                if deadline.remaining() < eta + p95:
                    # The request cannot finish inside its budget even
                    # riding the very next step: the soonest in-flight
                    # step completes in `eta`, then its own bucket's
                    # p95 device time runs.
                    item.future.set_exception(DeadlineInfeasible(
                        f"remaining deadline budget "
                        f"{deadline.remaining() * 1e3:.0f}ms is below "
                        f"the in-flight step ETA {eta * 1e3:.0f}ms + "
                        f"bucket p95 device time {p95 * 1e3:.0f}ms",
                        retry_after_s=eta + p95))
                    return item.future
        n = len(item.lines)
        kind = ("rows" if self.backend is not None
                and n <= self.max_batch_rows
                and getattr(self.backend, "supports_rows",
                            lambda: True)() else "lines")
        with self._cond:
            if self._draining:
                item.future.set_exception(
                    RuntimeError("batcher is draining; not accepting "
                                 "new requests"))
                return item.future
            slot = self._slots[-1] if self._slots else None
            if (slot is None or slot.sealed or slot.kind != kind
                    or slot.rows + n > self.max_batch_rows
                    or self._tenant_cap_hit_locked(slot, tenant, n)):
                if slot is not None and not slot.sealed:
                    slot.sealed = True
                buffer = self._get_buffer_locked() if kind == "rows" \
                    else None
                slot = _Slot(kind, buffer)
                self._slots.append(slot)
            off = slot.rows
            slot.items.append(item)
            slot.offsets.append((off, n))
            slot.rows += n
            if slot.rows >= self.max_batch_rows:
                slot.sealed = True
            if self._inflight > 0 and not slot.chained:
                # this row arrived while a step was on device: the slot
                # rides the next step instead of a fresh delay window
                slot.chained = True
            if self._inflight > 0:
                self.rides += 1
                _C_RIDES.inc()
            if kind == "rows":
                slot.pending_writes += 1
            self._cond.notify_all()
        if kind != "rows":
            return item.future
        # Zero-copy parse, outside the lock: this submitter thread
        # writes its own disjoint row range of the slot buffer.
        try:
            fp = self.backend.parse_into(item.lines, slot.buffer, off)
        except BaseException as e:  # noqa: BLE001 — future must settle
            with self._cond:
                slot.pending_writes -= 1
                slot.buffer.context_valid_mask[off:off + n] = 0.0
                slot.buffer.example_valid[off:off + n] = False
                item.settled = True
                if item.future.set_running_or_notify_cancel():
                    item.future.set_exception(e)
                self._cond.notify_all()
            return item.future
        with self._cond:
            slot.pending_writes -= 1
            slot.fps.add(fp)
            self._cond.notify_all()
        return item.future

    def rebucket(self, buckets: Optional[Sequence[int]]) -> None:
        """Hot-swap support: adopt the new model's bucket grid, drop
        device-time samples keyed to the old one, and drop pooled
        buffers (they were allocated by the old model's backend). Slots
        already forming keep their parse-time fingerprints — the worker
        notices the mismatch and re-parses via the lines path, so a
        batch never mixes weights generations."""
        with self._cond:
            self.buckets = tuple(buckets) if buckets else None
            self.device_times = _DeviceTimeTracker()
            self._pool = []

    def drain(self, timeout: Optional[float] = None) -> None:
        """Stop intake, flush every forming slot (partially filled
        included), join the workers. Idempotent."""
        with self._cond:
            self._draining = True
            self._cond.notify_all()
        deadline = None if timeout is None \
            else time.monotonic() + timeout
        for t in self._workers:
            t.join(None if deadline is None
                   else max(deadline - time.monotonic(), 0.0))

    # -------------------------------------------------------- dispatch

    def _inflight_eta(self) -> float:
        """Seconds until the soonest in-flight step is expected to
        free a worker; 0 when a worker is idle or the tracker is cold
        for any in-flight bucket (never refuse on a guess)."""
        with self._cond:
            if self._inflight < self.inflight_steps:
                return 0.0
            meta = [tuple(m) for m in self._inflight_meta]
        now = time.perf_counter()
        eta = None
        for t_launch, bucket, _slot in meta:
            p95 = self.device_times.p95(bucket)
            if p95 is None:
                return 0.0
            done_in = max(t_launch + p95 - now, 0.0)
            eta = done_in if eta is None else min(eta, done_in)
        return eta or 0.0

    def _get_buffer_locked(self):
        if self._pool:
            return self._pool.pop()
        return self.backend.alloc(self.max_batch_rows)

    def _release_buffer(self, buffer, rows: int) -> None:
        if buffer is None:
            return
        # wipe the used rows' validity so a pooled buffer can never
        # inflate the next batch's bucket (indices are re-PADded per
        # claim by parse_into)
        buffer.context_valid_mask[:rows] = 0.0
        buffer.example_valid[:rows] = False
        with self._cond:
            if len(self._pool) < self._pool_cap:
                self._pool.append(buffer)

    def _due_wait_locked(self, slot: _Slot) -> float:
        """Seconds until the head slot is due (<= 0: dispatch now)."""
        if self._draining or slot.sealed or slot.chained:
            return 0.0
        wait = self.max_delay_s - (time.perf_counter() - slot.t_open)
        for item in slot.items:
            if item.deadline is None or not item.deadline.bounded \
                    or item.settled:
                continue
            remaining = item.deadline.remaining()
            p95 = self.device_times.p95(item.bucket) or 0.0
            wait = min(wait, remaining - p95, remaining)
        return wait

    def _expire_head_locked(self, slot: _Slot) -> None:
        if slot.pending_writes:
            return   # a parse is writing; next pass catches expiries
        for (off, n), item in zip(slot.offsets, slot.items):
            if item.settled or item.deadline is None \
                    or not item.deadline.expired():
                continue
            expired_counter("batch_wait").inc()
            item.settled = True
            if slot.buffer is not None:
                slot.buffer.context_valid_mask[off:off + n] = 0.0
                slot.buffer.example_valid[off:off + n] = False
            if item.future.set_running_or_notify_cancel():
                item.future.set_exception(DeadlineExceeded(
                    "request deadline expired while waiting for "
                    "batch-mates"))

    def _worker(self) -> None:
        while True:
            slot = self._next_slot()
            if slot is None:
                return
            try:
                self._run_slot(slot)
            finally:
                self._release_buffer(slot.buffer, slot.rows)
                with self._cond:
                    self._inflight -= 1
                    self._inflight_meta = [
                        m for m in self._inflight_meta
                        if m[2] is not slot]
                    _G_INFLIGHT.set(self._inflight)
                    self._cond.notify_all()

    def _next_slot(self) -> Optional[_Slot]:
        with self._cond:
            while True:
                slot = self._slots[0] if self._slots else None
                if slot is None:
                    if self._draining:
                        return None
                    self._cond.wait()
                    continue
                self._expire_head_locked(slot)
                if all(i.settled for i in slot.items) \
                        and not slot.pending_writes:
                    self._slots.popleft()
                    self._release_buffer_nolock_queue(slot)
                    continue
                wait = self._due_wait_locked(slot)
                if wait <= 0 and slot.pending_writes == 0:
                    self._slots.popleft()
                    slot.sealed = True
                    self._inflight += 1
                    bucket = max((i.bucket for i in slot.items
                                  if i.bucket is not None
                                  and not i.settled), default=None)
                    self._inflight_meta.append(
                        [time.perf_counter(), bucket, slot])
                    _G_INFLIGHT.set(self._inflight)
                    return slot
                self._cond.wait(timeout=wait if wait > 0 else None)

    def _release_buffer_nolock_queue(self, slot: _Slot) -> None:
        # called with the lock held for a fully-expired slot: return
        # the (already mask-wiped) buffer straight to the pool
        if slot.buffer is not None \
                and len(self._pool) < self._pool_cap:
            self._pool.append(slot.buffer)
            slot.buffer = None

    def _run_slot(self, slot: _Slot) -> None:
        t_dispatch = time.perf_counter()
        with self._cond:
            self._expire_head_locked(slot)
        live = [i for i in slot.items if not i.settled]
        if not live:
            return
        for item in live:
            wait = t_dispatch - item.t_submit
            _H_BATCH_WAIT.observe(wait)
            if item.phases is not None:
                item.phases["batch_wait"] = wait
            if item.trace is not None:
                item.trace.add_span("batch_wait", item.t_submit, wait)
        rows_live = sum(len(i.lines) for i in live)
        _C_BATCHES.inc()
        self.batches_dispatched += 1
        batch_id = self.batches_dispatched
        _C_ROWS.inc(rows_live)
        _H_BATCH_ROWS.observe(rows_live)
        use_rows = slot.kind == "rows" and len(slot.fps) == 1
        try:
            if use_rows:
                try:
                    results = self.backend.predict_rows(
                        slot.buffer, slot.rows, next(iter(slot.fps)))
                except StaleParse:
                    use_rows = False
                else:
                    if len(results) < slot.rows:
                        raise RuntimeError(
                            f"predict_rows returned {len(results)} "
                            f"results for {slot.rows} rows")
            if not use_rows:
                # lines fallback: plain lines slot, a rows slot that
                # straddled a hot-swap (mixed parse fingerprints or
                # StaleParse), — re-parse under the CURRENT model so
                # the batch answers with one fingerprint
                all_lines = [l for i in live for l in i.lines]
                fn = (self.backend.predict_lines
                      if self.backend is not None else self.predict_fn)
                results = fn(all_lines)
                if len(results) != len(all_lines):
                    raise RuntimeError(
                        f"predict_fn returned {len(results)} results "
                        f"for {len(all_lines)} lines")
        except BaseException as e:  # noqa: BLE001 — futures must settle
            for item in live:
                if item.future.set_running_or_notify_cancel():
                    item.future.set_exception(e)
            return
        dur = time.perf_counter() - t_dispatch
        _H_DEVICE.observe(dur)
        batch_bucket = max((i.bucket for i in live
                            if i.bucket is not None), default=None)
        self.device_times.record(batch_bucket, dur)
        _record_batch_spans(live, batch_id, batch_bucket, rows_live,
                            t_dispatch, dur)
        if use_rows:
            for (off, n), item in zip(slot.offsets, slot.items):
                if item.settled:
                    continue
                if item.phases is not None:
                    item.phases["device"] = dur
                if item.future.set_running_or_notify_cancel():
                    item.future.set_result(results[off:off + n])
        else:
            off = 0
            for item in live:
                n = len(item.lines)
                if item.phases is not None:
                    item.phases["device"] = dur
                if item.future.set_running_or_notify_cancel():
                    item.future.set_result(results[off:off + n])
                off += n
