from code2vec_tpu.models.code2vec import Code2VecModule, ModelDims  # noqa: F401
