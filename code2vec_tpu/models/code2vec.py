"""The code2vec model as a single Flax module.

One TPU-first implementation replaces the reference's two parallel
backends (TF1 session graphs tensorflow_model.py:196-308 and tf.keras
keras_model.py:37-95). Architecture (identical math):

  token/path embedding gathers -> concat (B, M, 3d) -> dropout(0.25)
  -> tanh(. @ TRANSFORM) -> masked single-query attention -> code vector
  -> logits = code_vector @ TARGET_EMB^T  (~261K-way classifier)

Parameter shapes and initializers follow tensorflow_model.py:204-219 and
:248-253: embeddings use variance_scaling(1.0, fan_out, uniform);
TRANSFORM/ATTENTION use TF's get_variable default (glorot_uniform).
Parameters are float32; matmuls run in `compute_dtype` (bfloat16 on the
MXU) with float32 accumulation.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from code2vec_tpu.ops.attention import masked_single_query_attention


@dataclasses.dataclass(frozen=True)
class ModelDims:
    token_vocab_size: int
    path_vocab_size: int
    target_vocab_size: int
    token_dim: int = 128
    path_dim: int = 128
    # Real (unpadded) target vocab size. Table rows may be padded up to a
    # multiple of the tensor-parallel degree so row shards are equal-sized
    # under shard_map; padded classifier columns must never win, so logits
    # for ids >= real_target_vocab_size are masked to -inf.
    real_target_vocab_size: int = 0
    # Highest special-word (PAD/OOV) index in the target vocab. Eval rows
    # whose label is <= this floor have no real in-vocab target, so their
    # CE term is excluded from the reported eval loss (train rows are
    # already filtered by the reader; the reference's eval loop reports no
    # loss at all, tensorflow_model.py:155-182, so the convention here is
    # chosen to keep eval loss comparable to train loss).
    target_oov_floor: int = 0

    def __post_init__(self):
        if self.real_target_vocab_size == 0:
            object.__setattr__(self, "real_target_vocab_size",
                               self.target_vocab_size)

    @property
    def context_dim(self) -> int:
        return self.path_dim + 2 * self.token_dim

    @property
    def code_dim(self) -> int:
        return self.context_dim

    @property
    def has_padded_targets(self) -> bool:
        return self.real_target_vocab_size < self.target_vocab_size

    def padded_to(self, tp: int) -> "ModelDims":
        """Round table row counts up to a multiple of `tp` (equal row
        shards for the manual tensor-parallel kernels)."""
        def up(n):
            return ((n + tp - 1) // tp) * tp
        return dataclasses.replace(
            self,
            token_vocab_size=up(self.token_vocab_size),
            path_vocab_size=up(self.path_vocab_size),
            target_vocab_size=up(self.target_vocab_size),
            real_target_vocab_size=self.real_target_vocab_size,
        )

    @classmethod
    def from_config_and_vocabs(cls, config, vocabs) -> "ModelDims":
        tv = vocabs.target_vocab
        dims = cls(
            token_vocab_size=vocabs.token_vocab.size,
            path_vocab_size=vocabs.path_vocab.size,
            target_vocab_size=tv.size,
            token_dim=config.token_embeddings_size,
            path_dim=config.path_embeddings_size,
            target_oov_floor=max(tv.pad_index, tv.oov_index),
        )
        if config.tp > 1:
            dims = dims.padded_to(config.tp)
        return dims


def _embedding_init():
    # reference: tensorflow_model.py:208 — variance_scaling(scale=1.0,
    # mode='fan_out', distribution='uniform').
    return nn.initializers.variance_scaling(1.0, "fan_out", "uniform")


class Code2VecModule(nn.Module):
    dims: ModelDims
    dropout_keep_rate: float = 0.75
    compute_dtype: jnp.dtype = jnp.bfloat16
    # Mesh axis name the context dimension is sharded over (context/sequence
    # parallelism); None under plain jit/GSPMD.
    context_axis_name: Optional[str] = None

    def setup(self):
        d = self.dims
        self.token_embedding = self.param(
            "token_embedding", _embedding_init(),
            (d.token_vocab_size, d.token_dim), jnp.float32)
        self.path_embedding = self.param(
            "path_embedding", _embedding_init(),
            (d.path_vocab_size, d.path_dim), jnp.float32)
        self.target_embedding = self.param(
            "target_embedding", _embedding_init(),
            (d.target_vocab_size, d.code_dim), jnp.float32)
        self.transform = self.param(
            "transform", nn.initializers.glorot_uniform(),
            (d.context_dim, d.code_dim), jnp.float32)
        self.attention = self.param(
            "attention", nn.initializers.glorot_uniform(),
            (d.code_dim, 1), jnp.float32)

    def transform_contexts(
        self,
        source_token_indices: jax.Array,   # (B, M) int32
        path_indices: jax.Array,           # (B, M) int32
        target_token_indices: jax.Array,   # (B, M) int32
        deterministic: bool = True,
    ) -> jax.Array:
        """Embed, concat, dropout, tanh-transform: (B, M, code_dim).

        reference: tensorflow_model.py:237-251.
        """
        src = jnp.take(self.token_embedding, source_token_indices, axis=0)
        pth = jnp.take(self.path_embedding, path_indices, axis=0)
        tgt = jnp.take(self.token_embedding, target_token_indices, axis=0)
        return self.transform_gathered(src, pth, tgt,
                                       deterministic=deterministic)

    def transform_gathered(
        self,
        source_rows: jax.Array,            # (B, M, token_dim) f32
        path_rows: jax.Array,              # (B, M, path_dim) f32
        target_rows: jax.Array,            # (B, M, token_dim) f32
        deterministic: bool = True,
    ) -> jax.Array:
        """Concat, dropout, tanh-transform pre-gathered embedding rows.

        Entry point for the sparse-optimizer train step
        (training/step.py): gathers happen *outside* the differentiated
        function so gradients arrive per-row instead of as dense
        table-shaped scatters (training/sparse_adam.py).
        """
        ctx = jnp.concatenate([source_rows, path_rows, target_rows],
                              axis=-1)                       # (B, M, 3d)
        # Cast to the compute dtype *before* dropout: the masked/scaled
        # (B, M, 3d) intermediate (and its backward) then moves through
        # HBM at half width. The 1/keep scale in bfloat16 differs from
        # f32 scaling below dropout's own noise floor; with
        # compute_dtype=float32 this is exactly the reference math
        # (tensorflow_model.py:244-245, keep=0.75).
        ctx = ctx.astype(self.compute_dtype)
        if not deterministic:
            keep = self.dropout_keep_rate
            rng = self.make_rng("dropout")
            mask = jax.random.bernoulli(rng, p=keep, shape=ctx.shape)
            ctx = jnp.where(mask, ctx / jnp.asarray(keep, ctx.dtype),
                            jnp.zeros((), ctx.dtype))
        transformed = jnp.tanh(
            jnp.einsum("bmc,cd->bmd", ctx, self.transform.astype(self.compute_dtype),
                       preferred_element_type=jnp.float32))
        return transformed.astype(self.compute_dtype)

    def encode(
        self,
        source_token_indices: jax.Array,
        path_indices: jax.Array,
        target_token_indices: jax.Array,
        context_valid_mask: jax.Array,     # (B, M) float
        deterministic: bool = True,
    ) -> Tuple[jax.Array, jax.Array]:
        """Code vectors (B, code_dim) float32 + attention weights (B, M)."""
        transformed = self.transform_contexts(
            source_token_indices, path_indices, target_token_indices,
            deterministic=deterministic)
        code_vectors, attention = masked_single_query_attention(
            transformed, self.attention[:, 0], context_valid_mask,
            axis_name=self.context_axis_name)
        return code_vectors.astype(jnp.float32), attention

    def logits_from_code_vectors(self, code_vectors: jax.Array) -> jax.Array:
        """(B, target_vocab) float32 — the replicated (non-TP) classifier.

        reference: tensorflow_model.py:225, :296. The tensor-parallel
        variant lives in ops/sharded.py and consumes `target_embedding`
        row-sharded.
        """
        logits = jnp.einsum(
            "bd,vd->bv", code_vectors.astype(self.compute_dtype),
            self.target_embedding.astype(self.compute_dtype),
            preferred_element_type=jnp.float32)
        if self.dims.has_padded_targets:
            col = jnp.arange(self.dims.target_vocab_size)
            logits = jnp.where(col[None, :] < self.dims.real_target_vocab_size,
                               logits, -jnp.inf)
        return logits

    def apply_from_rows(self, source_rows, path_rows, target_rows,
                        context_valid_mask, deterministic: bool = True):
        """Full forward from pre-gathered embedding rows (sparse-update
        train path): (logits, code_vectors f32, attention)."""
        transformed = self.transform_gathered(
            source_rows, path_rows, target_rows, deterministic=deterministic)
        code_vectors, attention = masked_single_query_attention(
            transformed, self.attention[:, 0], context_valid_mask,
            axis_name=self.context_axis_name)
        code_vectors = code_vectors.astype(jnp.float32)
        logits = self.logits_from_code_vectors(code_vectors)
        return logits, code_vectors, attention

    def __call__(self, source_token_indices, path_indices, target_token_indices,
                 context_valid_mask, deterministic: bool = True):
        code_vectors, attention = self.encode(
            source_token_indices, path_indices, target_token_indices,
            context_valid_mask, deterministic=deterministic)
        logits = self.logits_from_code_vectors(code_vectors)
        return logits, code_vectors, attention
