"""Corpus-scale batch embedding job: packed `.c2vb` corpus -> vector store.

The `embed` CLI subcommand body. Runs an entire packed corpus through
the model's eval pipeline at device speed — the same fixed-shape jitted
eval step the Evaluator drives (facade checkpoint via --load, or a PR-8
release artifact via --artifact: int8 fused-dequant tables + blockwise
top-k, no checkpoint in RSS) — and writes the code vectors into a
sharded `retrieval/store.py` vector store whose manifest records the
embedding model's fingerprint.

Resumable at shard granularity: a killed job restarted with the same
--embed_out skips every row already inside a committed shard (the eval
iteration order is deterministic — strided file order, no shuffle — so
"skip the first `rows_done` valid rows" resumes exactly). Skipped rows
cost a host-side batch walk, never device work.

Instrumented through obs/: `retrieval_embed_rows_total`,
`retrieval_embed_seconds{phase=device|assemble}` (device dispatch+wait
vs host-side fetch/ids/shard-write), `retrieval_embed_rows_per_sec`.
"""

from __future__ import annotations

import time
from typing import Optional

import jax
import numpy as np

from code2vec_tpu import obs
from code2vec_tpu.data.reader import EstimatorAction
from code2vec_tpu.retrieval.store import VectorStoreWriter
from code2vec_tpu.training.step import device_put_batch

_H_PHASE_HELP = ("batch embedding job latency by phase: device (eval "
                 "step dispatch + wait), assemble (host fetch, id "
                 "resolution, shard write)")


def _phase_hist(phase: str):
    return obs.histogram("retrieval_embed_seconds", _H_PHASE_HELP,
                         phase=phase)


def run_embed_job(model, corpus_path: Optional[str] = None,
                  out_dir: Optional[str] = None, log=None) -> dict:
    """Embed `corpus_path` (default config.test_data_path) with `model`
    into a vector store at `out_dir` (default config.embed_out).
    Returns a summary dict {rows, resumed_rows, shards, seconds,
    rows_per_sec, fingerprint, path}."""
    config = model.config
    log = log or config.log
    corpus = corpus_path or config.test_data_path
    out = out_dir or config.embed_out
    if not corpus:
        raise ValueError("embed needs a corpus: pass --test FILE (the "
                         "packed .c2vb sits next to it)")
    if not out:
        raise ValueError("embed needs --embed_out DIR")
    fingerprint = model.model_fingerprint()
    writer = VectorStoreWriter(
        out, dim=config.code_vector_size, dtype=config.embed_dtype,
        model_fingerprint=fingerprint, source=corpus,
        shard_rows=config.embed_shard_rows, log=log)
    resumed_rows = writer.rows_done
    if resumed_rows:
        log(f"Embed job resuming past {resumed_rows} committed row(s)")

    ds = model._packed_dataset(corpus)
    batch_size = int(config.test_batch_size)
    eval_step, params = model.eval_callable()
    target_vocab = model.vocabs.target_vocab

    h_device = _phase_hist("device")
    h_assemble = _phase_hist("assemble")
    rows_counter = obs.counter(
        "retrieval_embed_rows_total",
        "corpus rows embedded into a vector store")
    rate_gauge = obs.gauge(
        "retrieval_embed_rows_per_sec",
        "last embed job's end-to-end throughput")

    to_skip = resumed_rows
    written = 0
    t0 = time.perf_counter()
    batches = ds.iter_batches(batch_size, EstimatorAction.Evaluate,
                              with_target_strings=True)
    for batch in batches:
        valid = np.asarray(batch.example_valid)
        n_valid = int(valid.sum())
        if to_skip >= n_valid:
            # already inside a committed shard: no device work on resume
            to_skip -= n_valid
            continue
        t_dev = time.perf_counter()
        arrays = device_put_batch(batch, model.mesh)
        out_step = eval_step(params, *arrays)
        code_vectors = out_step.code_vectors
        jax.block_until_ready(code_vectors)
        h_device.observe(time.perf_counter() - t_dev)

        t_asm = time.perf_counter()
        vectors = np.asarray(code_vectors)[valid]
        if batch.target_strings is not None:
            ids = [s for s, v in zip(batch.target_strings, valid) if v]
        else:
            ids = [target_vocab.lookup_word(int(i))
                   for i, v in zip(batch.target_index, valid) if v]
        if to_skip:
            vectors, ids = vectors[to_skip:], ids[to_skip:]
            to_skip = 0
        writer.append(vectors, ids)
        written += len(ids)
        rows_counter.inc(len(ids))
        h_assemble.observe(time.perf_counter() - t_asm)

    manifest = writer.finalize()
    seconds = time.perf_counter() - t0
    rows_per_sec = written / max(seconds, 1e-9)
    rate_gauge.set(rows_per_sec)
    log(f"Embed job done: {written} row(s) embedded "
        f"({resumed_rows} resumed) into {len(manifest['shards'])} "
        f"shard(s) at {out} in {seconds:.1f}s "
        f"({rows_per_sec:.0f} rows/s, dtype {config.embed_dtype}, "
        f"fingerprint {fingerprint})")
    return {"rows": int(manifest["rows"]), "resumed_rows": resumed_rows,
            "embedded_rows": written,
            "shards": len(manifest["shards"]), "seconds": seconds,
            "rows_per_sec": rows_per_sec, "fingerprint": fingerprint,
            "path": writer.path}
