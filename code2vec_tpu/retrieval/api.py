"""Serving mount for the retrieval index: the /neighbors data plane.

`serve --retrieval_index DIR` mounts a built index into the
PredictionServer. A /neighbors request rides the EXACT /predict
pipeline — cache probe, admission gate, deadline budget, extractor
pool behind its breaker, dynamic batcher, device step behind its
breaker — and only then searches the index with the batch's code
vectors, so the second traffic class inherits every resilience
property PR 7/9 built (and its very different batching profile
exercises them).

Embedding-space safety is the handle's whole job:

- MOUNT: the index's recorded `model_fingerprint` must equal the live
  model's — a mismatch refuses to mount (startup config error, loud).
- SWAP: serving/swap.py consults the handle before committing a model
  hot-swap; policy `refuse` (default) rejects the swap, policy `detach`
  lets the swap commit but atomically detaches the index (reason in
  /healthz `retrieval.detach_reason`, `serving_retrieval_detached_total`).
- SERVE: every /neighbors response re-checks that the fingerprint of the
  model that actually computed the batch equals the index fingerprint —
  the airtight last line against any race between the cache probe, the
  batcher's model-ref read and a concurrent swap.
"""

from __future__ import annotations

import threading
import time
from typing import List, Optional

import numpy as np

from code2vec_tpu import obs
from code2vec_tpu.retrieval.index import NeighborIndex, load_index

_H_SEARCH = obs.histogram(
    "retrieval_search_seconds",
    "ANN search latency per /neighbors batch (device matmul + host "
    "id resolution)")


def _pow2_ceil(n: int) -> int:
    return 1 << max(int(n) - 1, 0).bit_length()


class EmbeddingSpaceMismatch(RuntimeError):
    """A /neighbors answer would have crossed embedding spaces (model
    fingerprint != index fingerprint); maps to 503 — the index needs a
    rebuild or the model a rollback."""


class RetrievalHandle:
    """The server's handle on one mounted index. `detach()` is
    one-way and atomic with respect to `require_attached()` readers;
    a detached handle keeps its status (and the reason) for /healthz."""

    def __init__(self, index: NeighborIndex, default_topk: int = 10):
        self.index = index
        self.default_topk = int(default_topk)
        self._lock = threading.Lock()
        self._attached = True
        self._detach_reason: Optional[str] = None

    @classmethod
    def mount(cls, path: str, model_fingerprint: str,
              default_topk: int = 10, log=None) -> "RetrievalHandle":
        """Load + fingerprint-check an index for a live model. Raises
        IndexArtifactError (named field) on any validation failure,
        including an embedding-space mismatch."""
        index = load_index(path, expect_fingerprint=model_fingerprint)
        if log is not None:
            log(f"Retrieval index mounted from {path}: "
                f"{index.rows} rows, backend {index.backend}, "
                f"nlist {index.nlist}, default nprobe {index.nprobe}, "
                f"metric {index.metric} (fingerprint "
                f"{index.fingerprint})")
        return cls(index, default_topk=default_topk)

    # ------------------------------------------------------------ state

    @property
    def attached(self) -> bool:
        with self._lock:
            return self._attached

    @property
    def fingerprint(self) -> str:
        return self.index.fingerprint

    def detach(self, reason: str) -> None:
        with self._lock:
            if not self._attached:
                return
            self._attached = False
            self._detach_reason = reason
        obs.counter("serving_retrieval_detached_total",
                    "retrieval indexes detached from a live server",
                    reason="fingerprint_mismatch").inc()

    def status(self) -> dict:
        with self._lock:
            attached, reason = self._attached, self._detach_reason
        return {
            "status": "attached" if attached else "detached",
            "detach_reason": reason,
            "fingerprint": self.index.fingerprint,
            "path": self.index.path,
            "backend": self.index.backend,
            "metric": self.index.metric,
            "rows": self.index.rows,
            "nlist": self.index.nlist,
            "nprobe": self.index.nprobe,
            "default_topk": self.default_topk,
        }

    # ----------------------------------------------------------- search

    def require_attached(self) -> None:
        with self._lock:
            if not self._attached:
                raise EmbeddingSpaceMismatch(
                    f"retrieval index detached: {self._detach_reason}")

    def neighbors(self, code_vectors: np.ndarray, result_fingerprint: str,
                  k: Optional[int] = None, nprobe: Optional[int] = None,
                  trace=None) -> List[List[dict]]:
        """Per-query neighbor lists for one batch of code vectors
        computed by the model identified by `result_fingerprint`. The
        fingerprint check here is per-RESPONSE: whatever interleaving of
        cache probe / batcher model-ref read / hot swap produced these
        vectors, they only turn into neighbors if they came out of the
        index's own embedding space."""
        self.require_attached()
        if result_fingerprint != self.index.fingerprint:
            raise EmbeddingSpaceMismatch(
                f"batch was embedded by {result_fingerprint!r} but the "
                f"index holds vectors from {self.index.fingerprint!r}")
        # Client-controlled knobs are BUCKETED to powers of two before
        # they reach the jitted search: NeighborIndex compiles one
        # function per distinct (k, nprobe) and a client walking
        # k=1,2,3,... would otherwise force an XLA compile per request
        # and grow the executable cache without bound — the same
        # compilation-budget discipline the serving batcher's context
        # buckets enforce. Results are sliced back to the requested k.
        k = self.default_topk if k is None else max(1, int(k))
        k = min(k, self.index.rows)
        k_eff = min(_pow2_ceil(k), self.index.rows)
        nprobe_eff = None
        if nprobe is not None:
            nprobe_eff = min(_pow2_ceil(max(1, int(nprobe))),
                             self.index.nlist)
        t0 = time.perf_counter()
        pos, scores = self.index.search(
            np.asarray(code_vectors, dtype=np.float32), k_eff,
            nprobe=nprobe_eff)
        dists = self.index.distances(scores)
        if trace is not None:
            trace.add_span(
                "ann_search", t0, time.perf_counter() - t0,
                attrs={"k": k_eff, "nprobe": nprobe_eff,
                       "rows": self.index.rows,
                       "queries": int(len(pos))})
        out: List[List[dict]] = []
        for row_pos, row_scores, row_dists in zip(pos, scores, dists):
            row = []
            for p, s, d in zip(row_pos[:k], row_scores[:k],
                               row_dists[:k]):
                if p < 0:
                    continue  # fewer candidates than k in the probed lists
                row.append({"id": self.index.ids[int(p)],
                            "store_row": int(self.index.store_rows[int(p)]),
                            "score": float(s),
                            "distance": float(d)})
            out.append(row)
        _H_SEARCH.observe(time.perf_counter() - t0)
        return out
