"""Code-vector retrieval stack: the paper's "vectors are the product"
workload (code similarity / clone detection / near-duplicate mining)
built on the serving and release subsystems.

Three pillars (README "Retrieval"):

- `store.py`  — sharded, memmappable vector store written by the batch
  embedding job (`embed` CLI subcommand, `embed_job.py`): fp32/fp16
  `(N, code_vector_size)` shards + a method-id sidecar + a manifest
  recording the embedding model's fingerprint. Resumable per shard.
- `index.py`  — IVF-flat ANN index built in JAX (`index-build`
  subcommand): jitted-Lloyd k-means coarse quantizer, inverted lists,
  queries scored by one batched matmul over the probed lists with the
  blockwise top-k merge from ops/topk; plus a brute-force exact backend
  (small-corpus fallback and recall ground truth).
- `api.py`    — the serving mount (`serve --retrieval_index DIR`):
  POST /neighbors = snippet -> extractor pool -> embed batch -> ANN
  search, sharing the admission/deadline/breaker/cache machinery, with
  the model-fingerprint/index-fingerprint agreement enforced on every
  response so neighbors are never computed in a stale embedding space.
"""
