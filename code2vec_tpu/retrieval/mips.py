"""Approximate-MIPS prediction head: the retrieval stack's IVF coarse
quantizer pointed at the ~246K-name target classifier table.

The serve-time prediction head is `top_k(code_vector @ table.T)` — a
maximum-inner-product search over the target vocabulary. The PR-8
blockwise head (ops/topk.py) already avoids materializing the (B, V)
logit row, but still STREAMS every table row through the matmul per
batch. This module reuses the PR-10 IVF machinery (retrieval/index.py:
jitted Lloyd k-means, list-contiguous reordering, padded-list gathers)
to search k ≪ V candidates instead:

- **Build** (once, at model load): k-means over the real-vocab rows
  (plain L2 Lloyd — the standard IVF coarse quantizer; probing ranks
  lists by centroid INNER PRODUCT, the MIPS analogue of the cosine
  probe the /neighbors index uses), rows reordered list-contiguously
  IN THEIR QUANTIZED FORM (int8/fp8 bytes or int4-packed nibbles move
  through HBM, scales reordered alongside — the byte-count lever and
  the candidate-count lever compose).
- **Search**: one (B, nlist) centroid matmul -> top-`nprobe` lists ->
  gather + fused-dequant the candidate rows -> exact scores over the
  candidates -> top-k, mapped back to global vocab ids.

Approximation contract: scores of returned candidates are EXACT (same
contraction as the blockwise head); only the candidate set is
approximate. `--serve_mips_nprobe 0` (the default) keeps the exact
blockwise head; accuracy evaluation always uses the exact head. Top-1
agreement vs exact per nprobe is measured by experiments/quant_bench.py
(BENCH_QUANT.md), with the tuned value documented as the smallest
nprobe keeping agreement >= 0.99. nprobe = nlist searches every row and
pins equality with the exact head in tests/test_quant.py.

Head dispatch (PR 18): MIPS wins by an order of magnitude at single-row
shapes but the exact blockwise head wins at bulk, where the candidate
gather stops amortizing — so serving routes PER BATCH SHAPE. Batches
with at most `--serve_mips_crossover` live rows take this head
(compiled at the crossover row shape, small batches repad down);
larger batches take the exact head at the serve shape. The default
(-1) adopts the crossover the export calibration measured into the
artifact meta (`mips_crossover`, see release/runtime.py:
calibrate_mips_crossover) and falls back to legacy all-MIPS when the
artifact predates calibration; `--serve_mips_crossover 0` disables the
head entirely, bit-for-bit the nprobe=0 exact path.
"""

from __future__ import annotations

import math
import time
from typing import Optional, Tuple

import numpy as np

from code2vec_tpu import obs


class MipsHead:
    """Built coarse quantizer + list-contiguous quantized rows on
    device. Thread-safe for concurrent searches (read-only after build;
    jit caches are internally locked by jax)."""

    def __init__(self, centroids, rows, scales, list_pad, global_ids,
                 *, int4_dim: Optional[int], real_vocab: int,
                 nprobe: int, build_seconds: float):
        import jax.numpy as jnp
        self._centroids = jnp.asarray(centroids)
        self._rows = jnp.asarray(rows)
        self._scales = None if scales is None else jnp.asarray(scales)
        self._list_pad = jnp.asarray(list_pad)
        self._global_ids = jnp.asarray(global_ids)
        self._int4_dim = int4_dim
        self.real_vocab = int(real_vocab)
        self.nlist = int(centroids.shape[0])
        self.nprobe = max(1, min(int(nprobe), self.nlist))
        self.build_seconds = build_seconds
        obs.gauge("serving_mips_nlist",
                  "coarse-quantizer size of the approximate-MIPS "
                  "prediction head (0 = head not built)"
                  ).set(self.nlist)

    @classmethod
    def build(cls, table, scales, *, real_vocab: int, nlist: int = 0,
              nprobe: int = 8, int4_dim: Optional[int] = None,
              kmeans_iters: int = 6, seed: int = 0, log=None
              ) -> "MipsHead":
        """Train the coarse quantizer over the REAL vocab rows of a
        (possibly quantized) target table and reorder the quantized
        payload list-contiguously. `table`/`scales` follow the
        ops/quant.py conventions (scales None = f32 table; int4_dim set
        = packed uint8 rows). Padded classifier rows (>= real_vocab)
        are excluded up front — they can never be predicted."""
        from code2vec_tpu.ops import quant
        from code2vec_tpu.retrieval.index import assign_lists, train_kmeans

        t0 = time.perf_counter()
        table_np = np.asarray(table)[:real_vocab]
        scales_np = None if scales is None else \
            np.asarray(scales)[:real_vocab]
        if scales_np is None:
            x = np.asarray(table_np, np.float32)
        elif int4_dim is not None:
            x = quant.dequantize_rows_int4(table_np, scales_np, int4_dim)
        elif table_np.dtype == np.int8:
            x = quant.dequantize_rows(table_np, scales_np)
        else:
            # fp8 payload already viewed to its ml_dtypes type by the
            # caller (release/runtime.py device params)
            x = table_np.astype(np.float32) * scales_np
        n = x.shape[0]
        if nlist <= 0:
            nlist = max(1, int(math.isqrt(n)))
        nlist = min(int(nlist), n)
        centroids = train_kmeans(x, nlist, iters=kmeans_iters, seed=seed)
        nlist = centroids.shape[0]
        assign = assign_lists(x, centroids)
        # stable sort: ties in the scored matmul resolve identically
        # run to run (same discipline as index-build)
        order = np.argsort(assign, kind="stable")
        counts = np.bincount(assign, minlength=nlist)
        offsets = np.zeros(nlist + 1, dtype=np.int64)
        np.cumsum(counts, out=offsets[1:])
        maxlen = max(int(counts.max()), 1)
        pad = np.full((nlist, maxlen), -1, dtype=np.int32)
        for i in range(nlist):
            lo, hi = int(offsets[i]), int(offsets[i + 1])
            pad[i, :hi - lo] = np.arange(lo, hi, dtype=np.int32)
        head = cls(centroids, table_np[order],
                   None if scales_np is None else scales_np[order],
                   pad, order.astype(np.int32),
                   int4_dim=int4_dim, real_vocab=n, nprobe=nprobe,
                   build_seconds=round(time.perf_counter() - t0, 3))
        if log:
            log(f"MIPS head built over {n} target rows: nlist {nlist}, "
                f"default nprobe {head.nprobe}, max list {maxlen}, "
                f"{head.build_seconds}s")
        return head

    # ----------------------------------------------------------- search

    def topk_fn(self, k: int, nprobe: Optional[int] = None):
        """Pure (code_vectors (B, D) f32) -> (values (B, k), indices
        (B, k) i32 global vocab ids) over the head's closure arrays —
        jit-safe, composed into the serve step by release/runtime.py
        and model_facade. Rows short of k candidates pad with -inf/0
        (never happens at production nprobe; k is clamped by callers)."""
        import jax
        import jax.numpy as jnp
        from code2vec_tpu.ops.quant import unpack_int4

        nprobe = self.nprobe if nprobe is None else \
            max(1, min(int(nprobe), self.nlist))
        k = max(1, min(int(k), self.real_vocab))
        centroids = self._centroids
        rows, scales = self._rows, self._scales
        list_pad, global_ids = self._list_pad, self._global_ids
        int4_dim = self._int4_dim

        def topk(code_vectors):
            cv = code_vectors.astype(jnp.float32)
            # (B, nlist) inner-product probe picks the searched lists
            cscores = cv @ centroids.T
            _, probe = jax.lax.top_k(cscores, nprobe)
            cand = list_pad[probe].reshape(cv.shape[0], -1)
            live = cand >= 0
            safe = jnp.maximum(cand, 0)
            gathered = jnp.take(rows, safe, axis=0)       # (B, P, D')
            if int4_dim is not None:
                gathered = unpack_int4(gathered, int4_dim)
            scores = jnp.einsum("bd,bpd->bp", cv,
                                gathered.astype(jnp.float32),
                                preferred_element_type=jnp.float32)
            if scales is not None:
                scores = scores * jnp.take(scales[:, 0], safe, axis=0)
            scores = jnp.where(live, scores, -jnp.inf)
            kk = min(k, scores.shape[1])
            vals, pos = jax.lax.top_k(scores, kk)
            idx = jnp.take_along_axis(cand, pos, axis=1)
            # candidate positions -> global target-vocab ids; dead
            # slots get the blockwise head's sentinel (value -inf,
            # index 0)
            idx = jnp.where(idx >= 0,
                            jnp.take(global_ids, jnp.maximum(idx, 0)), 0)
            if kk < k:
                vals = jnp.pad(vals, ((0, 0), (0, k - kk)),
                               constant_values=-jnp.inf)
                idx = jnp.pad(idx, ((0, 0), (0, k - kk)))
            return vals, idx.astype(jnp.int32)

        return topk

    def search(self, code_vectors: np.ndarray, k: int,
               nprobe: Optional[int] = None
               ) -> Tuple[np.ndarray, np.ndarray]:
        """Host convenience wrapper (bench/tests): jitted `topk_fn`
        cached per (k, nprobe)."""
        import jax
        key = (int(k), self.nprobe if nprobe is None else int(nprobe))
        cache = getattr(self, "_search_fns", None)
        if cache is None:
            cache = self._search_fns = {}
        fn = cache.get(key)
        if fn is None:
            fn = cache[key] = jax.jit(self.topk_fn(k, nprobe))
        vals, idx = fn(np.asarray(code_vectors, np.float32))
        return np.asarray(vals), np.asarray(idx)
