"""IVF-flat approximate-nearest-neighbor index over a code-vector store.

Built in JAX end to end (the `index-build` CLI subcommand):

- COARSE QUANTIZER: k-means trained with jitted Lloyd steps — one
  `fori_loop` of (assign by batched matmul, centroid update by
  `segment_sum`) compiled once per (rows, dim, nlist) shape. Empty
  clusters keep their previous centroid.
- INVERTED LISTS: every vector is assigned to its nearest centroid; the
  store is re-ordered list-contiguously (CSR layout: `list_offsets`
  (nlist+1,) + vectors/ids in list order), so probing a list is a
  contiguous slice.
- QUERY: centroid scores = one (B, nlist) matmul -> top-nprobe lists per
  query; candidates gathered from a padded list matrix; candidate scores
  = one batched matmul over the probed rows; the final top-k runs
  through `ops/topk.blockwise_top_k_from_logits` — the same blockwise
  merge the PR-8 prediction head streams the 246K-name classifier with.
- BRUTE-FORCE BACKEND: `ops/topk.blockwise_matmul_top_k` over the whole
  store (the vector table never materializes a (B, N) score row) — the
  small-corpus fallback at build time AND the exact ground truth
  `measure_recall` scores IVF against. With nprobe = nlist the IVF
  candidate set is the whole store, so both backends return identical
  neighbor sets (pinned in tests/test_retrieval.py).

The index artifact directory mirrors the PR-8 release-artifact contract:
`index_meta.json` is field-validated on load (kind/format/backend/
metric/dims/dtype, named-field IndexArtifactError) and carries the
embedding store's `model_fingerprint`, which the serving mount checks
against the live model so neighbors are never computed across two
embedding spaces.

Similarity is cosine by default (vectors L2-normalized at build, queries
at search; score = cosine in [-1, 1], distance = 1 - score) or raw dot
(`--index_metric dot`; distance = -score).
"""

from __future__ import annotations

import json
import math
import os
import time
from functools import partial
from typing import List, Optional, Tuple

import numpy as np

from code2vec_tpu import obs
from code2vec_tpu.retrieval.store import VectorStore, _atomic_write_json

INDEX_META_NAME = "index_meta.json"
INDEX_KIND = "code2vec_ivf_index"
INDEX_FORMAT = 1
BACKEND_IVF = "ivf_flat"
BACKEND_BRUTE = "brute_force"
METRICS = ("cosine", "dot")
# Below this row count IVF cannot beat one small matmul: index-build
# falls back to the brute-force backend (still a valid index artifact).
MIN_IVF_ROWS = 256
_TOPK_BLOCK = 4096


class IndexArtifactError(ValueError):
    """Index artifact rejected with the offending field named."""

    def __init__(self, field: str, message: str):
        super().__init__(f"retrieval index field `{field}`: {message}")
        self.field = field


def _normalize(x: np.ndarray) -> np.ndarray:
    norms = np.linalg.norm(x, axis=-1, keepdims=True)
    return x / np.maximum(norms, 1e-12)


# ------------------------------------------------------------------ k-means

def train_kmeans(vectors: np.ndarray, nlist: int, iters: int = 10,
                 seed: int = 0, spherical: bool = False) -> np.ndarray:
    """Lloyd k-means over (N, D) f32 vectors; returns (nlist, D) f32
    centroids. The whole iteration loop is one jitted function — each
    Lloyd step is an (N, nlist) matmul assign + segment_sum update.
    `spherical=True` re-normalizes centroids after every update
    (spherical k-means — the standard coarse quantizer for cosine
    similarity: unnormalized means drift inward and skew list sizes)."""
    import jax
    import jax.numpy as jnp

    x = np.ascontiguousarray(vectors, dtype=np.float32)
    n = x.shape[0]
    nlist = int(min(nlist, n))
    rng = np.random.default_rng(seed)
    init = x[rng.permutation(n)[:nlist]]

    @partial(jax.jit, static_argnames=("steps", "sph"))
    def lloyd(xd, c0, steps, sph):
        def body(_, c):
            assign = _assign_jax(xd, c)
            ones = jnp.ones((xd.shape[0],), jnp.float32)
            sums = jax.ops.segment_sum(xd, assign,
                                       num_segments=c.shape[0])
            counts = jax.ops.segment_sum(ones, assign,
                                         num_segments=c.shape[0])
            fresh = sums / jnp.maximum(counts, 1.0)[:, None]
            if sph:
                fresh = fresh / jnp.maximum(
                    jnp.linalg.norm(fresh, axis=1, keepdims=True), 1e-12)
            # empty cluster: keep the old centroid (it can re-acquire
            # members on a later step; dropping it would shrink nlist)
            return jnp.where((counts > 0)[:, None], fresh, c)
        return jax.lax.fori_loop(0, steps, body, c0)

    return np.asarray(lloyd(jnp.asarray(x), jnp.asarray(init),
                            steps=int(iters), sph=bool(spherical)))


def _assign_jax(x, c):
    """Nearest centroid per row by L2: argmin(|x-c|^2) == argmin over
    (|c|^2 - 2 x.c) since |x|^2 is constant per row."""
    import jax.numpy as jnp
    d = (c * c).sum(axis=1)[None, :] - 2.0 * x @ c.T
    return jnp.argmin(d, axis=1).astype(jnp.int32)


def assign_lists(vectors: np.ndarray, centroids: np.ndarray) -> np.ndarray:
    import jax
    import jax.numpy as jnp
    fn = jax.jit(_assign_jax)
    return np.asarray(fn(jnp.asarray(vectors, dtype=jnp.float32),
                         jnp.asarray(centroids)))


# -------------------------------------------------------------------- build

def build_index(store_dir: str, out_dir: str, nlist: int = 0,
                nprobe: int = 8, kmeans_iters: int = 10, seed: int = 0,
                metric: str = "cosine", log=None) -> dict:
    """Build an index artifact at `out_dir` from the vector store at
    `store_dir`; returns the index meta dict."""
    log = log or print
    if metric not in METRICS:
        raise IndexArtifactError("metric",
                                 f"must be one of {METRICS}, got {metric!r}")
    store = VectorStore.open(store_dir)
    n = store.rows
    if n == 0:
        raise IndexArtifactError("rows", f"vector store {store_dir} is "
                                         f"empty; nothing to index")
    x = store.load(np.float32)
    if metric == "cosine":
        x = _normalize(x)
    if nlist <= 0:
        nlist = max(1, int(math.isqrt(n)))
    nlist = min(nlist, n)
    backend = BACKEND_IVF if (n >= MIN_IVF_ROWS and nlist > 1) \
        else BACKEND_BRUTE

    os.makedirs(out_dir, exist_ok=True)
    t0 = time.perf_counter()
    store_order: np.ndarray
    if backend == BACKEND_IVF:
        centroids = train_kmeans(x, nlist, iters=kmeans_iters, seed=seed,
                                 spherical=(metric == "cosine"))
        nlist = centroids.shape[0]
        assign = assign_lists(x, centroids)
        # stable sort: within a list, rows keep store order — ties in
        # the scored matmul then resolve identically run to run
        store_order = np.argsort(assign, kind="stable").astype(np.int64)
        counts = np.bincount(assign, minlength=nlist)
        offsets = np.zeros(nlist + 1, dtype=np.int64)
        np.cumsum(counts, out=offsets[1:])
        np.save(os.path.join(out_dir, "centroids.npy"),
                centroids.astype(np.float32))
        np.save(os.path.join(out_dir, "list_offsets.npy"), offsets)
    else:
        nlist = 1
        store_order = np.arange(n, dtype=np.int64)
    # vectors re-ordered list-contiguously, persisted in the STORE's
    # dtype (fp16 stays fp16 on disk; search computes in f32). `x` is
    # already the f32 (cosine: normalized) matrix loaded above — reuse
    # it instead of a second store.load() walk over the shards.
    ordered = x[store_order].astype(np.dtype(store.dtype))
    np.save(os.path.join(out_dir, "vectors.npy"), ordered)
    ids = store.ids
    with open(os.path.join(out_dir, "ids.txt.tmp"), "w") as f:
        for row in store_order:
            f.write(ids[int(row)] + "\n")
    os.replace(os.path.join(out_dir, "ids.txt.tmp"),
               os.path.join(out_dir, "ids.txt"))
    np.save(os.path.join(out_dir, "store_rows.npy"), store_order)

    nprobe = max(1, min(int(nprobe), nlist))
    meta = {
        "kind": INDEX_KIND,
        "format": INDEX_FORMAT,
        "backend": backend,
        "metric": metric,
        "dim": store.dim,
        "dtype": store.dtype,
        "rows": n,
        "nlist": int(nlist),
        "nprobe": nprobe,
        "kmeans_iters": int(kmeans_iters),
        "seed": int(seed),
        "model_fingerprint": store.fingerprint,
        "source_store": store.path,
        "build_seconds": round(time.perf_counter() - t0, 3),
    }
    # meta last: a kill mid-build leaves a directory load_index rejects
    # (missing meta) instead of a torn index that loads
    _atomic_write_json(os.path.join(out_dir, INDEX_META_NAME), meta)
    log(f"Built {backend} index at {out_dir}: {n} rows, dim {store.dim}, "
        f"nlist {nlist}, default nprobe {nprobe}, metric {metric}, "
        f"{meta['build_seconds']}s (fingerprint {store.fingerprint})")
    return meta


# --------------------------------------------------------------------- load

class NeighborIndex:
    """Loaded, validated index artifact with a `search` surface shared
    by both backends. Thread-safe for concurrent searches (all state is
    read-only after load; jit caches are internally locked by jax)."""

    def __init__(self, path: str, meta: dict, vectors: np.ndarray,
                 ids: List[str], store_rows: np.ndarray,
                 centroids: Optional[np.ndarray],
                 offsets: Optional[np.ndarray]):
        self.path = path
        self.meta = meta
        self.ids = ids
        self.store_rows = store_rows
        self.backend = meta["backend"]
        self.metric = meta["metric"]
        self.dim = int(meta["dim"])
        self.rows = int(meta["rows"])
        self.nlist = int(meta["nlist"])
        self.nprobe = int(meta["nprobe"])
        self.fingerprint = str(meta["model_fingerprint"])
        import jax.numpy as jnp
        self._vectors = jnp.asarray(np.asarray(vectors, dtype=np.float32))
        self._centroids = (None if centroids is None
                           else jnp.asarray(centroids))
        self._offsets = offsets
        self._list_pad: Optional[np.ndarray] = None
        self._search_fns: dict = {}

    # ------------------------------------------------------- candidates

    def _padded_lists(self):
        """(nlist, max_list_len) DEVICE matrix of member positions, -1
        padded — built lazily once; turns 'gather nprobe ragged lists'
        into one fixed-shape take. Cached as a device array like
        `_vectors`: with skewed lists it is O(rows) bytes, and
        re-transferring it per search would tax every /neighbors
        batch."""
        if self._list_pad is None:
            import jax.numpy as jnp
            lens = np.diff(self._offsets)
            maxlen = max(int(lens.max()), 1)
            pad = np.full((self.nlist, maxlen), -1, dtype=np.int32)
            for i in range(self.nlist):
                lo, hi = int(self._offsets[i]), int(self._offsets[i + 1])
                pad[i, :hi - lo] = np.arange(lo, hi, dtype=np.int32)
            self._list_pad = jnp.asarray(pad)
        return self._list_pad

    # ------------------------------------------------------------ search

    def search(self, queries: np.ndarray, k: int,
               nprobe: Optional[int] = None, exact: bool = False
               ) -> Tuple[np.ndarray, np.ndarray]:
        """Top-k neighbors of (B, dim) query vectors.

        Returns (positions, scores): positions (B, k) int32 into
        `self.ids`/`self.store_rows` (-1 where fewer than k candidates
        exist), scores (B, k) f32 descending (cosine or dot per the
        index metric). `exact=True` forces the brute-force path — the
        recall ground truth."""
        import jax.numpy as jnp
        q = np.atleast_2d(np.asarray(queries, dtype=np.float32))
        if q.shape[1] != self.dim:
            raise ValueError(f"query dim {q.shape[1]} != index dim "
                             f"{self.dim}")
        if self.metric == "cosine":
            q = _normalize(q)
        k = max(1, min(int(k), self.rows))
        if exact or self.backend == BACKEND_BRUTE:
            backend = "brute"
            vals, pos = self._search_brute(jnp.asarray(q), k)
        else:
            backend = "ivf"
            np_probe = self.nprobe if nprobe is None else \
                max(1, min(int(nprobe), self.nlist))
            vals, pos = self._search_ivf(jnp.asarray(q), k, np_probe)
        obs.counter("retrieval_searches_total",
                    "ANN searches by backend",
                    backend=backend).inc()
        vals = np.asarray(vals)
        pos = np.asarray(pos)
        # candidate shortfall (tiny probed set) surfaces as -inf scores;
        # normalize to position -1 so callers need no score sentinel
        pos = np.where(np.isfinite(vals), pos, -1).astype(np.int32)
        return pos, vals

    def _search_brute(self, q, k: int):
        from code2vec_tpu.ops.topk import blockwise_matmul_top_k
        fn = self._search_fns.get(("brute", k))
        if fn is None:
            import jax

            def brute(qd, table):
                out = blockwise_matmul_top_k(
                    qd, table, k, min(_TOPK_BLOCK, table.shape[0]))
                return out.values, out.indices
            fn = self._search_fns[("brute", k)] = jax.jit(brute)
        return fn(q, self._vectors)

    def _search_ivf(self, q, k: int, nprobe: int):
        import jax
        import jax.numpy as jnp
        from code2vec_tpu.ops.topk import blockwise_top_k_from_logits
        pad = self._padded_lists()
        fn = self._search_fns.get(("ivf", k, nprobe, int(pad.shape[1])))
        if fn is None:
            def ivf(qd, table, centroids, list_pad):
                # one (B, nlist) matmul picks the probed lists per query
                cscores = qd @ centroids.T
                _, probe = jax.lax.top_k(cscores, nprobe)
                # (B, nprobe * maxlen) candidate positions, -1 padded
                cand = list_pad[probe].reshape(qd.shape[0], -1)
                live = cand >= 0
                rows = table[jnp.maximum(cand, 0)]          # (B, P, D)
                scores = jnp.einsum("bd,bpd->bp", qd, rows)
                scores = jnp.where(live, scores, -jnp.inf)
                kk = min(k, scores.shape[1])
                vals, pos = blockwise_top_k_from_logits(
                    scores, kk, _TOPK_BLOCK)
                idx = jnp.take_along_axis(cand, pos, axis=1)
                if kk < k:  # fewer candidates than k: pad the result
                    padw = k - kk
                    vals = jnp.pad(vals, ((0, 0), (0, padw)),
                                   constant_values=-jnp.inf)
                    idx = jnp.pad(idx, ((0, 0), (0, padw)),
                                  constant_values=-1)
                return vals, idx
            fn = self._search_fns[("ivf", k, nprobe, int(pad.shape[1]))] \
                = jax.jit(ivf)
        return fn(q, self._vectors, self._centroids, pad)

    def distances(self, scores: np.ndarray) -> np.ndarray:
        """Metric-appropriate distance of a score array: 1 - cosine, or
        -dot. -inf scores (missing candidates) map to +inf distance."""
        with np.errstate(invalid="ignore"):
            d = (1.0 - scores) if self.metric == "cosine" else -scores
        return np.where(np.isfinite(scores), d, np.inf)


def load_index(path: str,
               expect_fingerprint: Optional[str] = None) -> NeighborIndex:
    base = os.path.abspath(path)
    meta_path = os.path.join(base, INDEX_META_NAME)
    if not os.path.isfile(meta_path):
        raise IndexArtifactError(
            "kind", f"{base} is not a retrieval index ({INDEX_META_NAME} "
                    f"missing); indexes are built by the `index-build` "
                    f"subcommand")
    with open(meta_path) as f:
        try:
            meta = json.load(f)
        except json.JSONDecodeError as e:
            raise IndexArtifactError("kind",
                                     f"unparseable {INDEX_META_NAME}: {e}")
    if meta.get("kind") != INDEX_KIND:
        raise IndexArtifactError("kind", f"expected {INDEX_KIND!r}, got "
                                         f"{meta.get('kind')!r}")
    if int(meta.get("format", -1)) > INDEX_FORMAT:
        raise IndexArtifactError(
            "format", f"index format {meta.get('format')} is newer than "
                      f"this build understands (<= {INDEX_FORMAT})")
    for field in ("backend", "metric", "dim", "dtype", "rows", "nlist",
                  "nprobe", "model_fingerprint"):
        if field not in meta:
            raise IndexArtifactError(
                field, f"missing from {INDEX_META_NAME} (torn build?)")
    if meta["backend"] not in (BACKEND_IVF, BACKEND_BRUTE):
        raise IndexArtifactError("backend",
                                 f"unknown backend {meta['backend']!r}")
    if meta["metric"] not in METRICS:
        raise IndexArtifactError("metric",
                                 f"unknown metric {meta['metric']!r}")
    if expect_fingerprint is not None and \
            meta["model_fingerprint"] != expect_fingerprint:
        raise IndexArtifactError(
            "model_fingerprint",
            f"index was built over vectors from "
            f"{meta['model_fingerprint']!r} but the serving model is "
            f"{expect_fingerprint!r} — refusing to answer /neighbors "
            f"across embedding spaces")
    rows, dim = int(meta["rows"]), int(meta["dim"])
    vec_path = os.path.join(base, "vectors.npy")
    if not os.path.isfile(vec_path):
        raise IndexArtifactError("vectors", "vectors.npy missing")
    vectors = np.load(vec_path, mmap_mode="r")
    if tuple(vectors.shape) != (rows, dim):
        raise IndexArtifactError(
            "vectors.shape", f"expected ({rows}, {dim}) per meta, file "
                             f"holds {tuple(vectors.shape)}")
    if vectors.dtype != np.dtype(meta["dtype"]):
        raise IndexArtifactError(
            "vectors.dtype", f"expected {meta['dtype']} per meta, file "
                             f"holds {vectors.dtype}")
    ids_path = os.path.join(base, "ids.txt")
    if not os.path.isfile(ids_path):
        raise IndexArtifactError("ids", "ids.txt missing")
    with open(ids_path) as f:
        ids = f.read().splitlines()
    if len(ids) != rows:
        raise IndexArtifactError(
            "ids", f"{len(ids)} ids for {rows} vectors (torn sidecar)")
    store_rows_path = os.path.join(base, "store_rows.npy")
    if not os.path.isfile(store_rows_path):
        raise IndexArtifactError("store_rows", "store_rows.npy missing")
    store_rows = np.load(store_rows_path)
    if store_rows.shape != (rows,):
        raise IndexArtifactError(
            "store_rows.shape", f"expected ({rows},), file holds "
                                f"{tuple(store_rows.shape)}")
    centroids = offsets = None
    if meta["backend"] == BACKEND_IVF:
        cpath = os.path.join(base, "centroids.npy")
        opath = os.path.join(base, "list_offsets.npy")
        if not os.path.isfile(cpath):
            raise IndexArtifactError("centroids", "centroids.npy missing")
        if not os.path.isfile(opath):
            raise IndexArtifactError("list_offsets",
                                     "list_offsets.npy missing")
        centroids = np.load(cpath)
        nlist = int(meta["nlist"])
        if tuple(centroids.shape) != (nlist, dim):
            raise IndexArtifactError(
                "centroids.shape", f"expected ({nlist}, {dim}), file "
                                   f"holds {tuple(centroids.shape)}")
        offsets = np.load(opath)
        if offsets.shape != (nlist + 1,) or int(offsets[-1]) != rows:
            raise IndexArtifactError(
                "list_offsets",
                f"expected ({nlist + 1},) ending at {rows}, file holds "
                f"{tuple(offsets.shape)} ending at "
                f"{int(offsets[-1]) if len(offsets) else 'nothing'}")
    obs.gauge("retrieval_index_rows",
              "rows in the mounted/loaded retrieval index").set(rows)
    return NeighborIndex(base, meta, np.asarray(vectors), ids,
                         store_rows, centroids, offsets)


def measure_recall(index: NeighborIndex, queries: np.ndarray, k: int,
                   nprobe: Optional[int] = None) -> float:
    """recall@k of the index's ANN path against its own brute-force
    exact ground truth: |ANN ∩ exact| / (|queries| * k), neighbor
    identity by position set."""
    approx_pos, _ = index.search(queries, k, nprobe=nprobe)
    exact_pos, _ = index.search(queries, k, exact=True)
    hits = 0
    total = 0
    for a, e in zip(approx_pos, exact_pos):
        truth = set(int(i) for i in e if i >= 0)
        if not truth:
            continue
        hits += len(truth & set(int(i) for i in a if i >= 0))
        total += len(truth)
    return hits / max(total, 1)
