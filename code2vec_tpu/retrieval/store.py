"""Sharded, memmappable code-vector store: the embed job's output format.

A vector store is a directory:

    vector_manifest.json    kind/format/dim/dtype/model_fingerprint/
                            shard records (see VectorStoreWriter)
    shard_00000.npy         (rows, dim) fp32 or fp16 vectors
    shard_00000.ids         one method-id string per row (utf-8 text)
    shard_00001.npy / .ids  ...

The manifest carries the EMBEDDING MODEL's fingerprint
(`model_fingerprint()`: checkpoint path+step for the facade, artifact
content hash for a PR-8 release bundle). Every consumer — the
`index-build` job, the serving mount — propagates it, which is what lets
the stack prove end to end that a query vector and the stored corpus
came out of the same embedding space (mixing spaces silently returns
garbage neighbors, not an error).

Shards commit atomically (tmp + rename, manifest rewritten after each
commit), so the batch embed job is resumable at shard granularity: a
killed job re-runs only the rows past the last committed shard. Loads
validate every manifest field the consumers touch and raise StoreError
naming the offending field, mirroring the PR-8 artifact contract.
"""

from __future__ import annotations

import json
import os
from typing import Iterable, List, Optional, Sequence, Tuple

import numpy as np

MANIFEST_NAME = "vector_manifest.json"
STORE_KIND = "code2vec_vector_store"
STORE_FORMAT = 1
STORE_DTYPES = ("float32", "float16")


class StoreError(ValueError):
    """Vector store rejected with the offending manifest/shard field
    named (the PR-8 ArtifactError contract)."""

    def __init__(self, field: str, message: str):
        super().__init__(f"vector store field `{field}`: {message}")
        self.field = field


def _shard_base(index: int) -> str:
    return f"shard_{index:05d}"


def _atomic_write_json(path: str, payload: dict) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f, indent=2, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)


class VectorStoreWriter:
    """Shard-committing writer. `append(vectors, ids)` buffers rows and
    commits a shard every `shard_rows`; `finalize()` commits the ragged
    tail and marks the manifest complete.

    Resume: pointing a new writer at an existing (incomplete) store with
    the SAME fingerprint/dim/dtype keeps its committed shards —
    `rows_done` tells the embed job how many rows to skip. Any identity
    mismatch is a StoreError: resuming into a different embedding space
    would interleave incompatible vectors. `resume=False` rebuilds from
    scratch (the offline --export_code_vectors path: one eval, one
    store)."""

    def __init__(self, path: str, dim: int, dtype: str,
                 model_fingerprint: str, source: Optional[str] = None,
                 shard_rows: int = 65536, resume: bool = True, log=None):
        if dtype not in STORE_DTYPES:
            raise StoreError("dtype", f"must be one of {STORE_DTYPES}, "
                                      f"got {dtype!r}")
        if shard_rows < 1:
            raise StoreError("shard_rows", "must be >= 1")
        self.path = os.path.abspath(path)
        self.dim = int(dim)
        self.dtype = dtype
        self.fingerprint = model_fingerprint
        self.shard_rows = int(shard_rows)
        self.log = log or (lambda msg: None)
        os.makedirs(self.path, exist_ok=True)
        self._buf_vecs: List[np.ndarray] = []
        self._buf_ids: List[str] = []
        self._buffered = 0
        self._shards: List[dict] = []
        self._finalized = False
        manifest_path = os.path.join(self.path, MANIFEST_NAME)
        if os.path.isfile(manifest_path) and resume:
            self._resume_from(manifest_path)
        else:
            if os.path.isfile(manifest_path):
                self._wipe_existing()
            self._manifest = {
                "kind": STORE_KIND,
                "format": STORE_FORMAT,
                "dim": self.dim,
                "dtype": self.dtype,
                "model_fingerprint": model_fingerprint,
                "source": source,
                "shard_rows": self.shard_rows,
                "shards": [],
                "rows": 0,
                "complete": False,
            }
            self._write_manifest()

    # ----------------------------------------------------------- resume

    def _wipe_existing(self) -> None:
        for name in os.listdir(self.path):
            if name == MANIFEST_NAME or name.startswith("shard_"):
                os.unlink(os.path.join(self.path, name))

    def _resume_from(self, manifest_path: str) -> None:
        with open(manifest_path) as f:
            manifest = json.load(f)
        for field, want in (("kind", STORE_KIND), ("dim", self.dim),
                            ("dtype", self.dtype),
                            ("model_fingerprint", self.fingerprint)):
            if manifest.get(field) != want:
                raise StoreError(
                    field,
                    f"existing store at {self.path} holds "
                    f"{manifest.get(field)!r} but this job produces "
                    f"{want!r}; resuming would mix embedding spaces — "
                    f"delete the store or point --embed_out elsewhere")
        if manifest.get("complete"):
            raise StoreError(
                "complete",
                f"store at {self.path} is already complete "
                f"({manifest.get('rows')} rows); delete it to re-embed")
        # keep only shards whose files actually verify (a kill between
        # the shard rename and the manifest rewrite leaves an extra
        # file on disk; the manifest is authoritative)
        self._shards = list(manifest.get("shards") or [])
        for rec in self._shards:
            p = os.path.join(self.path, rec["file"])
            if not os.path.isfile(p):
                raise StoreError(
                    "shards", f"manifest lists {rec['file']} but the "
                              f"file is missing (torn store)")
        self._manifest = manifest
        self.log(f"Vector store resume: {self.rows_done} rows in "
                 f"{len(self._shards)} committed shard(s) at {self.path}")

    @property
    def rows_done(self) -> int:
        """Rows safely committed (resumable watermark); buffered rows of
        the open shard are not counted until their shard commits."""
        return int(sum(rec["rows"] for rec in self._shards))

    # ------------------------------------------------------------ write

    def append(self, vectors: np.ndarray, ids: Sequence[str]) -> None:
        if self._finalized:
            raise StoreError("complete", "writer already finalized")
        vectors = np.asarray(vectors)
        if vectors.ndim != 2 or vectors.shape[1] != self.dim:
            raise StoreError(
                "dim", f"append expects (rows, {self.dim}) vectors, got "
                       f"{vectors.shape}")
        if len(ids) != vectors.shape[0]:
            raise StoreError(
                "ids", f"{len(ids)} ids for {vectors.shape[0]} vectors")
        self._buf_vecs.append(vectors.astype(self.dtype))
        self._buf_ids.extend(str(i) for i in ids)
        self._buffered += vectors.shape[0]
        while self._buffered >= self.shard_rows:
            self._commit_shard(self.shard_rows)

    def _take_buffered(self, n: int) -> Tuple[np.ndarray, List[str]]:
        vecs = np.concatenate(self._buf_vecs, axis=0)
        take, rest = vecs[:n], vecs[n:]
        ids, self._buf_ids = self._buf_ids[:n], self._buf_ids[n:]
        self._buf_vecs = [rest] if len(rest) else []
        self._buffered -= n
        return take, ids

    def _commit_shard(self, n: int) -> None:
        vecs, ids = self._take_buffered(n)
        base = _shard_base(len(self._shards))
        vec_name, ids_name = base + ".npy", base + ".ids"
        vec_tmp = os.path.join(self.path, vec_name + ".tmp.npy")
        np.save(vec_tmp, vecs)
        os.replace(vec_tmp, os.path.join(self.path, vec_name))
        ids_tmp = os.path.join(self.path, ids_name + ".tmp")
        with open(ids_tmp, "w") as f:
            for method_id in ids:
                # ids are one-per-line; an embedded newline would shift
                # every later row's identity
                f.write(method_id.replace("\n", " ") + "\n")
        os.replace(ids_tmp, os.path.join(self.path, ids_name))
        self._shards.append({"file": vec_name, "ids_file": ids_name,
                             "rows": int(vecs.shape[0])})
        self._manifest["shards"] = self._shards
        self._manifest["rows"] = self.rows_done
        self._write_manifest()

    def finalize(self) -> dict:
        """Commit the ragged tail shard and mark the store complete;
        returns the final manifest."""
        if self._buffered:
            self._commit_shard(self._buffered)
        self._manifest["complete"] = True
        self._manifest["rows"] = self.rows_done
        self._write_manifest()
        self._finalized = True
        return dict(self._manifest)

    def _write_manifest(self) -> None:
        _atomic_write_json(os.path.join(self.path, MANIFEST_NAME),
                           self._manifest)


class VectorStore:
    """Validated read view: shards stay memory-mapped until a consumer
    asks for the concatenated matrix."""

    def __init__(self, path: str, manifest: dict,
                 shards: List[np.ndarray], ids: List[str]):
        self.path = path
        self.manifest = manifest
        self._shards = shards
        self._ids = ids

    @property
    def rows(self) -> int:
        return int(self.manifest["rows"])

    @property
    def dim(self) -> int:
        return int(self.manifest["dim"])

    @property
    def dtype(self) -> str:
        return str(self.manifest["dtype"])

    @property
    def fingerprint(self) -> str:
        return str(self.manifest["model_fingerprint"])

    @property
    def ids(self) -> List[str]:
        return self._ids

    def iter_shards(self) -> Iterable[np.ndarray]:
        return iter(self._shards)

    def load(self, dtype=np.float32) -> np.ndarray:
        """The full (rows, dim) matrix, materialized in `dtype`."""
        if not self._shards:
            return np.empty((0, self.dim), dtype=dtype)
        return np.concatenate(
            [np.asarray(s, dtype=dtype) for s in self._shards], axis=0)

    @classmethod
    def open(cls, path: str, expect_fingerprint: Optional[str] = None,
             allow_partial: bool = False) -> "VectorStore":
        base = os.path.abspath(path)
        manifest_path = os.path.join(base, MANIFEST_NAME)
        if not os.path.isfile(manifest_path):
            raise StoreError(
                "kind", f"{base} is not a vector store ({MANIFEST_NAME} "
                        f"missing); stores are written by the `embed` "
                        f"subcommand")
        with open(manifest_path) as f:
            try:
                manifest = json.load(f)
            except json.JSONDecodeError as e:
                raise StoreError("kind",
                                 f"unparseable {MANIFEST_NAME}: {e}")
        if manifest.get("kind") != STORE_KIND:
            raise StoreError("kind", f"expected {STORE_KIND!r}, got "
                                     f"{manifest.get('kind')!r}")
        if int(manifest.get("format", -1)) > STORE_FORMAT:
            raise StoreError(
                "format", f"store format {manifest.get('format')} is "
                          f"newer than this build understands "
                          f"(<= {STORE_FORMAT})")
        for field in ("dim", "dtype", "model_fingerprint", "rows",
                      "shards"):
            if field not in manifest:
                raise StoreError(field, f"missing from {MANIFEST_NAME} "
                                        f"(torn write?)")
        if manifest["dtype"] not in STORE_DTYPES:
            raise StoreError("dtype",
                             f"unknown dtype {manifest['dtype']!r}")
        if not manifest.get("complete") and not allow_partial:
            raise StoreError(
                "complete",
                f"store at {base} is incomplete (embed job still "
                f"running or killed mid-way; re-run `embed` to finish "
                f"it, or pass allow_partial to read the committed "
                f"prefix)")
        if expect_fingerprint is not None and \
                manifest["model_fingerprint"] != expect_fingerprint:
            raise StoreError(
                "model_fingerprint",
                f"store was embedded by {manifest['model_fingerprint']!r}"
                f" but the consumer expects {expect_fingerprint!r} — "
                f"mixing embedding spaces returns garbage neighbors")
        dim = int(manifest["dim"])
        want_dtype = np.dtype(manifest["dtype"])
        shards: List[np.ndarray] = []
        ids: List[str] = []
        total = 0
        for rec in manifest["shards"]:
            p = os.path.join(base, rec["file"])
            if not os.path.isfile(p):
                raise StoreError("shards",
                                 f"{rec['file']} missing on disk")
            arr = np.load(p, mmap_mode="r")
            if arr.dtype != want_dtype:
                raise StoreError(
                    f"{rec['file']}.dtype",
                    f"expected {want_dtype} per manifest, file holds "
                    f"{arr.dtype}")
            if arr.ndim != 2 or arr.shape[1] != dim or \
                    arr.shape[0] != int(rec["rows"]):
                raise StoreError(
                    f"{rec['file']}.shape",
                    f"expected ({rec['rows']}, {dim}), file holds "
                    f"{tuple(arr.shape)}")
            ids_path = os.path.join(base, rec["ids_file"])
            if not os.path.isfile(ids_path):
                raise StoreError("shards",
                                 f"{rec['ids_file']} missing on disk")
            with open(ids_path) as f:
                shard_ids = f.read().splitlines()
            if len(shard_ids) != int(rec["rows"]):
                raise StoreError(
                    f"{rec['ids_file']}.rows",
                    f"{len(shard_ids)} ids for {rec['rows']} vectors "
                    f"(torn sidecar)")
            shards.append(arr)
            ids.extend(shard_ids)
            total += arr.shape[0]
        if manifest.get("complete") and total != int(manifest["rows"]):
            raise StoreError(
                "rows", f"manifest says {manifest['rows']} rows but the "
                        f"shards hold {total}")
        return cls(base, manifest, shards, ids)
