"""CLI entry point, flag-compatible with the reference
(reference: config.py:10-44 for the flags, code2vec.py:16-37 for the
dispatch), plus TPU mesh/precision knobs."""

from __future__ import annotations

import os
import sys
from argparse import ArgumentParser

from code2vec_tpu.config import Config
from code2vec_tpu.vocab import VocabType


def arguments_parser() -> ArgumentParser:
    parser = ArgumentParser(prog="code2vec_tpu")
    # reference flags (config.py:10-44)
    parser.add_argument("-d", "--data", dest="data_path",
                        help="path prefix to preprocessed dataset", required=False)
    parser.add_argument("-te", "--test", dest="test_path", metavar="FILE",
                        required=False, default="",
                        help="path to test/validation .c2v file")
    parser.add_argument("-s", "--save", dest="save_path", metavar="FILE",
                        required=False, help="path to save the model")
    parser.add_argument("-l", "--load", dest="load_path", metavar="FILE",
                        required=False, help="path to load the model from")
    parser.add_argument("--save_w2v", dest="save_w2v", metavar="FILE",
                        required=False,
                        help="save token embeddings in word2vec format")
    parser.add_argument("--save_t2v", dest="save_t2v", metavar="FILE",
                        required=False,
                        help="save target embeddings in word2vec format")
    parser.add_argument("--export_code_vectors", action="store_true",
                        help="export code vectors for the given examples")
    parser.add_argument("--release", action="store_true",
                        help="release the loaded model (strip optimizer "
                             "state for a smaller artifact)")
    parser.add_argument("--predict", action="store_true",
                        help="run the interactive prediction shell")
    parser.add_argument("--serve", action="store_true",
                        help="run the batched prediction HTTP server "
                             "(POST /predict, POST /embed, GET /healthz, "
                             "GET /metrics) on the loaded model; also "
                             "reachable as the `serve` subcommand "
                             "(`code2vec_tpu serve --load ...`). "
                             "SIGTERM drains gracefully")
    parser.add_argument("--serve_port", type=int, default=None,
                        metavar="PORT",
                        help="HTTP port for --serve (default: config.py's "
                             "8800; 0 picks a free port)")
    parser.add_argument("--serve_host", default=None, metavar="HOST",
                        help="HTTP bind address for --serve (default "
                             "127.0.0.1; put a proxy in front for "
                             "external exposure)")
    parser.add_argument("--serve_batch_size", type=int, default=None,
                        metavar="ROWS",
                        help="rows per coalesced serving device batch "
                             "(also the padded row count of every "
                             "compiled predict shape; default 64)")
    parser.add_argument("--serve_max_delay_ms", type=float, default=None,
                        metavar="MS",
                        help="max milliseconds a request waits for "
                             "batch-mates before dispatching anyway "
                             "(default 10; 0 = no coalescing)")
    parser.add_argument("--serve_continuous", action="store_true",
                        default=None,
                        help="continuous batching: admit arriving rows "
                             "into the next device step of an already-"
                             "forming slot (zero-copy parse into the "
                             "slot buffer; a row arriving while a step "
                             "is on device rides the NEXT step instead "
                             "of opening a fresh delay window)")
    parser.add_argument("--serve_inflight_steps", type=int, default=None,
                        metavar="N",
                        help="device steps the continuous batcher may "
                             "keep in flight at once (default 2; only "
                             "read with --serve_continuous)")
    parser.add_argument("--serve_buckets", default=None, metavar="LIST",
                        help="comma-separated padded-context-count "
                             "buckets for the predict path (default "
                             "'32,64,128'; max_contexts is always "
                             "appended) — bounds the number of pjit "
                             "compilations serving can trigger")
    parser.add_argument("--serve_cache_entries", type=int, default=None,
                        metavar="N",
                        help="LRU prediction-cache capacity keyed by "
                             "normalized method-body hash (default "
                             "4096; 0 disables)")
    parser.add_argument("--extractor_pool_size", type=int, default=None,
                        metavar="N",
                        help="warm extractor worker processes kept "
                             "resident by the serving pool (default 2)")
    parser.add_argument("--serve_drain_timeout_s", type=float,
                        default=None, metavar="SECONDS",
                        help="SIGTERM grace: seconds the drain waits "
                             "for in-flight requests (default 30)")
    parser.add_argument("--serve_deadline_ms", type=float, default=None,
                        metavar="MS",
                        help="default end-to-end deadline per serving "
                             "request (default 2000; clients override "
                             "via the X-Deadline-Ms header; 0 = no "
                             "default deadline). Expiry mid-pipeline "
                             "is an honest 504")
    parser.add_argument("--serve_deadline_max_ms", type=float,
                        default=None, metavar="MS",
                        help="hard ceiling on any request deadline, "
                             "header-supplied included (default 30000; "
                             "0 = no ceiling)")
    parser.add_argument("--serve_queue_depth", type=int, default=None,
                        metavar="N",
                        help="admission bound: max requests in the "
                             "cache-miss pipeline before excess load "
                             "is shed with 503 + Retry-After "
                             "(default 64)")
    parser.add_argument("--serve_tenants", type=str, default=None,
                        metavar="NAME=W,...",
                        help="named tenants and their admission "
                             "weights (e.g. acme=4,dev=1; bare name = "
                             "weight 1). Unset = tenancy off: serving "
                             "behavior is byte-identical to a build "
                             "without the feature")
    parser.add_argument("--serve_tenant_default_weight", type=float,
                        default=None, metavar="W",
                        help="admission weight for tenants not named "
                             "in --serve_tenants, including the "
                             "implicit 'default' tenant (default 1.0)")
    parser.add_argument("--serve_tenant_qps", type=str, default=None,
                        metavar="NAME=QPS,...",
                        help="per-tenant token-bucket rate quotas "
                             "(e.g. acme=50,dev=5, or a bare number "
                             "applied to every tenant); 0 = uncapped "
                             "(the default). Over-quota requests are "
                             "shed 503 shed_reason=tenant_quota with "
                             "Retry-After from the bucket refill")
    parser.add_argument("--serve_breaker_window",
                        dest="serve_breaker_window_s", type=float,
                        default=None, metavar="SECONDS",
                        help="circuit-breaker rolling failure window "
                             "(default 10)")
    parser.add_argument("--serve_breaker_failure_ratio", type=float,
                        default=None, metavar="RATIO",
                        help="failure ratio over the window that opens "
                             "a breaker (default 0.5)")
    parser.add_argument("--serve_breaker_min_requests", type=int,
                        default=None, metavar="N",
                        help="minimum samples in the window before a "
                             "breaker can open (default 4)")
    parser.add_argument("--serve_breaker_cooldown",
                        dest="serve_breaker_cooldown_s", type=float,
                        default=None, metavar="SECONDS",
                        help="seconds an open breaker waits before the "
                             "half-open recovery probe (default 5)")
    parser.add_argument("--replicas", dest="serve_replicas", type=int,
                        default=None, metavar="N",
                        help="supervised multi-replica serving: fork N "
                             "single-model replicas sharing the listen "
                             "port (SO_REUSEPORT, else a supervisor "
                             "round-robin proxy), restart crashed/hung "
                             "ones with backoff, drain all on SIGTERM "
                             "(default 1 = no supervisor)")
    parser.add_argument("--serve_max_restarts", type=int, default=None,
                        metavar="N",
                        help="restarts the supervisor grants each "
                             "replica before escalating to supervisor "
                             "exit (default 5)")
    parser.add_argument("--serve_heartbeat_interval",
                        dest="serve_heartbeat_interval_s", type=float,
                        default=None, metavar="SECONDS",
                        help="seconds between serving heartbeat "
                             "rewrites; the supervisor restarts a "
                             "replica whose heartbeat goes ~3 "
                             "intervals stale (default 5)")
    parser.add_argument("--serve_debug_trace", action="store_true",
                        default=None,
                        help="honor ?debug=trace on serving endpoints: "
                             "the JSON response gains a `trace` field "
                             "with the request's span tree. OFF by "
                             "default (exposes worker pids / batch "
                             "composition; debug replicas only — "
                             "README 'Telemetry')")
    parser.add_argument("--serve_flight_dir", metavar="DIR",
                        help="directory for flight-recorder dumps "
                             "(incident-triggered + POST /admin/dump); "
                             "default: next to --heartbeat_file")
    parser.add_argument("--serve_flight_records", type=int, default=None,
                        metavar="N",
                        help="terminal request records the incident "
                             "flight recorder retains (default 512)")
    parser.add_argument("--serve_flight_max_dumps", type=int,
                        default=None, metavar="N",
                        help="flight dumps retained per dump dir: past "
                             "the cap the oldest flight-*.json files "
                             "are deleted after each new dump "
                             "(default 64; 0 = unbounded)")
    parser.add_argument("--serve_telemetry_port", type=int, default=None,
                        metavar="PORT",
                        help="supervisor fleet-telemetry listener "
                             "(merged GET /metrics + GET /fleet under "
                             "--replicas); default: public port + 1, "
                             "0 picks a free port")
    # -- cross-host serving fleet (README "Fleet") --
    parser.add_argument("--fleet_hosts", type=int, default=None,
                        metavar="N",
                        help="`fleet` subcommand: host supervisors "
                             "launched per model group (default 2); "
                             "each host is a full `serve --replicas N` "
                             "supervisor behind the fleet router")
    parser.add_argument("--fleet_port", type=int, default=None,
                        metavar="PORT",
                        help="fleet router public port (default: "
                             "--serve_port; 0 picks a free port)")
    parser.add_argument("--fleet_models", default=None, metavar="LIST",
                        help="multi-model fleet: comma list of "
                             "name=artifact_dir groups, each getting "
                             "--fleet_hosts hosts; the router keys on "
                             "the X-Model request header (empty = one "
                             "'default' group from --artifact)")
    parser.add_argument("--fleet_poll_interval",
                        dest="fleet_poll_interval_s", type=float,
                        default=None, metavar="SECONDS",
                        help="control-plane poll + scaling-decision "
                             "cadence (default 1)")
    parser.add_argument("--fleet_scale_min", type=int, default=None,
                        metavar="N",
                        help="per-host replica floor for "
                             "telemetry-driven scaling (default 1)")
    parser.add_argument("--fleet_scale_max", type=int, default=None,
                        metavar="N",
                        help="per-host replica ceiling for "
                             "telemetry-driven scaling (default 4)")
    parser.add_argument("--fleet_scale_up_shed_rate", type=float,
                        default=None, metavar="RATIO",
                        help="scale a host up when its window shed "
                             "rate exceeds this fraction (default "
                             "0.05)")
    parser.add_argument("--fleet_scale_up_p95_ms", type=float,
                        default=None, metavar="MS",
                        help="scale a host up when its window "
                             "total-phase p95 exceeds this many ms "
                             "(default 500 = 10x the measured healthy "
                             "p95, serving_bench.py p95 mode; 0 "
                             "disables the trigger)")
    parser.add_argument("--fleet_scale_up_ticks", type=int,
                        default=None, metavar="N",
                        help="consecutive over-threshold ticks before "
                             "a scale-up (hysteresis; default 2)")
    parser.add_argument("--fleet_scale_down_ticks", type=int,
                        default=None, metavar="N",
                        help="consecutive zero-request ticks before a "
                             "scale-down (hysteresis; default 10)")
    parser.add_argument("--fleet_scale_cooldown",
                        dest="fleet_scale_cooldown_s", type=float,
                        default=None, metavar="SECONDS",
                        help="cooldown after every scaling action "
                             "(default 15)")
    parser.add_argument("--fleet_swap_timeout",
                        dest="fleet_swap_timeout_s", type=float,
                        default=None, metavar="SECONDS",
                        help="per-host convergence budget of the "
                             "canary-first coordinated hot-swap "
                             "(default 120)")
    parser.add_argument("--fleet_max_host_restarts", type=int,
                        default=None, metavar="N",
                        help="restarts the control plane grants each "
                             "host before escalating to fleet exit "
                             "(default 5)")
    parser.add_argument("--fleet_routers", type=int, default=None,
                        metavar="N",
                        help="public edge router processes (README "
                             "'Edge'): 1 (default) = the embedded "
                             "router; N >= 2 spawns N stateless "
                             "router agents on consecutive ports "
                             "(--fleet_port..+N-1) sharing the fleet "
                             "view, supervised with the host "
                             "backoff/escalation policy")
    parser.add_argument("--fleet_control", default=None,
                        metavar="HOST:PORT",
                        help="control-listener address a router agent "
                             "polls for the shared fleet view "
                             "(set by the control plane on router "
                             "re-exec commands, not by operators)")
    parser.add_argument("--fleet_no_affinity",
                        action="store_true", default=None,
                        help="disable consistent-hash cache affinity "
                             "(routers then always weighted-sample; "
                             "fleet-level cache hit rate decays "
                             "as 1/N — see BENCH_SERVING.md)")
    parser.add_argument("--fleet_launcher", default=None,
                        metavar="TEMPLATE",
                        help="remote HostLauncher wrapper template, "
                             "e.g. 'ssh {address}' or 'docker exec "
                             "{address}' (empty = local processes); "
                             "needs the fleet run dir on a shared "
                             "filesystem and reachable host ports")
    parser.add_argument("--fleet_addresses", default=None,
                        metavar="LIST",
                        help="comma list of addresses hosts are "
                             "placed on round-robin and reached at "
                             "(default: --serve_host for every host)")
    parser.add_argument("--fleet_tsdb_retention",
                        dest="fleet_tsdb_retention_s", type=float,
                        default=None, metavar="SECONDS",
                        help="telemetry-history window the control "
                             "plane keeps (obs/tsdb.py segment ring "
                             "under the run dir; default 3600)")
    parser.add_argument("--fleet_tsdb_max_mb", type=float,
                        default=None, metavar="MB",
                        help="byte cap on the on-disk history ring "
                             "(oldest segments evicted first; "
                             "default 64)")
    parser.add_argument("--fleet_slo_availability", type=float,
                        default=None, metavar="RATIO",
                        help="availability SLO target: fraction of "
                             "non-5xx/non-shed requests (default "
                             "0.999; 0 disables the objective)")
    parser.add_argument("--fleet_slo_latency_ms", type=float,
                        default=None, metavar="MS",
                        help="latency SLO threshold: requests "
                             "completing under this many ms count as "
                             "good (default 500; 0 disables)")
    parser.add_argument("--fleet_slo_latency_target", type=float,
                        default=None, metavar="RATIO",
                        help="latency SLO target: fraction of "
                             "requests that must beat the threshold "
                             "(default 0.95; 0 disables)")
    parser.add_argument("--fleet_slo_period",
                        dest="fleet_slo_period_s", type=float,
                        default=None, metavar="SECONDS",
                        help="error-budget period for "
                             "slo_error_budget_remaining (default "
                             "2592000 = 30 days)")
    parser.add_argument("--fleet_slo_window_scale", type=float,
                        default=None, metavar="FACTOR",
                        help="uniform scale on every burn-rate "
                             "window (default 1.0 = the standard SRE "
                             "5m/1h + 30m/6h pairs; shrink for "
                             "drills so a page fires in seconds)")
    parser.add_argument("--fleet_trace_id", default=None,
                        metavar="HEX32",
                        help="`fleet trace` collector: stitch this "
                             "trace id's spans from every process's "
                             "trace files into one Chrome trace on "
                             "stdout (use with --fleet_trace_dir or "
                             "--fleet_control)")
    parser.add_argument("--fleet_trace_dir", default=None,
                        metavar="DIR",
                        help="fleet run dir to walk for *.trace.json "
                             "span files when stitching locally "
                             "(default: ask the live control plane "
                             "at --fleet_control via GET /trace)")
    parser.add_argument("--artifact", dest="serve_artifact", metavar="DIR",
                        help="serve/evaluate from a release artifact "
                             "(produced by the `export` subcommand) "
                             "instead of --load: int8 tables with fused "
                             "dequant, blockwise top-k, AOT cold-start")
    parser.add_argument("--artifact_out", dest="export_artifact_path",
                        metavar="DIR",
                        help="write a release artifact of the --load'ed "
                             "model here (the `export` subcommand body): "
                             "quantized tables + vocabularies + AOT "
                             "serve lowerings, see README 'Release "
                             "artifacts'")
    parser.add_argument("--no_quantize", action="store_true",
                        help="export fp32 tables instead of per-row "
                             "symmetric int8 (the artifact stays "
                             "self-contained, just 4x the bytes; the "
                             "control arm of BENCH_QUANT.md)")
    parser.add_argument("--release_scheme",
                        choices=["int8", "fp8_e4m3", "fp8_e5m2", "int4",
                                 "float32"],
                        default=None,
                        help="quantization scheme of the exported "
                             "tables (default int8; fp8 keeps 1 "
                             "byte/weight with a relative error "
                             "profile, int4 packs two weights per byte "
                             "for another ~2x — per-scheme accuracy "
                             "deltas in BENCH_QUANT.md)")
    parser.add_argument("--serve_mips_nprobe", type=int, default=None,
                        metavar="N",
                        help="approximate-MIPS prediction head: search "
                             "only the N nearest coarse-quantizer "
                             "lists of the target-name table at "
                             "serve/predict time instead of streaming "
                             "all ~246K rows (default 0 = exact "
                             "blockwise top-k; BENCH_QUANT.md records "
                             "the agreement-vs-speedup sweep and the "
                             "tuned value)")
    parser.add_argument("--serve_mips_nlist", type=int, default=None,
                        metavar="N",
                        help="coarse-quantizer size of the MIPS head "
                             "(default 0 = sqrt(vocab) auto)")
    parser.add_argument("--serve_mips_crossover", type=int, default=None,
                        metavar="ROWS",
                        help="batch-shape-aware head dispatch: device "
                             "batches with at most ROWS live rows "
                             "route to the MIPS head, bulk shapes to "
                             "the exact blockwise head (default -1 = "
                             "adopt the crossover calibrated at "
                             "export, or all-MIPS for artifacts "
                             "without one; 0 = exact-only bit-for-bit; "
                             "requires --serve_mips_nprobe > 0)")
    parser.add_argument("--overlap_allreduce",
                        dest="overlap_grad_allreduce",
                        action="store_true", default=None,
                        help="bucketed async gradient all-reduce: "
                             "split the train step into backward + "
                             "per-bucket all-reduce+Adam dispatches so "
                             "communication overlaps the optimizer "
                             "apply (dense optimizer; dp meshes, or "
                             "tp/cp with --manual_tp_kernels; "
                             "BENCH_ROOFLINE.md 'Roofline levers')")
    parser.add_argument("--overlap_bucket_mb", type=float, default=None,
                        metavar="MB",
                        help="target gradient-bucket size for "
                             "--overlap_allreduce (default 32)")
    parser.add_argument("--overlap_in_backward",
                        action="store_true", default=None,
                        help="in-backward bucket completion for "
                             "--overlap_allreduce: split the backward "
                             "itself by bucket so bucket i's "
                             "all-reduce+apply dispatches while bucket "
                             "i+1's backward runs (costs one forward "
                             "per extra bucket; BENCH_INPUT.md A/B)")
    parser.add_argument("--no_aot", action="store_true",
                        help="skip the jax.export AOT lowerings in the "
                             "exported artifact (consumers then always "
                             "trace+compile at cold start)")
    # -- retrieval stack (README "Retrieval") --
    parser.add_argument("--embed_out", dest="embed_out", metavar="DIR",
                        help="batch embedding job (the `embed` "
                             "subcommand body): run the --test corpus's "
                             "packed .c2vb through the eval pipeline at "
                             "device speed and write a sharded vector "
                             "store here (resumable per shard; model "
                             "from --load or --artifact)")
    parser.add_argument("--embed_dtype", choices=["float32", "float16"],
                        default=None,
                        help="vector-store payload dtype (default "
                             "float32; float16 halves the store)")
    parser.add_argument("--embed_shard_rows", type=int, default=None,
                        metavar="N",
                        help="rows per committed vector-store shard — "
                             "the embed job's resume granularity "
                             "(default 65536)")
    parser.add_argument("--vectors_text", action="store_true",
                        help="--export_code_vectors compat: write the "
                             "reference's `.vectors` text layout "
                             "instead of the sharded store format")
    parser.add_argument("--embeddings_out", dest="embeddings_out",
                        metavar="DIR",
                        help="dump the token + target embedding tables "
                             "in word2vec text format here (the "
                             "`export-embeddings` subcommand body; the "
                             "reference's --save_w2v/--save_t2v pair)")
    parser.add_argument("--vectors", dest="index_vectors", metavar="DIR",
                        help="index-build input: the vector store the "
                             "`embed` subcommand wrote")
    parser.add_argument("--index_out", dest="index_out", metavar="DIR",
                        help="index-build output: write the ANN index "
                             "artifact here (IVF-flat, or brute-force "
                             "on small corpora)")
    parser.add_argument("--nlist", dest="index_nlist", type=int,
                        default=None, metavar="N",
                        help="IVF coarse-quantizer size (default 0 = "
                             "sqrt(rows) auto)")
    parser.add_argument("--nprobe", dest="index_nprobe", type=int,
                        default=None, metavar="N",
                        help="inverted lists probed per query — the "
                             "recall/latency knob (default 8; baked "
                             "into the index as its default, clients "
                             "override per request)")
    parser.add_argument("--kmeans_iters", dest="index_kmeans_iters",
                        type=int, default=None, metavar="N",
                        help="jitted Lloyd iterations for the coarse "
                             "quantizer (default 10)")
    parser.add_argument("--index_metric", dest="index_metric",
                        choices=["cosine", "dot"], default=None,
                        help="similarity metric baked into the index "
                             "(default cosine)")
    parser.add_argument("--retrieval_index", dest="retrieval_index",
                        metavar="DIR",
                        help="serve: mount this index so the server "
                             "answers POST /neighbors (snippet -> "
                             "embed -> ANN search); the index's "
                             "embedding fingerprint must match the "
                             "serving model's")
    parser.add_argument("--retrieval_topk", dest="retrieval_topk",
                        type=int, default=None, metavar="K",
                        help="default neighbors per method from "
                             "/neighbors (default 10; JSON body `k` "
                             "overrides)")
    parser.add_argument("--retrieval_swap_policy",
                        choices=["refuse", "detach"], default=None,
                        help="hot-swap vs mounted index on fingerprint "
                             "mismatch: refuse the swap (default) or "
                             "commit it and detach the index "
                             "(/neighbors then answers 503)")
    # -- continuous-training pipeline (README "Continuous training") --
    parser.add_argument("--pipeline_dir", metavar="DIR",
                        help="`pipeline` subcommand state root: the "
                             "journaled pipeline manifest, per-stage "
                             "work dirs and the candidate artifacts "
                             "live here; a rerun of a killed pipeline "
                             "resumes from the last committed stage")
    parser.add_argument("--pipeline_raw", metavar="FILE",
                        help="new raw extractor output to ingest as a "
                             "delta shard against the frozen incumbent "
                             "vocab (OOV rate exported through obs)")
    parser.add_argument("--pipeline_incumbent", metavar="DIR",
                        help="the incumbent RELEASE ARTIFACT the fleet "
                             "serves today — shadow-eval's baseline "
                             "and the rollback identity")
    parser.add_argument("--pipeline_traffic", metavar="FILE",
                        help="recorded live-traffic sample to replay "
                             "through incumbent and candidate at "
                             "shadow-eval (what --serve_traffic_sample "
                             "records on serving replicas); empty = "
                             "gate on the accuracy harness alone")
    parser.add_argument("--pipeline_shadow_samples", type=int,
                        default=None, metavar="N",
                        help="max traffic lines replayed at shadow-eval "
                             "(deterministically sampled; default 256)")
    parser.add_argument("--pipeline_finetune_epochs", type=int,
                        default=None, metavar="N",
                        help="epochs the fine-tune stage trains on the "
                             "delta shard, resumed from the latest "
                             "committed checkpoint (default 1)")
    parser.add_argument("--pipeline_gate_top1_drop", type=float,
                        default=None, metavar="DELTA",
                        help="largest tolerated top-1 accuracy drop of "
                             "the candidate vs the incumbent before "
                             "the gate refuses promotion (default "
                             "0.01)")
    parser.add_argument("--pipeline_gate_topk_drop", type=float,
                        default=None, metavar="DELTA",
                        help="largest tolerated top-k accuracy drop "
                             "(default 0.01)")
    parser.add_argument("--pipeline_gate_f1_drop", type=float,
                        default=None, metavar="DELTA",
                        help="largest tolerated subtoken-F1 drop "
                             "(default 0.01)")
    parser.add_argument("--pipeline_gate_min_agreement", type=float,
                        default=None, metavar="RATIO",
                        help="smallest tolerated top-k agreement over "
                             "the replayed traffic slice (default "
                             "0.98; only checked when traffic was "
                             "replayed)")
    parser.add_argument("--pipeline_fleet", default=None,
                        metavar="HOST:PORT",
                        help="fleet router admin address the promote "
                             "stage drives the canary-first "
                             "coordinated swap through; empty = stop "
                             "after shadow-eval with a gated candidate "
                             "artifact on disk")
    parser.add_argument("--pipeline_model", default=None,
                        metavar="NAME",
                        help="fleet model group to promote into "
                             "(default 'default')")
    parser.add_argument("--pipeline_promote_timeout",
                        dest="pipeline_promote_timeout_s", type=float,
                        default=None, metavar="SECONDS",
                        help="budget for one fleet rollout to reach a "
                             "terminal state before the stage fails "
                             "(default 600)")
    parser.add_argument("--pipeline_refresh_retrieval",
                        action="store_true", default=None,
                        help="after promotion, re-embed the delta "
                             "shard with the candidate, build a fresh "
                             "ANN index behind its fingerprint and "
                             "remount it fleet-wide (refuse/detach "
                             "policy guards every replica transition)")
    parser.add_argument("--serve_traffic_sample",
                        dest="serve_traffic_sample_file", metavar="FILE",
                        help="record every Nth request's extracted "
                             "lines into this bounded ring file — the "
                             "shadow-eval replay corpus (README "
                             "'Continuous training'; off by default)")
    parser.add_argument("--serve_traffic_sample_every", type=int,
                        default=None, metavar="N",
                        help="sample every Nth cache-miss request into "
                             "the traffic ring (default 10)")
    parser.add_argument("--serve_traffic_sample_cap", type=int,
                        default=None, metavar="N",
                        help="lines the traffic sample ring retains "
                             "(default 4096)")
    parser.add_argument("--topk_block", dest="topk_block_size", type=int,
                        default=None, metavar="ROWS",
                        help="target-table rows per block of the "
                             "blockwise top-k prediction head (default "
                             "4096; 0 forces the classic full-logits "
                             "materialization)")
    parser.add_argument("-fw", "--framework", dest="dl_framework",
                        choices=["jax", "tensorflow", "keras"], default="jax",
                        help="accepted for reference CLI compatibility; this "
                             "framework always runs the JAX/TPU backend")
    parser.add_argument("--tensorboard", dest="use_tensorboard",
                        action="store_true",
                        help="write TensorBoard scalars (train loss/"
                             "throughput + eval metrics) next to the model "
                             "artifacts")
    parser.add_argument("-v", "--verbose", dest="verbose_mode", type=int,
                        default=1, help="verbose mode in {0,1,2}")
    parser.add_argument("-lp", "--logs-path", dest="logs_path", metavar="FILE",
                        required=False, help="log file path")
    # TPU-native knobs
    parser.add_argument("--dp", type=int, default=1,
                        help="data-parallel mesh axis size")
    parser.add_argument("--tp", type=int, default=1,
                        help="tensor-parallel (row-sharded tables) axis size")
    parser.add_argument("--cp", type=int, default=1,
                        help="context-parallel axis size (shards MAX_CONTEXTS)")
    parser.add_argument("--compute_dtype", choices=["bfloat16", "float32"],
                        default="bfloat16")
    parser.add_argument("--adam_mu_dtype", choices=["bfloat16", "float32"],
                        default=None,
                        help="Adam first-moment storage dtype (default: "
                             "config.py's bfloat16); resuming an artifact "
                             "saved under a different dtype requires "
                             "matching it (checkpoint meta is checked)")
    parser.add_argument("--adam_nu_dtype", choices=["bfloat16", "float32"],
                        default=None,
                        help="Adam second-moment storage dtype (see "
                             "--adam_mu_dtype)")
    parser.add_argument("--batch_size", type=int, default=None)
    parser.add_argument("--test_batch_size", type=int, default=None)
    parser.add_argument("--epochs", type=int, default=None)
    parser.add_argument("--max_contexts", type=int, default=None)
    parser.add_argument("--seed", type=int, default=42)
    parser.add_argument("--no_packed_data", action="store_true",
                        help="stream text .c2v instead of packed .c2vb")
    parser.add_argument("--train_corpus_manifest", metavar="FILE",
                        default=None,
                        help="train from a corpus manifest (JSON list "
                             "of .c2vb shards — incumbent pack + delta "
                             "shards) as one logical row space with "
                             "the same epoch-keyed global shuffle as a "
                             "single pack; build/grow it with the "
                             "`corpus` subcommand (README 'Training at "
                             "pod scale')")
    parser.add_argument("--prefetch_double_buffer",
                        action="store_true", default=None,
                        help="double-buffer device transfers: issue "
                             "batch N+1's device_put before handing "
                             "batch N to the step loop, overlapping "
                             "the transfer with step dispatch (one "
                             "extra batch of device memory; watch "
                             "train_input_bound_fraction)")
    parser.add_argument("--gspmd", action="store_true",
                        help="disable the manual shard_map TP kernels and "
                             "rely on GSPMD sharding propagation")
    parser.add_argument("--sparse_embedding_update", action="store_true",
                        help="touched-rows (lazy) Adam for the token/path "
                             "tables; wins at pod scale with the manual TP "
                             "kernels (see config.py)")
    parser.add_argument("--rss_limit_gb", type=float, default=0.0,
                        help="checkpoint-and-stop (like SIGTERM "
                             "preemption) when process peak RSS crosses "
                             "this many GB; 0 disables")
    parser.add_argument("--on_nonfinite_loss", choices=["halt", "warn"],
                        default=None,
                        help="what to do when a log-window average loss "
                             "is NaN/Inf: halt (default; checkpoint via "
                             "the preemption path and exit nonzero) or "
                             "warn (log and continue)")
    parser.add_argument("--extractor_timeout", dest="extractor_timeout_s",
                        type=float, default=None, metavar="SECONDS",
                        help="kill a hung serving-side path-extractor "
                             "child after this many seconds (default: "
                             "config.py's 120; 0 disables)")
    parser.add_argument("--extractor_retries", dest="extractor_retries",
                        type=int, default=None, metavar="N",
                        help="retry a crashed/failed-to-launch "
                             "serving-side extractor child up to N times "
                             "with exponential backoff (default: "
                             "config.py's 2; timeouts are never retried; "
                             "0 disables)")
    parser.add_argument("--async_checkpointing", action="store_true",
                        help="defer the checkpoint commit (Orbax flush "
                             "wait + cross-host barrier + manifest + "
                             "atomic rename) to a background commit "
                             "thread with bounded in-flight depth; the "
                             "step loop only pays staging + dispatch. "
                             "Crash-atomicity and the multi-host commit "
                             "protocol are unchanged")
    parser.add_argument("--save_barrier_timeout",
                        dest="save_barrier_timeout_s", type=float,
                        default=None, metavar="SECONDS",
                        help="per-barrier timeout of the cross-host "
                             "checkpoint commit protocol (default: "
                             "config.py's 600); on expiry the save "
                             "fails loudly instead of hanging the pod "
                             "on a dead peer")
    parser.add_argument("--no_cursor_resume", action="store_true",
                        help="ignore the checkpoint's saved data cursor "
                             "and re-run an interrupted epoch from its "
                             "start instead of skipping the rows it "
                             "already consumed (cursor resume works on "
                             "any host count; see README 'Elastic "
                             "resume')")
    parser.add_argument("--corpus_create", metavar="SHARD[,SHARD...]",
                        default=None,
                        help="(`corpus` subcommand) build a new "
                             "manifest at --train_corpus_manifest over "
                             "these .c2vb shards, in order (shard "
                             "order defines global row ids); refuses "
                             "mixed-vocab shard sets")
    parser.add_argument("--corpus_add", metavar="SHARD", default=None,
                        help="(`corpus` subcommand) append one .c2vb "
                             "delta shard to the manifest — pure "
                             "append, existing row ids stay stable; "
                             "refused on vocab-fingerprint mismatch")
    parser.add_argument("--corpus_validate", action="store_true",
                        default=None,
                        help="(`corpus` subcommand) re-read every "
                             "listed shard's header/meta and fail on "
                             "drift (row count changed, mixed vocab) "
                             "instead of just printing the manifest")
    parser.add_argument("--preprocess_workers", type=int, default=0,
                        metavar="N",
                        help="host worker processes for the on-demand "
                             ".c2v -> .c2vb pack at training startup "
                             "(and the offline fused corpus compiler); "
                             "output is byte-identical at any worker "
                             "count; 0 = in-process serial")
    parser.add_argument("--checkpoint_hash_content", action="store_true",
                        help="record full-content sha256 of every "
                             "checkpoint file (incl. the Orbax shards, "
                             "hashed on a thread pool AFTER the atomic "
                             "commit) into the manifest; resume "
                             "verifies the hashes when present")
    parser.add_argument("--profile_dir", metavar="DIR",
                        help="write a jax.profiler trace of train batches "
                             "10-20 to DIR (TensorBoard/Perfetto viewable)")
    parser.add_argument("--metrics_file", metavar="FILE",
                        help="write a Prometheus text-format metrics "
                             "snapshot here, atomically rewritten at every "
                             "log boundary (node-exporter textfile style)")
    parser.add_argument("--metrics_port", type=int, default=0,
                        metavar="PORT",
                        help="serve the Prometheus snapshot at "
                             "http://127.0.0.1:PORT/metrics during "
                             "training; 0 disables")
    parser.add_argument("--heartbeat_file", metavar="FILE",
                        help="atomically rewrite a JSON heartbeat {step, "
                             "epoch, last_loss, wall_time, ...} here each "
                             "log window so external watchdogs can detect "
                             "hangs by staleness")
    parser.add_argument("--trace_export", metavar="FILE",
                        help="write host-side wall-time spans (data wait/"
                             "dispatch/loss sync/checkpoint/eval) as Chrome "
                             "trace-event JSON here when training ends "
                             "(Perfetto-loadable; complements "
                             "--profile_dir's device trace)")
    return parser


def config_from_args(argv=None) -> Config:
    if argv is None:
        argv = sys.argv[1:]
    # Subcommand sugar: `code2vec_tpu serve --load M` == `--serve
    # --load M`; `code2vec_tpu export --load M --artifact_out D` builds
    # a release artifact (README "Release artifacts"); `embed`,
    # `index-build` and `export-embeddings` are the retrieval-stack
    # jobs (README "Retrieval").
    subcommands = ("serve", "fleet", "export", "embed", "index-build",
                   "export-embeddings", "pipeline", "corpus")
    subcommand = argv[0] if argv and argv[0] in subcommands else None
    if subcommand:
        argv = argv[1:]
    # `fleet` = a serving deployment whose parent is the control plane
    # (README "Fleet"); each host it launches re-runs this CLI as
    # `serve`.
    serve_subcommand = subcommand in ("serve", "fleet")
    args = arguments_parser().parse_args(argv)
    if subcommand == "export" and not args.export_artifact_path:
        raise SystemExit(
            "the `export` subcommand requires --artifact_out DIR")
    if subcommand == "embed" and not args.embed_out:
        raise SystemExit("the `embed` subcommand requires --embed_out "
                         "DIR (plus --test CORPUS and --load/--artifact)")
    if subcommand == "index-build" and not (args.index_vectors
                                            and args.index_out):
        raise SystemExit("the `index-build` subcommand requires "
                         "--vectors DIR and --index_out DIR")
    if subcommand == "export-embeddings" and not args.embeddings_out:
        raise SystemExit("the `export-embeddings` subcommand requires "
                         "--embeddings_out DIR (plus --load MODEL)")
    if subcommand == "pipeline" and not args.pipeline_dir:
        raise SystemExit(
            "the `pipeline` subcommand requires --pipeline_dir DIR "
            "(plus --load CKPT, --pipeline_raw FILE, "
            "--pipeline_incumbent DIR and --test CORPUS)")
    if subcommand == "corpus" and not args.train_corpus_manifest:
        raise SystemExit(
            "the `corpus` subcommand requires --train_corpus_manifest "
            "FILE (plus --corpus_create/--corpus_add/--corpus_validate "
            "for the mutation/check actions; plain `corpus` lists the "
            "manifest)")
    knobs = {knob: value for knob in ("adam_mu_dtype", "adam_nu_dtype",
                                      "on_nonfinite_loss",
                                      "extractor_timeout_s",
                                      "extractor_retries",
                                      "save_barrier_timeout_s",
                                      "serve_port", "serve_host",
                                      "serve_batch_size",
                                      "serve_max_delay_ms",
                                      "serve_continuous",
                                      "serve_inflight_steps",
                                      "serve_buckets",
                                      "serve_cache_entries",
                                      "extractor_pool_size",
                                      "serve_drain_timeout_s",
                                      "serve_deadline_ms",
                                      "serve_deadline_max_ms",
                                      "serve_queue_depth",
                                      "serve_tenants",
                                      "serve_tenant_default_weight",
                                      "serve_tenant_qps",
                                      "serve_breaker_window_s",
                                      "serve_breaker_failure_ratio",
                                      "serve_breaker_min_requests",
                                      "serve_breaker_cooldown_s",
                                      "serve_replicas",
                                      "serve_max_restarts",
                                      "serve_heartbeat_interval_s",
                                      "serve_debug_trace",
                                      "serve_flight_dir",
                                      "serve_flight_records",
                                      "serve_flight_max_dumps",
                                      "serve_telemetry_port",
                                      "fleet_hosts", "fleet_port",
                                      "fleet_models",
                                      "fleet_poll_interval_s",
                                      "fleet_scale_min",
                                      "fleet_scale_max",
                                      "fleet_scale_up_shed_rate",
                                      "fleet_scale_up_p95_ms",
                                      "fleet_scale_up_ticks",
                                      "fleet_scale_down_ticks",
                                      "fleet_scale_cooldown_s",
                                      "fleet_swap_timeout_s",
                                      "fleet_max_host_restarts",
                                      "fleet_routers",
                                      "fleet_control",
                                      "fleet_launcher",
                                      "fleet_addresses",
                                      "fleet_tsdb_retention_s",
                                      "fleet_tsdb_max_mb",
                                      "fleet_slo_availability",
                                      "fleet_slo_latency_ms",
                                      "fleet_slo_latency_target",
                                      "fleet_slo_period_s",
                                      "fleet_slo_window_scale",
                                      "fleet_trace_id",
                                      "fleet_trace_dir",
                                      "serve_artifact",
                                      "export_artifact_path",
                                      "release_scheme",
                                      "serve_mips_nprobe",
                                      "serve_mips_nlist",
                                      "serve_mips_crossover",
                                      "overlap_grad_allreduce",
                                      "overlap_bucket_mb",
                                      "overlap_in_backward",
                                      "prefetch_double_buffer",
                                      "train_corpus_manifest",
                                      "topk_block_size",
                                      "embed_out", "embed_dtype",
                                      "embed_shard_rows",
                                      "embeddings_out",
                                      "index_vectors", "index_out",
                                      "index_nlist", "index_nprobe",
                                      "index_kmeans_iters",
                                      "index_metric",
                                      "retrieval_index",
                                      "retrieval_topk",
                                      "retrieval_swap_policy",
                                      "pipeline_dir", "pipeline_raw",
                                      "pipeline_incumbent",
                                      "pipeline_traffic",
                                      "pipeline_shadow_samples",
                                      "pipeline_finetune_epochs",
                                      "pipeline_gate_top1_drop",
                                      "pipeline_gate_topk_drop",
                                      "pipeline_gate_f1_drop",
                                      "pipeline_gate_min_agreement",
                                      "pipeline_fleet",
                                      "pipeline_model",
                                      "pipeline_promote_timeout_s",
                                      "pipeline_refresh_retrieval",
                                      "serve_traffic_sample_file",
                                      "serve_traffic_sample_every",
                                      "serve_traffic_sample_cap")
             if (value := getattr(args, knob)) is not None}
    if args.fleet_no_affinity:
        knobs["fleet_cache_affinity"] = False
    config = Config(
        predict=args.predict,
        serve=args.serve or serve_subcommand,
        fleet=subcommand == "fleet",
        pipeline=subcommand == "pipeline",
        corpus=subcommand == "corpus",
        corpus_create=args.corpus_create,
        corpus_add=args.corpus_add,
        corpus_validate=bool(args.corpus_validate),
        model_save_path=args.save_path,
        model_load_path=args.load_path,
        train_data_path_prefix=args.data_path,
        test_data_path=args.test_path,
        release=args.release,
        export_code_vectors=args.export_code_vectors,
        save_w2v=args.save_w2v,
        save_t2v=args.save_t2v,
        verbose_mode=args.verbose_mode,
        logs_path=args.logs_path,
        use_tensorboard=args.use_tensorboard,
        use_sparse_embedding_update=args.sparse_embedding_update,
        dp=args.dp, tp=args.tp, cp=args.cp,
        compute_dtype=args.compute_dtype,
        **knobs,
        # A knob present here was typed on the command line — consumers
        # that would otherwise override a config DEFAULT (ReleaseModel
        # adopting the artifact's serve_batch_size) must not override an
        # explicitly-requested value, even one equal to the default.
        explicit_knobs=tuple(sorted(knobs)),
        release_quantize=not args.no_quantize,
        release_aot=not args.no_aot,
        vectors_text=args.vectors_text,
        async_checkpointing=args.async_checkpointing,
        cursor_resume=not args.no_cursor_resume,
        seed=args.seed,
        use_packed_data=not args.no_packed_data,
        preprocess_workers=args.preprocess_workers,
        checkpoint_hash_content=args.checkpoint_hash_content,
        use_manual_tp_kernels=not args.gspmd,
        rss_limit_gb=args.rss_limit_gb,
        profile_dir=args.profile_dir,
        metrics_file=args.metrics_file,
        metrics_port=args.metrics_port,
        heartbeat_file=args.heartbeat_file,
        trace_export=args.trace_export,
    )
    if args.batch_size:
        config.train_batch_size = args.batch_size
        config.test_batch_size = args.batch_size
    if args.test_batch_size:
        config.test_batch_size = args.test_batch_size
    if args.epochs:
        config.num_train_epochs = args.epochs
    if args.max_contexts:
        config.max_contexts = args.max_contexts
    return config


def corpus_main(config) -> int:
    """`corpus` subcommand: sharded-corpus manifest tooling. Never
    builds a model — fingerprints come from the shards' own meta
    sidecars, so the manifest can be managed on a machine that has no
    vocabularies loaded."""
    from code2vec_tpu.data import packed
    manifest_path = config.train_corpus_manifest
    try:
        if config.corpus_create:
            shards = [s for s in config.corpus_create.split(",") if s]
            packed.create_manifest(manifest_path, shards)
            config.log(f"created {manifest_path} "
                       f"({len(shards)} shard(s))")
        if config.corpus_add:
            packed.append_manifest_shard(manifest_path, config.corpus_add)
            config.log(f"appended {config.corpus_add} to {manifest_path}")
        manifest = packed.load_manifest(manifest_path)
        if config.corpus_validate:
            reports = packed.validate_manifest(manifest_path)
        else:
            reports = manifest["shards"]
    except (ValueError, OSError) as e:
        config.log(f"corpus: {e}")
        return 1
    total = sum(r["rows"] for r in reports)
    config.log(f"{manifest_path}: {len(reports)} shard(s), {total} rows, "
               f"max_contexts={manifest['max_contexts']}, vocab "
               f"fingerprint {manifest.get('vocab_fingerprint')}"
               + (" [validated]" if config.corpus_validate else ""))
    for r in reports:
        config.log(f"  {r['path']}: {r['rows']} rows, "
                   f"fingerprint={r.get('vocab_fingerprint')}")
    return 0


def main(argv=None) -> None:
    # dispatch mirrors reference code2vec.py:16-37
    if argv is None:
        argv = sys.argv[1:]
    config = config_from_args(argv)
    config.verify()

    # Corpus manifest tooling: pure file-level job, no model, no
    # distributed runtime (README "Training at pod scale").
    if config.corpus:
        sys.exit(corpus_main(config))

    # Continuous-training pipeline: the supervisor PARENT never builds
    # a model either — each stage re-execs this CLI (train/export/
    # embed/index-build) or drives the fleet router over HTTP, and the
    # journaled manifest makes a killed run resumable
    # (pipeline/supervisor.py, README "Continuous training").
    if config.pipeline:
        from code2vec_tpu.pipeline.supervisor import pipeline_main
        sys.exit(pipeline_main(config, argv=list(argv)))

    # Trace collector: `fleet --fleet_trace_id ID` stitches every
    # process's span files (or a live control plane's, via
    # --fleet_control) into ONE Chrome trace on stdout — it launches
    # nothing. Must dispatch before the router/fleet branches.
    if config.fleet and config.fleet_trace_id:
        from code2vec_tpu.obs.stitch import stitch_main
        sys.exit(stitch_main(config))

    # Edge router agent: a `fleet` re-exec child marked by
    # C2V_FLEET_ROUTER never builds a model — it routes over a polled
    # copy of the fleet view (serving/fleet/edge.py, README "Edge").
    # Must dispatch before the fleet branch: the child's argv still
    # says `fleet`.
    if (config.serve and config.fleet
            and "C2V_FLEET_ROUTER" in os.environ):
        from code2vec_tpu.serving.fleet.edge import router_main
        sys.exit(router_main(config))

    # Cross-host fleet: the control-plane PARENT never builds a model;
    # it launches one `serve` supervisor per host behind the
    # health-gated router and drives scaling + coordinated hot-swap
    # (serving/fleet/, README "Fleet").
    if (config.serve and config.fleet
            and "C2V_FLEET_HOST" not in os.environ
            and "C2V_SERVE_REPLICA" not in os.environ):
        from code2vec_tpu.serving.fleet.control import fleet_main
        sys.exit(fleet_main(config, argv=list(argv)))

    # Supervised multi-replica serving: the PARENT never builds a model
    # (each replica is its own process with its own model + extractor
    # pool); it forks N re-execed copies of this command with
    # --replicas stripped, monitors their heartbeats, restarts crashed
    # or hung ones, and fans SIGTERM out as a coordinated drain. A
    # fleet HOST always supervises (even at --replicas 1) so the
    # control plane gets its telemetry listener + scaling headroom.
    if (config.serve
            and (config.serve_replicas > 1
                 or "C2V_FLEET_HOST" in os.environ)
            and "C2V_SERVE_REPLICA" not in os.environ):
        from code2vec_tpu.serving.supervisor import supervisor_main
        sys.exit(supervisor_main(config, argv=list(argv)))

    # joins the multi-host runtime when a coordinator is configured;
    # no-op on single-process runs (parallel/distributed.py)
    from code2vec_tpu.parallel import distributed
    distributed.initialize()

    if config.index_out:
        # `index-build` is a pure vector-store -> ANN-artifact job: no
        # model, no checkpoint — the store manifest carries the
        # embedding fingerprint the index inherits.
        from code2vec_tpu.retrieval.index import build_index
        build_index(config.index_vectors, config.index_out,
                    nlist=config.index_nlist,
                    nprobe=config.index_nprobe,
                    kmeans_iters=config.index_kmeans_iters,
                    seed=config.seed, metric=config.index_metric,
                    log=config.log)
        return

    if config.serve_artifact:
        # Release-artifact runtime: no checkpoint, no training state —
        # the artifact carries tables + vocabs + AOT lowerings.
        from code2vec_tpu.release.runtime import ReleaseModel
        model = ReleaseModel(config)
        if config.embed_out:
            # embed from the quantized bundle: fused-dequant tables +
            # blockwise top-k, no checkpoint in RSS
            from code2vec_tpu.retrieval.embed_job import run_embed_job
            run_embed_job(model)
            return
        if not (config.predict or config.serve or config.is_testing):
            config.log("--artifact given without `serve`, --predict or "
                       "--test; nothing to do")
        if config.is_testing:
            eval_results = model.evaluate()
            config.log(
                str(eval_results).replace(
                    "topk",
                    f"top{config.top_k_words_considered_during_prediction}"))
        if config.predict:
            from code2vec_tpu.serving.interactive import InteractivePredictor
            InteractivePredictor(config, model).predict()
        if config.serve:
            from code2vec_tpu.serving.server import serve_main
            sys.exit(serve_main(config, model))
        return

    from code2vec_tpu.model_facade import Code2VecModel
    model = Code2VecModel(config)

    if config.export_artifact_path:
        from code2vec_tpu.release.artifact import export_artifact
        export_artifact(model, config.export_artifact_path)
        return

    if config.embed_out:
        from code2vec_tpu.retrieval.embed_job import run_embed_job
        run_embed_job(model)
        return

    if config.embeddings_out:
        model.export_embeddings(config.embeddings_out)
        return

    if config.is_training:
        model.train()
    if config.save_w2v is not None:
        model.save_word2vec_format(config.save_w2v, VocabType.Token)
        config.log(f"Origin word vectors saved in word2vec text format in: "
                   f"{config.save_w2v}")
    if config.save_t2v is not None:
        model.save_word2vec_format(config.save_t2v, VocabType.Target)
        config.log(f"Target word vectors saved in word2vec text format in: "
                   f"{config.save_t2v}")
    if (config.is_testing and not config.is_training) or config.release:
        eval_results = model.evaluate()
        if eval_results is not None:
            config.log(
                str(eval_results).replace(
                    "topk",
                    f"top{config.top_k_words_considered_during_prediction}"))
    if config.predict:
        from code2vec_tpu.serving.interactive import InteractivePredictor
        predictor = InteractivePredictor(config, model)
        predictor.predict()
    if config.serve:
        from code2vec_tpu.serving.server import serve_main
        sys.exit(serve_main(config, model))


if __name__ == "__main__":
    main()
