from code2vec_tpu.data.reader import (  # noqa: F401
    EstimatorAction,
    RowBatch,
    PathContextReader,
    parse_context_lines,
)
from code2vec_tpu.data.packed import pack_c2v, PackedDataset  # noqa: F401
