"""Packed binary `.c2vb` datasets: `.c2v` text compiled to int32 memmaps.

The reference parses 201-field CSV rows and does string hash-table lookups
inside the input graph on every epoch (reference:
path_context_reader.py:122-125, 184-228). At the TPU north-star rate
(>=47K examples/sec, BASELINE.md) text parsing is the bottleneck, so —
like the reference's own offline preprocess stage — we compile the text
once into integer arrays and train from a zero-copy memmap. Layout:

    [ 16-byte header: magic 'C2VB', uint32 version, uint32 N, uint32 M ]
    [ target_index  int32 (N,)   ]
    [ source_tokens int32 (N, M) ]
    [ paths         int32 (N, M) ]
    [ target_tokens int32 (N, M) ]

An optional `<path>.targets` sidecar holds one raw target string per row
(needed by evaluation, which scores OOV targets too). Vocab identity is
guarded by a content hash in the sidecar meta.
"""

from __future__ import annotations

import hashlib
import json
import os
import struct
from typing import Iterator, List, Optional

import numpy as np

from code2vec_tpu.data import reader as reader_mod
from code2vec_tpu.data.reader import EpochEnd, EstimatorAction, RowBatch
from code2vec_tpu.vocab import Code2VecVocabs

_MAGIC = b"C2VB"
_VERSION = 1
_HEADER = struct.Struct("<4sIII")


def vocabs_fingerprint(vocabs: Code2VecVocabs) -> str:
    """Cheap content hash to detect vocab/packed-data mismatch."""
    h = hashlib.sha256()
    for vocab in (vocabs.token_vocab, vocabs.path_vocab, vocabs.target_vocab):
        h.update(str(vocab.size).encode())
        for idx in (0, 1, vocab.size // 2, vocab.size - 1):
            h.update(vocab.index_to_word.get(idx, "").encode())
    return h.hexdigest()[:16]


def pack_c2v(c2v_path: str, vocabs: Code2VecVocabs, max_contexts: int,
             out_path: Optional[str] = None, chunk_lines: int = 8192,
             write_targets_sidecar: bool = True) -> str:
    """Compile a `.c2v` text file into a `.c2vb` memmap (returns its path)."""
    out_path = out_path or (c2v_path + "b")  # data.train.c2v -> data.train.c2vb
    tmp_path = out_path + ".tmp"
    n_rows = 0
    targets_sidecar = out_path + ".targets" if write_targets_sidecar else None

    # Native whole-file compile when libc2vdata.so is built (same layout,
    # multithreaded split+lookup in C++); both branches share the meta
    # write below.
    from code2vec_tpu.data import native
    tables = native.tables_for(vocabs)
    if tables is not None:
        n_rows = tables.pack_file(c2v_path, out_path, max_contexts,
                                  targets_path=targets_sidecar)
        return _write_pack_meta(out_path, c2v_path, n_rows, max_contexts,
                                vocabs)

    with open(tmp_path, "wb") as out:
        out.write(_HEADER.pack(_MAGIC, _VERSION, 0, max_contexts))
        tgt_file = open(targets_sidecar, "w") if targets_sidecar else None
        try:
            chunk: List[str] = []
            with open(c2v_path, "r", buffering=16 * 1024 * 1024) as f:
                for line in f:
                    chunk.append(line)
                    if len(chunk) >= chunk_lines:
                        n_rows += _write_chunk(out, tgt_file, chunk, vocabs,
                                               max_contexts)
                        chunk = []
            if chunk:
                n_rows += _write_chunk(out, tgt_file, chunk, vocabs, max_contexts)
        finally:
            if tgt_file:
                tgt_file.close()
        out.seek(0)
        out.write(_HEADER.pack(_MAGIC, _VERSION, n_rows, max_contexts))
    os.replace(tmp_path, out_path)
    return _write_pack_meta(out_path, c2v_path, n_rows, max_contexts, vocabs)


def _write_pack_meta(out_path: str, c2v_path: str, n_rows: int,
                     max_contexts: int, vocabs: Code2VecVocabs) -> str:
    meta = {"rows": n_rows, "max_contexts": max_contexts,
            "vocab_fingerprint": vocabs_fingerprint(vocabs),
            "source": os.path.basename(c2v_path)}
    with open(out_path + ".meta.json", "w") as f:
        json.dump(meta, f)
    return out_path


def _write_chunk(out, tgt_file, chunk, vocabs, max_contexts) -> int:
    batch = reader_mod.parse_context_lines(
        chunk, vocabs, max_contexts, EstimatorAction.Evaluate)
    # Each row is written interleaved as [target, src, path, tgt] so the
    # file stays appendable in a single streaming pass.
    n, m = batch.source_token_indices.shape
    rec = np.empty((n, 1 + 3 * m), dtype=np.int32)
    rec[:, 0] = batch.target_index
    rec[:, 1:1 + m] = batch.source_token_indices
    rec[:, 1 + m:1 + 2 * m] = batch.path_indices
    rec[:, 1 + 2 * m:] = batch.target_token_indices
    out.write(rec.tobytes())
    if tgt_file and batch.target_strings:
        tgt_file.write("\n".join(batch.target_strings) + "\n")
    return n


class PackedDataset:
    """Zero-copy view over a `.c2vb` file with batched iteration.

    Training iteration uses a full random permutation per epoch (strictly
    better shuffling than the reference's 10K-element buffer,
    path_context_reader.py:139) and yields fixed-size batches.
    """

    def __init__(self, path: str, vocabs: Code2VecVocabs,
                 shard_index: int = 0, num_shards: int = 1):
        self.path = path
        self.vocabs = vocabs
        with open(path, "rb") as f:
            magic, version, n, m = _HEADER.unpack(f.read(_HEADER.size))
        if magic != _MAGIC:
            raise ValueError(f"{path} is not a .c2vb file")
        if version != _VERSION:
            raise ValueError(f"{path}: unsupported .c2vb version {version}")
        self.num_rows_total = n
        self.max_contexts = m
        self._rec = np.memmap(path, dtype=np.int32, mode="r",
                              offset=_HEADER.size,
                              shape=(n, 1 + 3 * m))
        meta_path = path + ".meta.json"
        if os.path.exists(meta_path):
            with open(meta_path) as f:
                meta = json.load(f)
            fp = vocabs_fingerprint(vocabs)
            if meta.get("vocab_fingerprint") not in (None, fp):
                raise ValueError(
                    f"{path} was packed with different vocabularies "
                    f"(fingerprint {meta.get('vocab_fingerprint')} != {fp}); re-pack it.")
        # Host shard: disjoint strided row subset.
        self.row_ids = np.arange(shard_index, n, num_shards)
        self._target_strings: Optional[List[str]] = None
        self._filtered_cache: dict = {}

    def __len__(self) -> int:
        return len(self.row_ids)

    @property
    def target_strings(self) -> Optional[List[str]]:
        sidecar = self.path + ".targets"
        if self._target_strings is None and os.path.exists(sidecar):
            with open(sidecar, "r") as f:
                strings = f.read().splitlines()
            # cross-check: a stale/partial sidecar (e.g. interrupted
            # re-pack) must not silently mislabel evaluation rows
            if len(strings) != self.num_rows_total:
                raise ValueError(
                    f"{sidecar} has {len(strings)} rows but {self.path} has "
                    f"{self.num_rows_total}; re-pack the dataset.")
            self._target_strings = strings
        return self._target_strings

    def gather(self, rows: np.ndarray,
               with_target_strings: bool = False) -> RowBatch:
        m = self.max_contexts
        rec = np.asarray(self._rec[rows])  # copy out of the memmap
        src = rec[:, 1:1 + m]
        pth = rec[:, 1 + m:1 + 2 * m]
        tgt = rec[:, 1 + 2 * m:]
        token_pad = self.vocabs.token_vocab.pad_index
        path_pad = self.vocabs.path_vocab.pad_index
        mask = ((src != token_pad) | (tgt != token_pad) | (pth != path_pad))
        strings = None
        if with_target_strings and self.target_strings is not None:
            strings = [self.target_strings[r] for r in rows]
        return RowBatch(
            source_token_indices=src,
            path_indices=pth,
            target_token_indices=tgt,
            context_valid_mask=mask.astype(np.float32),
            target_index=rec[:, 0],
            example_valid=np.ones((len(rows),), dtype=bool),
            target_strings=strings,
        )

    def _filtered_row_ids(self, estimator_action: EstimatorAction) -> np.ndarray:
        """Apply the reference row filter once, vectorized over the memmap.
        Cached per action: the result is immutable for a given file, and
        both `steps_per_epoch` and `iter_batches` need it (mid-epoch eval
        calls both every firing — one O(rows) scan, not two)."""
        cached = self._filtered_cache.get(estimator_action)
        if cached is not None:
            return cached
        m = self.max_contexts
        token_pad = self.vocabs.token_vocab.pad_index
        path_pad = self.vocabs.path_vocab.pad_index
        keep_chunks = []
        for start in range(0, len(self.row_ids), 1 << 18):
            rows = self.row_ids[start:start + (1 << 18)]
            rec = self._rec[rows]
            src = rec[:, 1:1 + m]
            pth = rec[:, 1 + m:1 + 2 * m]
            tgt = rec[:, 1 + 2 * m:]
            any_valid = ((src != token_pad) | (tgt != token_pad)
                         | (pth != path_pad)).any(axis=1)
            if estimator_action.is_train:
                any_valid &= rec[:, 0] > self.vocabs.target_vocab.oov_index
            keep_chunks.append(rows[any_valid])
        out = (np.concatenate(keep_chunks) if keep_chunks
               else np.empty((0,), np.int64))
        self._filtered_cache[estimator_action] = out
        return out

    def steps_per_epoch(self, batch_size: int,
                        estimator_action: EstimatorAction) -> int:
        """Exact number of batches one data pass yields (post-filter) —
        unlike the reference's raw-line `train_steps_per_epoch`
        (config.py:165-167), this counts the rows the trainer will
        actually consume."""
        n = len(self._filtered_row_ids(estimator_action))
        if estimator_action.is_train:
            return n // batch_size
        return -(-n // batch_size)  # eval pads the tail batch

    def iter_batches(self, batch_size: int, estimator_action: EstimatorAction,
                     num_epochs: int = 1, seed: int = 0,
                     repeat_endlessly: bool = False,
                     with_target_strings: bool = False,
                     yield_epoch_markers: bool = False) -> Iterator[RowBatch]:
        rows = self._filtered_row_ids(estimator_action)
        rng = np.random.default_rng(seed)
        epoch = 0
        while repeat_endlessly or epoch < num_epochs:
            order = rng.permutation(rows) if estimator_action.is_train else rows
            n_full = (len(order) // batch_size) * batch_size
            for start in range(0, n_full, batch_size):
                yield self.gather(order[start:start + batch_size],
                                  with_target_strings)
            tail = len(order) - n_full
            if tail and not estimator_action.is_train:
                batch = self.gather(order[n_full:], with_target_strings)
                yield reader_mod._pad_rows(batch, batch_size)
            epoch += 1
            if yield_epoch_markers:
                yield EpochEnd(epoch)
