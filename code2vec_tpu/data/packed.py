"""Packed binary `.c2vb` datasets: `.c2v` text compiled to int32 memmaps.

The reference parses 201-field CSV rows and does string hash-table lookups
inside the input graph on every epoch (reference:
path_context_reader.py:122-125, 184-228). At the TPU north-star rate
(>=47K examples/sec, BASELINE.md) text parsing is the bottleneck, so —
like the reference's own offline preprocess stage — we compile the text
once into integer arrays and train from a zero-copy memmap. Layout:

    [ 16-byte header: magic 'C2VB', uint32 version, uint32 N, uint32 M ]
    [ target_index  int32 (N,)   ]
    [ source_tokens int32 (N, M) ]
    [ paths         int32 (N, M) ]
    [ target_tokens int32 (N, M) ]

An optional `<path>.targets` sidecar holds one raw target string per row
(needed by evaluation, which scores OOV targets too). Vocab identity is
guarded by a content hash in the sidecar meta.
"""

from __future__ import annotations

import hashlib
import json
import os
import random
import shutil
import struct
import tempfile
from typing import Dict, Iterator, List, Optional

import numpy as np

from code2vec_tpu.data import preprocess as preprocess_mod
from code2vec_tpu.data import reader as reader_mod
from code2vec_tpu.data.reader import EpochEnd, EstimatorAction, RowBatch
from code2vec_tpu.vocab import Code2VecVocabs

_MAGIC = b"C2VB"
_VERSION = 1
_HEADER = struct.Struct("<4sIII")


def vocabs_fingerprint(vocabs: Code2VecVocabs) -> str:
    """Cheap content hash to detect vocab/packed-data mismatch."""
    h = hashlib.sha256()
    for vocab in (vocabs.token_vocab, vocabs.path_vocab, vocabs.target_vocab):
        h.update(str(vocab.size).encode())
        for idx in (0, 1, vocab.size // 2, vocab.size - 1):
            h.update(vocab.index_to_word.get(idx, "").encode())
    return h.hexdigest()[:16]


def pack_c2v(c2v_path: str, vocabs: Code2VecVocabs, max_contexts: int,
             out_path: Optional[str] = None, chunk_lines: int = 8192,
             write_targets_sidecar: bool = True, num_workers: int = 0) -> str:
    """Compile a `.c2v` text file into a `.c2vb` memmap (returns its path).

    `num_workers > 1` shards the text by line-aligned byte ranges across
    that many worker processes (row order — and therefore the output
    bytes — are unchanged); the native whole-file path still wins when
    libc2vdata.so is built.
    """
    out_path = out_path or (c2v_path + "b")  # data.train.c2v -> data.train.c2vb
    tmp_path = out_path + ".tmp"
    n_rows = 0
    targets_sidecar = out_path + ".targets" if write_targets_sidecar else None

    # Native whole-file compile when libc2vdata.so is built (same layout,
    # multithreaded split+lookup in C++); both branches share the meta
    # write below.
    from code2vec_tpu.data import native
    tables = native.tables_for(vocabs)
    if tables is not None:
        n_rows = tables.pack_file(c2v_path, out_path, max_contexts,
                                  targets_path=targets_sidecar)
        return _write_pack_meta(out_path, c2v_path, n_rows, max_contexts,
                                vocabs)

    if num_workers > 1:
        # Compat mode of the fused compiler: no sampling (contexts past
        # `max_contexts` are truncated like `parse_context_lines`), one
        # row per line — exactly the serial loop below, sharded.
        pack_raw(c2v_path, out_path, vocabs, None, None, max_contexts,
                 num_workers=num_workers,
                 write_targets_sidecar=write_targets_sidecar)
        return out_path

    with open(tmp_path, "wb") as out:
        out.write(_HEADER.pack(_MAGIC, _VERSION, 0, max_contexts))
        tgt_file = open(targets_sidecar, "w") if targets_sidecar else None
        try:
            chunk: List[str] = []
            with open(c2v_path, "r", buffering=16 * 1024 * 1024) as f:
                for line in f:
                    chunk.append(line)
                    if len(chunk) >= chunk_lines:
                        n_rows += _write_chunk(out, tgt_file, chunk, vocabs,
                                               max_contexts)
                        chunk = []
            if chunk:
                n_rows += _write_chunk(out, tgt_file, chunk, vocabs, max_contexts)
        finally:
            if tgt_file:
                tgt_file.close()
        out.seek(0)
        out.write(_HEADER.pack(_MAGIC, _VERSION, n_rows, max_contexts))
    os.replace(tmp_path, out_path)
    return _write_pack_meta(out_path, c2v_path, n_rows, max_contexts, vocabs)


def _write_pack_meta(out_path: str, c2v_path: str, n_rows: int,
                     max_contexts: int, vocabs: Code2VecVocabs) -> str:
    meta = {"rows": n_rows, "max_contexts": max_contexts,
            "vocab_fingerprint": vocabs_fingerprint(vocabs),
            "source": os.path.basename(c2v_path)}
    with open(out_path + ".meta.json", "w") as f:
        json.dump(meta, f)
    return out_path


def _write_chunk(out, tgt_file, chunk, vocabs, max_contexts) -> int:
    batch = reader_mod.parse_context_lines(
        chunk, vocabs, max_contexts, EstimatorAction.Evaluate)
    # Each row is written interleaved as [target, src, path, tgt] so the
    # file stays appendable in a single streaming pass.
    n, m = batch.source_token_indices.shape
    rec = np.empty((n, 1 + 3 * m), dtype=np.int32)
    rec[:, 0] = batch.target_index
    rec[:, 1:1 + m] = batch.source_token_indices
    rec[:, 1 + m:1 + 2 * m] = batch.path_indices
    rec[:, 1 + 2 * m:] = batch.target_token_indices
    out.write(rec.tobytes())
    if tgt_file and batch.target_strings:
        tgt_file.write("\n".join(batch.target_strings) + "\n")
    return n


# ----------------------------------------------- fused raw -> .c2vb compile
#
# The offline compiler's hot half: multiprocessing workers read raw
# extractor output by line-aligned byte ranges, apply the reference's
# two-tier in-vocab sampling (reference: preprocess.py:41-56), look up
# vocab ids, and write int32 rows into per-shard segment files that the
# parent stitches (header + concatenation) into one `.c2vb` + `.targets`
# sidecar — no padded `.c2v` text intermediate. Output is byte-identical
# at any worker count: each method's sampling RNG is seeded from
# (global seed, global line ordinal), and segments concatenate in file
# order. The same machinery packs existing `.c2v` text in parallel
# (sampling disabled — `pack_c2v(num_workers=...)`).

_PACK_CTX: Optional[dict] = None
_PACK_NATIVE = "unset"


def _method_rng(seed: int, ordinal: int) -> random.Random:
    """Per-method sampling RNG from a stable hash of (seed, ordinal) —
    identical in every worker layout, which is what makes the parallel
    compile byte-identical to the serial one."""
    digest = hashlib.blake2b(struct.pack("<qq", seed, ordinal),
                             digest_size=16).digest()
    return random.Random(int.from_bytes(digest, "little"))


def _init_pack_worker(ctx: dict) -> None:
    global _PACK_CTX, _PACK_NATIVE
    _PACK_CTX = ctx
    _PACK_NATIVE = "unset"


def _pack_worker_native_tables():
    """Per-worker native split+lookup tables when libc2vdata.so is built
    (the GIL-releasing core from data/native.py), else None. Built once
    per worker process from the ctx's bytes->id dicts."""
    global _PACK_NATIVE
    if _PACK_NATIVE == "unset":
        from code2vec_tpu.data import native
        ctx = _PACK_CTX
        if native.load_library() is None:
            _PACK_NATIVE = None
        else:
            _PACK_NATIVE = native.NativeTables.from_tables(
                ctx["token_b2i"], ctx["path_b2i"], ctx["target_b2i"],
                token_pad=ctx["token_pad"], token_oov=ctx["token_oov"],
                path_pad=ctx["path_pad"], path_oov=ctx["path_oov"],
                target_oov=ctx["target_oov"])
    return _PACK_NATIVE


def _pack_shard(task) -> dict:
    """Compile one byte range of the raw file into segment files.

    Per-line work is memoized per DISTINCT context string (corpora
    repeat contexts heavily): one dict hit replaces split + three vocab
    lookups for every repeat occurrence. The memo is cleared past
    `_MEMO_CAP` entries so worker RSS stays bounded on any corpus.
    """
    shard_idx, start, end, ordinal = task
    ctx = _PACK_CTX
    m: int = ctx["max_contexts"]
    seed: int = ctx["seed"]
    token_b2i: Dict[bytes, int] = ctx["token_b2i"]
    path_b2i: Dict[bytes, int] = ctx["path_b2i"]
    target_b2i: Dict[bytes, int] = ctx["target_b2i"]
    token_pad, token_oov = ctx["token_pad"], ctx["token_oov"]
    path_pad, path_oov = ctx["path_pad"], ctx["path_oov"]
    target_oov = ctx["target_oov"]
    word_ok, path_ok = ctx["word_ok"], ctx["path_ok"]
    sampling = word_ok is not None
    tables = _pack_worker_native_tables()
    native_rows = tables is not None and hasattr(tables._lib,
                                                 "c2v_parse_rows")
    memo: Dict[bytes, tuple] = {}
    memo_cap = preprocess_mod._MEMO_CAP
    # Emission memo: one packed int64 per distinct context
    # (sid | pid<<21 | tid<<42), so a whole chunk's id resolution is a
    # C-speed `map` + `np.fromiter` instead of a per-context Python
    # loop. Packing needs every token/path id under 2^21 (the java14m
    # reference vocabs are 1.3M/911K); larger vocabs take the tuple
    # fallback below.
    memo_pack: Dict[bytes, int] = {}
    pack_ok = (max(token_b2i.values(), default=0) < (1 << 21)
               and max(path_b2i.values(), default=0) < (1 << 21))

    def lookup(c: bytes) -> tuple:
        """(src_id, path_id, tgt_id, tier) for one context string; tier
        is 2 fully-in-vocab / 1 partially / 0 (reference tier test,
        preprocess.py:77-84). Missing pieces behave like the reader's
        sparse fill (reader.py parse_context_lines): empty -> PAD."""
        pieces = c.split(b",")
        a = pieces[0]
        b = pieces[1] if len(pieces) > 1 else b""
        d = pieces[2] if len(pieces) > 2 else b""
        sid = token_b2i.get(a, token_pad if a == b"" else token_oov)
        pid = path_b2i.get(b, path_pad if b == b"" else path_oov)
        tid = token_b2i.get(d, token_pad if d == b"" else token_oov)
        if not sampling:
            tier = 0
        elif a in word_ok and b in path_ok and d in word_ok:
            tier = 2
        elif a in word_ok or b in path_ok or d in word_ok:
            tier = 1
        else:
            tier = 0
        if len(memo) >= memo_cap:
            memo.clear()
        memo[c] = entry = (sid, pid, tid, tier)
        return entry

    def lookup_pack(c: bytes) -> int:
        pieces = c.split(b",")
        a = pieces[0]
        b = pieces[1] if len(pieces) > 1 else b""
        d = pieces[2] if len(pieces) > 2 else b""
        v = (token_b2i.get(a, token_pad if a == b"" else token_oov)
             | path_b2i.get(b, path_pad if b == b"" else path_oov) << 21
             | token_b2i.get(d, token_pad if d == b"" else token_oov) << 42)
        if len(memo_pack) >= memo_cap:
            memo_pack.clear()
        memo_pack[c] = v
        return v

    seg_path = os.path.join(ctx["seg_dir"], f"seg{shard_idx:05d}")
    seg = open(seg_path + ".bin", "wb", buffering=4 * 1024 * 1024)
    tgt_seg = (open(seg_path + ".targets", "wb", buffering=1024 * 1024)
               if ctx["write_targets"] else None)
    c2v_seg = (open(seg_path + ".c2v", "wb", buffering=4 * 1024 * 1024)
               if ctx["emit_c2v"] else None)

    rows = contexts_seen = contexts_kept = widest = skipped = 0
    # chunk accumulators, flushed every `flush_rows` methods: one name,
    # one context count and a flat context stream per kept row (the flat
    # list is extended at C level in the line loop — per-context Python
    # work happens only in `flush`, vectorized)
    flush_rows = 8192
    names: List[bytes] = []
    ks: List[int] = []
    all_ctxs: List[bytes] = []
    need_row_slices = tables is not None or c2v_seg is not None

    def row_slices() -> List[List[bytes]]:
        pos = 0
        out = []
        for k in ks:
            out.append(all_ctxs[pos:pos + k])
            pos += k
        return out

    def flush() -> None:
        nonlocal rows
        n = len(names)
        if not n:
            return
        per_row = row_slices() if need_row_slices else None
        if tables is not None:
            blob = b"\n".join(b" ".join([name] + ctxs)
                              for name, ctxs in zip(names, per_row)) + b"\n"
            if native_rows:
                rec = tables.parse_rows_blob(blob, n, m)
            else:
                src, pth, tgt, label, _mask = tables.parse_blob(blob, n, m)
                rec = np.empty((n, 1 + 3 * m), dtype=np.int32)
                rec[:, 0] = label
                rec[:, 1:1 + m] = src
                rec[:, 1 + m:1 + 2 * m] = pth
                rec[:, 1 + 2 * m:] = tgt
        else:
            labels = np.fromiter(
                (target_b2i.get(nm, target_oov) for nm in names),
                dtype=np.int32, count=n)
            ks_arr = np.asarray(ks, dtype=np.int64)
            mask = np.arange(m) < ks_arr[:, None]
            rec = np.empty((n, 1 + 3 * m), dtype=np.int32)
            rec[:, 0] = labels
            if pack_ok:
                # one C-speed map over the occurrence stream; misses
                # (first sight of a distinct context) patched inline
                mget = memo_pack.get
                vals_list = list(map(mget, all_ctxs))
                if None in vals_list:
                    for i, v in enumerate(vals_list):
                        if v is None:
                            c = all_ctxs[i]
                            v = mget(c)  # repeats resolve on first sight
                            vals_list[i] = (v if v is not None
                                            else lookup_pack(c))
                vals = np.array(vals_list, dtype=np.int64)
                m21 = (1 << 21) - 1
                streams = ((1, vals & m21, token_pad),
                           (1 + m, (vals >> 21) & m21, path_pad),
                           (1 + 2 * m, vals >> 42, token_pad))
            else:
                # tuple fallback for vocabs too large for 21-bit packing
                flat_s: List[int] = []
                flat_p: List[int] = []
                flat_t: List[int] = []
                for c in all_ctxs:
                    entry = memo.get(c)
                    if entry is None:
                        entry = lookup(c)
                    flat_s.append(entry[0])
                    flat_p.append(entry[1])
                    flat_t.append(entry[2])
                streams = ((1, np.asarray(flat_s, np.int32), token_pad),
                           (1 + m, np.asarray(flat_p, np.int32), path_pad),
                           (1 + 2 * m, np.asarray(flat_t, np.int32),
                            token_pad))
            # boolean assignment fills in C (row-major) order == the
            # order `all_ctxs` was appended in
            for off, ids, pad in streams:
                block = rec[:, off:off + m]
                block.fill(pad)
                block[mask] = ids
        seg.write(rec)
        if tgt_seg is not None:
            tgt_seg.write(b"\n".join(names) + b"\n")
        if c2v_seg is not None:
            c2v_seg.write(b"".join(
                b" ".join([name] + ctxs) + b" " * (m - len(ctxs)) + b"\n"
                for name, ctxs in zip(names, per_row)))
        rows += n
        names.clear()
        ks.clear()
        all_ctxs.clear()

    def sample_line(parts: List[bytes], ordinal: int) -> List[bytes]:
        """Reference two-tier sampling for one over-budget method
        (preprocess.py:41-56): keep fully-in-vocab contexts first, then
        partially-in-vocab, sampling at random within the tier that
        crosses the budget."""
        in_vocab: List[bytes] = []
        mixed: List[bytes] = []
        for c in parts[1:]:
            entry = memo.get(c)
            if entry is None:
                entry = lookup(c)
            if entry[3] == 2:
                in_vocab.append(c)
            elif entry[3] == 1:
                mixed.append(c)
        if len(in_vocab) > m:
            return _method_rng(seed, ordinal).sample(in_vocab, m)
        if len(in_vocab) + len(mixed) > m:
            return in_vocab + _method_rng(seed, ordinal).sample(
                mixed, m - len(in_vocab))
        return in_vocab + mixed

    def run_native_lines() -> None:
        """Hot loop when the native core is built and no `.c2v` text is
        being emitted: under-budget lines go to the GIL-releasing C
        parser UNSPLIT (one `count` + one `find` of Python work per
        line); only the rare over-budget methods pay a Python split for
        the sampling tiers."""
        nonlocal rows, contexts_seen, contexts_kept, widest, skipped, ordinal
        pend_lines: List[bytes] = []

        def flush_lines() -> None:
            nonlocal rows
            n = len(pend_lines)
            if not n:
                return
            blob = b"\n".join(pend_lines) + b"\n"
            if native_rows:
                rec = tables.parse_rows_blob(blob, n, m)
            else:
                src, pth, tgt, label, _mask = tables.parse_blob(blob, n, m)
                rec = np.empty((n, 1 + 3 * m), dtype=np.int32)
                rec[:, 0] = label
                rec[:, 1:1 + m] = src
                rec[:, 1 + m:1 + 2 * m] = pth
                rec[:, 1 + 2 * m:] = tgt
            seg.write(rec)
            if tgt_seg is not None:
                tgt_seg.write(b"\n".join(names) + b"\n")
                names.clear()
            rows += n
            pend_lines.clear()

        for lines in preprocess_mod.iter_range_line_chunks(
                ctx["raw_path"], start, end):
            for line in lines:
                k = line.count(b" ")
                contexts_seen += k
                if k > widest:
                    widest = k
                if sampling:
                    if k > m:
                        parts = line.split(b" ")
                        contexts = sample_line(parts, ordinal)
                        k = len(contexts)
                        if not contexts:
                            skipped += 1
                            ordinal += 1
                            continue
                        line = b" ".join([parts[0]] + contexts)
                    elif k == 0:
                        skipped += 1
                        ordinal += 1
                        continue
                contexts_kept += k if k < m else m
                if tgt_seg is not None:
                    sp = line.find(b" ")
                    names.append(line if sp < 0 else line[:sp])
                pend_lines.append(line)
                ordinal += 1
                if len(pend_lines) >= flush_rows:
                    flush_lines()
        flush_lines()

    def run_general_lines() -> None:
        nonlocal all_ctxs, contexts_seen, contexts_kept, widest, skipped, \
            ordinal
        for lines in preprocess_mod.iter_range_line_chunks(
                ctx["raw_path"], start, end):
            for line in lines:
                parts = line.split(b" ")
                name, contexts = parts[0], parts[1:]
                k = len(contexts)
                contexts_seen += k
                if k > widest:
                    widest = k
                if sampling:
                    if k > m:
                        contexts = sample_line(parts, ordinal)
                        k = len(contexts)
                    if not contexts:
                        skipped += 1
                        ordinal += 1
                        continue
                elif k > m:
                    contexts = contexts[:m]
                    k = m
                contexts_kept += k
                names.append(name)
                ks.append(k)
                all_ctxs += contexts
                ordinal += 1
                if len(names) >= flush_rows:
                    flush()
        flush()

    try:
        if tables is not None and c2v_seg is None:
            run_native_lines()
        else:
            run_general_lines()
    finally:
        seg.close()
        if tgt_seg is not None:
            tgt_seg.close()
        if c2v_seg is not None:
            c2v_seg.close()
    return {"shard": shard_idx, "rows": rows, "skipped": skipped,
            "contexts_seen": contexts_seen, "contexts_kept": contexts_kept,
            "widest": widest}


def _encode_keys(d) -> Dict[bytes, int]:
    return {w.encode("utf-8", "surrogateescape"): i for w, i in d.items()}


def _encoded_tables(vocabs: Code2VecVocabs) -> Dict[str, Dict[bytes, int]]:
    """bytes->id worker tables for `vocabs`, cached on the instance:
    compile_corpus packs three splits with the same vocabs, and
    re-encoding the 2.2M java14m words per split costs seconds."""
    cache = getattr(vocabs, "_b2i_cache", None)
    if cache is None:
        cache = {
            "token": _encode_keys(vocabs.token_vocab.word_to_index),
            "path": _encode_keys(vocabs.path_vocab.word_to_index),
            "target": _encode_keys(vocabs.target_vocab.word_to_index),
        }
        vocabs._b2i_cache = cache
    return cache


def _append_file(dst, src_path: str) -> None:
    """Append `src_path` to the open binary file `dst` (kernel-side
    `sendfile` when available), then delete it to free disk."""
    dst.flush()
    with open(src_path, "rb") as src:
        size = os.fstat(src.fileno()).st_size
        offset = 0
        try:
            while offset < size:
                sent = os.sendfile(dst.fileno(), src.fileno(), offset,
                                   size - offset)
                if sent == 0:
                    break
                offset += sent
        except (AttributeError, OSError):
            src.seek(offset)
            shutil.copyfileobj(src, dst, 16 * 1024 * 1024)
    os.unlink(src_path)


def pack_raw(raw_path: str, out_path: str, vocabs: Code2VecVocabs,
             word_to_count: Optional[Dict[str, int]],
             path_to_count: Optional[Dict[str, int]], max_contexts: int,
             seed: int = 0, num_workers: int = 1,
             c2v_out: Optional[str] = None,
             write_targets_sidecar: bool = True, log=None) -> int:
    """Fused compile of RAW extractor output straight to `.c2vb` (+
    `.targets` sidecar, + optional compat `.c2v` text at `c2v_out`),
    applying the reference's in-vocab sampling when `word_to_count`/
    `path_to_count` are given (`None` disables sampling: contexts
    truncate at `max_contexts` and every line yields a row — the
    `.c2v`-repack compat mode). Returns the row count.

    Workers process line-aligned byte ranges into per-shard segment
    files; the parent stitches them in order, so the output is
    byte-identical at any `num_workers` (the per-method RNG makes the
    sampling itself worker-layout-invariant)."""
    workers = max(1, num_workers)
    sampling = word_to_count is not None
    ranges = preprocess_mod.line_aligned_ranges(raw_path, workers)
    out_dir = os.path.dirname(os.path.abspath(out_path)) or "."
    seg_dir = tempfile.mkdtemp(prefix="c2v_pack_", dir=out_dir)
    ctx = {
        "raw_path": raw_path,
        "seg_dir": seg_dir,
        "max_contexts": max_contexts,
        "seed": seed,
        "token_b2i": _encoded_tables(vocabs)["token"],
        "path_b2i": _encoded_tables(vocabs)["path"],
        "target_b2i": _encoded_tables(vocabs)["target"],
        "token_pad": vocabs.token_vocab.pad_index,
        "token_oov": vocabs.token_vocab.oov_index,
        "path_pad": vocabs.path_vocab.pad_index,
        "path_oov": vocabs.path_vocab.oov_index,
        "target_oov": vocabs.target_vocab.oov_index,
        "word_ok": (frozenset(_encode_keys(word_to_count)) if sampling
                    else None),
        "path_ok": (frozenset(_encode_keys(path_to_count)) if sampling
                    else None),
        "emit_c2v": c2v_out is not None,
        "write_targets": write_targets_sidecar,
    }
    # The final files are stitched INCREMENTALLY, in shard order, as
    # workers finish (imap preserves task order): most of the
    # concatenation I/O overlaps the remaining shards' compute instead
    # of serializing after the pool drains. Row count is patched into
    # the header at the end (it is unknown up front in sampling mode).
    seg = lambda i, suffix: os.path.join(seg_dir, f"seg{i:05d}{suffix}")  # noqa: E731
    outs = [(out_path, ".bin")]
    if write_targets_sidecar:
        outs.append((out_path + ".targets", ".targets"))
    if c2v_out is not None:
        outs.append((c2v_out, ".c2v"))
    handles = {}
    results = []

    def consume(result: dict) -> None:
        results.append(result)
        for final, suffix in outs:
            _append_file(handles[suffix], seg(result["shard"], suffix))

    global _PACK_CTX, _PACK_NATIVE
    try:
        for final, suffix in outs:
            handles[suffix] = open(final + ".tmp", "wb")
        handles[".bin"].write(_HEADER.pack(_MAGIC, _VERSION, 0, max_contexts))
        if len(ranges) == 1:
            _init_pack_worker(ctx)
            consume(_pack_shard((0, ranges[0][0], ranges[0][1], 0)))
        else:
            with preprocess_mod._worker_pool(
                    len(ranges), initializer=_init_pack_worker,
                    initargs=(ctx,)) as pool:
                ordinals = preprocess_mod.range_start_ordinals(
                    raw_path, ranges, pool=pool)
                tasks = [(i, s, e, o) for i, ((s, e), o)
                         in enumerate(zip(ranges, ordinals))]
                for result in pool.imap(_pack_shard, tasks):
                    consume(result)
        n_rows = sum(r["rows"] for r in results)
        handles[".bin"].seek(0)
        handles[".bin"].write(_HEADER.pack(_MAGIC, _VERSION, n_rows,
                                           max_contexts))
        for handle in handles.values():
            handle.close()
        for final, suffix in outs:
            os.replace(final + ".tmp", final)
    finally:
        _PACK_CTX, _PACK_NATIVE = None, "unset"
        for handle in handles.values():
            if not handle.closed:
                handle.close()
        for final, suffix in outs:
            if os.path.exists(final + ".tmp"):
                os.unlink(final + ".tmp")
        shutil.rmtree(seg_dir, ignore_errors=True)

    _write_pack_meta(out_path, raw_path, n_rows, max_contexts, vocabs)
    if log is not None and sampling:
        skipped = sum(r["skipped"] for r in results)
        seen = sum(r["contexts_seen"] for r in results)
        kept = sum(r["contexts_kept"] for r in results)
        widest = max(r["widest"] for r in results)
        denom = max(n_rows, 1)
        log(f"{out_path}: {n_rows} examples written, {skipped} skipped "
            f"(no contexts)")
        log(f"  contexts/method: {seen / denom:.1f} raw -> "
            f"{kept / denom:.1f} after sampling (widest method: {widest})")
    return n_rows


def _epoch_rng(seed: int, epoch: int) -> np.random.Generator:
    """Permutation RNG for one absolute epoch index: a pure function of
    (seed, epoch), identical on every host and across resume boundaries.
    This keying is what makes the training order ELASTIC — a run resumed
    at epoch e (on any host count) draws exactly the permutation the
    uninterrupted run would have used for epoch e, instead of restarting
    a stateful RNG chain from the seed."""
    return np.random.default_rng(np.random.SeedSequence(
        [int(seed) & 0x7FFFFFFFFFFFFFFF, int(epoch) & 0x7FFFFFFFFFFFFFFF]))


class PackedDataset:
    """Zero-copy view over a `.c2vb` file with batched iteration.

    Training iteration uses a full random permutation per epoch (strictly
    better shuffling than the reference's 10K-element buffer,
    path_context_reader.py:139) and yields fixed-size batches.

    The TRAINING order is host-count invariant: the row filter and the
    per-epoch permutation are computed over the GLOBAL row set (identical
    on every host), and host h of M takes the strided slice
    `perm[h::M]`, truncated so every host yields the same batch count.
    Global batch b therefore always consumes rows
    `perm[b*Bg:(b+1)*Bg]` (Bg = batch_size * num_shards) as a SET,
    whatever M is — which is what lets a checkpoint's data cursor
    (global row ordinal) be remapped exactly onto a different host
    count: no row skipped, none double-read. Evaluation keeps the plain
    per-host strided file order (metrics are global sums; order and
    grouping don't matter there).
    """

    @staticmethod
    def read_header(path: str):
        """(rows, max_contexts) from a `.c2vb` header without opening
        the memmap — lets the facade size a fused-compiled dataset that
        has no `.c2v` text to count lines in."""
        with open(path, "rb") as f:
            magic, _version, n, m = _HEADER.unpack(f.read(_HEADER.size))
        if magic != _MAGIC:
            raise ValueError(f"{path} is not a .c2vb file")
        return n, m

    def __init__(self, path: str, vocabs: Code2VecVocabs,
                 shard_index: int = 0, num_shards: int = 1):
        self.path = path
        self.vocabs = vocabs
        with open(path, "rb") as f:
            magic, version, n, m = _HEADER.unpack(f.read(_HEADER.size))
        if magic != _MAGIC:
            raise ValueError(f"{path} is not a .c2vb file")
        if version != _VERSION:
            raise ValueError(f"{path}: unsupported .c2vb version {version}")
        self.num_rows_total = n
        self.max_contexts = m
        self._rec = np.memmap(path, dtype=np.int32, mode="r",
                              offset=_HEADER.size,
                              shape=(n, 1 + 3 * m))
        meta_path = path + ".meta.json"
        if os.path.exists(meta_path):
            with open(meta_path) as f:
                meta = json.load(f)
            fp = vocabs_fingerprint(vocabs)
            if meta.get("vocab_fingerprint") not in (None, fp):
                raise ValueError(
                    f"{path} was packed with different vocabularies "
                    f"(fingerprint {meta.get('vocab_fingerprint')} != {fp}); re-pack it.")
        # Host shard: disjoint strided row subset (evaluation order;
        # training strides the per-epoch GLOBAL permutation instead).
        self.shard_index = shard_index
        self.num_shards = num_shards
        self.row_ids = np.arange(shard_index, n, num_shards)
        self._target_strings: Optional[List[str]] = None
        self._filtered_cache: dict = {}

    def __len__(self) -> int:
        return len(self.row_ids)

    @property
    def target_strings(self) -> Optional[List[str]]:
        sidecar = self.path + ".targets"
        if self._target_strings is None and os.path.exists(sidecar):
            with open(sidecar, "r") as f:
                strings = f.read().splitlines()
            # cross-check: a stale/partial sidecar (e.g. interrupted
            # re-pack) must not silently mislabel evaluation rows
            if len(strings) != self.num_rows_total:
                raise ValueError(
                    f"{sidecar} has {len(strings)} rows but {self.path} has "
                    f"{self.num_rows_total}; re-pack the dataset.")
            self._target_strings = strings
        return self._target_strings

    def gather(self, rows: np.ndarray,
               with_target_strings: bool = False) -> RowBatch:
        m = self.max_contexts
        rec = np.asarray(self._rec[rows])  # copy out of the memmap
        src = rec[:, 1:1 + m]
        pth = rec[:, 1 + m:1 + 2 * m]
        tgt = rec[:, 1 + 2 * m:]
        token_pad = self.vocabs.token_vocab.pad_index
        path_pad = self.vocabs.path_vocab.pad_index
        mask = ((src != token_pad) | (tgt != token_pad) | (pth != path_pad))
        strings = None
        if with_target_strings and self.target_strings is not None:
            strings = [self.target_strings[r] for r in rows]
        return RowBatch(
            source_token_indices=src,
            path_indices=pth,
            target_token_indices=tgt,
            context_valid_mask=mask.astype(np.float32),
            target_index=rec[:, 0],
            example_valid=np.ones((len(rows),), dtype=bool),
            target_strings=strings,
        )

    def _filter_rows(self, rows: np.ndarray,
                     estimator_action: EstimatorAction) -> np.ndarray:
        m = self.max_contexts
        token_pad = self.vocabs.token_vocab.pad_index
        path_pad = self.vocabs.path_vocab.pad_index
        keep_chunks = []
        for start in range(0, len(rows), 1 << 18):
            chunk = rows[start:start + (1 << 18)]
            rec = self._rec[chunk]
            src = rec[:, 1:1 + m]
            pth = rec[:, 1 + m:1 + 2 * m]
            tgt = rec[:, 1 + 2 * m:]
            any_valid = ((src != token_pad) | (tgt != token_pad)
                         | (pth != path_pad)).any(axis=1)
            if estimator_action.is_train:
                any_valid &= rec[:, 0] > self.vocabs.target_vocab.oov_index
            keep_chunks.append(chunk[any_valid])
        return (np.concatenate(keep_chunks) if keep_chunks
                else np.empty((0,), np.int64))

    def _filtered_row_ids(self, estimator_action: EstimatorAction) -> np.ndarray:
        """Apply the reference row filter once over this host's strided
        shard, vectorized over the memmap. Cached per action: the result
        is immutable for a given file, and both `steps_per_epoch` and
        `iter_batches` need it (mid-epoch eval calls both every firing —
        one O(rows) scan, not two)."""
        cached = self._filtered_cache.get(estimator_action)
        if cached is None:
            cached = self._filter_rows(self.row_ids, estimator_action)
            self._filtered_cache[estimator_action] = cached
        return cached

    def _global_filtered_row_ids(
            self, estimator_action: EstimatorAction) -> np.ndarray:
        """The row filter over ALL rows — identical on every host, the
        basis of the host-count-invariant training order. One shard is
        the global set already; multi-host pays a full-file scan once
        (cached), the price of an order every topology can agree on."""
        if self.num_shards == 1:
            return self._filtered_row_ids(estimator_action)
        key = ("global", estimator_action)
        cached = self._filtered_cache.get(key)
        if cached is None:
            cached = self._filter_rows(
                np.arange(self.num_rows_total, dtype=np.int64),
                estimator_action)
            self._filtered_cache[key] = cached
        return cached

    def steps_per_epoch(self, batch_size: int,
                        estimator_action: EstimatorAction,
                        skip_rows: int = 0) -> int:
        """Exact number of batches one data pass yields (post-filter) —
        unlike the reference's raw-line `train_steps_per_epoch`
        (config.py:165-167), this counts the rows the trainer will
        actually consume. Training counts are identical on EVERY host by
        construction (global row set // global batch). `skip_rows`
        (training only) is a resume cursor: the count of the epoch's
        remaining batches after the already-consumed global rows."""
        if estimator_action.is_train:
            n = len(self._global_filtered_row_ids(estimator_action))
            steps = n // (batch_size * self.num_shards)
            if skip_rows:
                skip_local = min(skip_rows // self.num_shards,
                                 steps * batch_size)
                return (steps * batch_size - skip_local) // batch_size
            return steps
        n = len(self._filtered_row_ids(estimator_action))
        return -(-n // batch_size)  # eval pads the tail batch

    def iter_batches(self, batch_size: int, estimator_action: EstimatorAction,
                     num_epochs: int = 1, seed: int = 0,
                     repeat_endlessly: bool = False,
                     with_target_strings: bool = False,
                     yield_epoch_markers: bool = False,
                     start_epoch: int = 0,
                     skip_rows: int = 0) -> Iterator[RowBatch]:
        """Batched iteration. Training epochs shuffle with the
        epoch-keyed permutation (absolute epoch index `start_epoch + k`)
        over the GLOBAL filtered row set, strided per host — see the
        class docstring. `start_epoch` makes a resumed run continue the
        exact permutation sequence of an uninterrupted one; `skip_rows`
        drops the first epoch's already-consumed global rows (this
        host's share: skip_rows // num_shards), the data-cursor remap
        for elastic resume. EpochEnd markers stay 1-based RELATIVE
        counts (the trainer adds its initial epoch)."""
        if estimator_action.is_train:
            rows = self._global_filtered_row_ids(estimator_action)
            steps = len(rows) // (batch_size * self.num_shards)
            epoch = 0
            while repeat_endlessly or epoch < num_epochs:
                perm = _epoch_rng(seed, start_epoch + epoch).permutation(rows)
                # Truncate BEFORE striding: every host sees the same
                # steps*batch_size sequence length, so batch counts are
                # lockstep by construction and the global batch set is
                # exactly perm[:steps*Bg].
                seq = perm[self.shard_index::self.num_shards][
                    :steps * batch_size]
                if epoch == 0 and skip_rows:
                    seq = seq[skip_rows // self.num_shards:]
                n_full = (len(seq) // batch_size) * batch_size
                for start in range(0, n_full, batch_size):
                    yield self.gather(seq[start:start + batch_size],
                                      with_target_strings)
                epoch += 1
                if yield_epoch_markers:
                    yield EpochEnd(epoch)
            return
        rows = self._filtered_row_ids(estimator_action)
        epoch = 0
        while repeat_endlessly or epoch < num_epochs:
            n_full = (len(rows) // batch_size) * batch_size
            for start in range(0, n_full, batch_size):
                yield self.gather(rows[start:start + batch_size],
                                  with_target_strings)
            tail = len(rows) - n_full
            if tail:
                batch = self.gather(rows[n_full:], with_target_strings)
                yield reader_mod._pad_rows(batch, batch_size)
            epoch += 1
            if yield_epoch_markers:
                yield EpochEnd(epoch)


# -------------------------------------------------- sharded corpus manifest
#
# A corpus manifest is a small JSON file listing N `.c2vb` shards (the
# incumbent pack plus any continuous-training delta shards) that
# ShardedCorpus presents as ONE logical row space. Shard paths are
# stored relative to the manifest's directory so the whole corpus
# directory can be moved/rsynced as a unit. The manifest pins one vocab
# fingerprint: every shard must have been packed with the same
# vocabularies, or the global row ids would mean different things in
# different shards.

MANIFEST_VERSION = 1
MANIFEST_SUFFIX = ".manifest.json"


def _shard_meta_fingerprint(shard_path: str) -> Optional[str]:
    meta_path = shard_path + ".meta.json"
    if not os.path.exists(meta_path):
        return None
    with open(meta_path) as f:
        return json.load(f).get("vocab_fingerprint")


def _manifest_shard_path(manifest_path: str, entry: dict) -> str:
    p = entry["path"]
    if os.path.isabs(p):
        return p
    return os.path.join(os.path.dirname(os.path.abspath(manifest_path)), p)


def load_manifest(manifest_path: str) -> dict:
    with open(manifest_path) as f:
        manifest = json.load(f)
    if not isinstance(manifest, dict) or "shards" not in manifest:
        raise ValueError(f"{manifest_path}: not a corpus manifest "
                         f"(missing 'shards')")
    version = manifest.get("version")
    if version != MANIFEST_VERSION:
        raise ValueError(f"{manifest_path}: unsupported corpus manifest "
                         f"version {version}")
    if not manifest["shards"]:
        raise ValueError(f"{manifest_path}: corpus manifest lists no shards")
    return manifest


def save_manifest(manifest_path: str, manifest: dict) -> None:
    tmp = manifest_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(manifest, f, indent=1)
        f.write("\n")
    os.replace(tmp, manifest_path)


def _manifest_entry(manifest_path: str, shard_path: str) -> dict:
    """One manifest entry for a shard: relative path when the shard
    lives under the manifest's directory, plus the header row count and
    the shard meta's vocab fingerprint (None when the shard has no
    sidecar meta)."""
    rows, max_contexts = PackedDataset.read_header(shard_path)
    base = os.path.dirname(os.path.abspath(manifest_path))
    abs_shard = os.path.abspath(shard_path)
    rel = os.path.relpath(abs_shard, base)
    path = rel if not rel.startswith("..") else abs_shard
    return {"path": path, "rows": rows, "max_contexts": max_contexts,
            "vocab_fingerprint": _shard_meta_fingerprint(shard_path)}


def _check_entry_vocab(manifest_path: str, manifest: dict,
                       entry: dict) -> None:
    """Refuse mixing shards packed with different vocabularies: the
    manifest fingerprint is pinned by the first fingerprinted shard and
    every later shard must match it."""
    fp = entry.get("vocab_fingerprint")
    pinned = manifest.get("vocab_fingerprint")
    if fp and pinned and fp != pinned:
        raise ValueError(
            f"{manifest_path}: refusing mixed-vocab manifest — shard "
            f"{entry['path']} was packed with vocab fingerprint {fp} but "
            f"the manifest pins {pinned}; re-pack the shard with the "
            f"manifest's vocabularies (or build a new manifest).")
    if fp and not pinned:
        manifest["vocab_fingerprint"] = fp
    if entry["max_contexts"] != manifest["max_contexts"]:
        raise ValueError(
            f"{manifest_path}: shard {entry['path']} has max_contexts="
            f"{entry['max_contexts']} but the manifest pins "
            f"{manifest['max_contexts']}; re-pack the shard.")


def create_manifest(manifest_path: str, shard_paths: List[str]) -> dict:
    """Build a corpus manifest over existing `.c2vb` shards (in the
    given order — global row ids follow shard order, so order is part
    of the corpus identity)."""
    if not shard_paths:
        raise ValueError("a corpus manifest needs at least one shard")
    first = _manifest_entry(manifest_path, shard_paths[0])
    manifest = {"version": MANIFEST_VERSION,
                "max_contexts": first["max_contexts"],
                "vocab_fingerprint": first["vocab_fingerprint"],
                "shards": [first]}
    for shard in shard_paths[1:]:
        entry = _manifest_entry(manifest_path, shard)
        _check_entry_vocab(manifest_path, manifest, entry)
        manifest["shards"].append(entry)
    save_manifest(manifest_path, manifest)
    return manifest


def append_manifest_shard(manifest_path: str, shard_path: str) -> dict:
    """Append one delta shard to an existing manifest (the continuous-
    training accumulation step: the corpus grows, nothing re-packs).
    Pure append — existing entries are never rewritten, so global row
    ids of already-listed rows are stable. Refuses duplicates and
    vocab-fingerprint mismatches."""
    manifest = load_manifest(manifest_path)
    entry = _manifest_entry(manifest_path, shard_path)
    abs_new = _manifest_shard_path(manifest_path, entry)
    for existing in manifest["shards"]:
        if os.path.abspath(_manifest_shard_path(
                manifest_path, existing)) == os.path.abspath(abs_new):
            raise ValueError(f"{manifest_path}: shard {entry['path']} is "
                             f"already listed")
    _check_entry_vocab(manifest_path, manifest, entry)
    manifest["shards"].append(entry)
    save_manifest(manifest_path, manifest)
    return manifest


def validate_manifest(manifest_path: str,
                      vocabs: Optional[Code2VecVocabs] = None) -> List[dict]:
    """Re-check every shard against the manifest: file present, header
    readable, row count unchanged, max_contexts and vocab fingerprint
    consistent (and matching `vocabs` when given). Returns one report
    dict per shard; raises on the first inconsistency."""
    manifest = load_manifest(manifest_path)
    want_fp = (vocabs_fingerprint(vocabs) if vocabs is not None
               else manifest.get("vocab_fingerprint"))
    reports = []
    for entry in manifest["shards"]:
        shard = _manifest_shard_path(manifest_path, entry)
        rows, max_contexts = PackedDataset.read_header(shard)
        if rows != entry["rows"]:
            raise ValueError(
                f"{manifest_path}: shard {entry['path']} has {rows} rows "
                f"but the manifest recorded {entry['rows']}; the shard "
                f"changed after it was listed — rebuild the manifest.")
        _check_entry_vocab(manifest_path, manifest, dict(entry))
        fp = _shard_meta_fingerprint(shard)
        if fp and want_fp and fp != want_fp:
            raise ValueError(
                f"{shard} was packed with different vocabularies "
                f"(fingerprint {fp} != {want_fp}); re-pack it.")
        reports.append({"path": entry["path"], "rows": rows,
                        "max_contexts": max_contexts,
                        "vocab_fingerprint": fp})
    return reports


class ShardedCorpus:
    """PackedDataset-shaped view over a MANIFEST of `.c2vb` shards.

    One logical row space: global row id r lives in the shard whose
    cumulative-row interval contains r, at local offset
    r - offsets[shard]. Because the global id space is exactly the
    shard-order concatenation, the epoch-keyed training order is a pure
    function of (seed, epoch) over the global filtered row set —
    identical to a single-file PackedDataset holding the same rows, and
    identical across shard counts and host counts. The PR-6 cursor laws
    (resume-at-epoch-e == uninterrupted-at-epoch-e; batch-as-set
    invariance across host counts) therefore hold verbatim: nothing is
    materialized, hosts stride the same global permutation.

    Delta shards appended to the manifest while a corpus is OPEN are
    not seen: the shard list is snapshotted at construction, and
    `adopt_appended_shards` refuses to extend the row space mid-epoch
    (a permutation drawn over N rows cannot grow to N+k rows without
    changing which rows batch b holds). Call it between epochs — or,
    as the continuous-training pipeline does, reopen per fine-tune run.
    """

    def __init__(self, manifest_path: str, vocabs: Code2VecVocabs,
                 shard_index: int = 0, num_shards: int = 1):
        self.path = manifest_path
        self.vocabs = vocabs
        self.shard_index = shard_index
        self.num_shards = num_shards
        self._recs: List[np.memmap] = []
        self._shard_paths: List[str] = []
        self._offsets = np.zeros((1,), dtype=np.int64)
        self.max_contexts = 0
        self._target_strings: Optional[List[str]] = None
        self._filtered_cache: dict = {}
        self._mid_epoch = False
        manifest = load_manifest(manifest_path)
        self._open_shards(manifest, manifest["shards"])

    def _open_shards(self, manifest: dict, entries: List[dict]) -> None:
        """Open (additional) shard memmaps and extend the offset table.
        Validates each shard the way PackedDataset validates its one
        file: header magic/version, manifest row count, max_contexts
        agreement, vocab fingerprint against the live vocabs."""
        fp = vocabs_fingerprint(self.vocabs)
        pinned = manifest.get("vocab_fingerprint")
        if pinned and pinned != fp:
            raise ValueError(
                f"{self.path} was built for vocab fingerprint {pinned} but "
                f"the loaded vocabularies have {fp}; re-pack the corpus.")
        for entry in entries:
            shard = _manifest_shard_path(self.path, entry)
            with open(shard, "rb") as f:
                magic, version, n, m = _HEADER.unpack(f.read(_HEADER.size))
            if magic != _MAGIC:
                raise ValueError(f"{shard} is not a .c2vb file")
            if version != _VERSION:
                raise ValueError(f"{shard}: unsupported .c2vb version "
                                 f"{version}")
            if n != entry["rows"]:
                raise ValueError(
                    f"{self.path}: shard {entry['path']} has {n} rows but "
                    f"the manifest recorded {entry['rows']}; rebuild the "
                    f"manifest.")
            if not self._recs:
                self.max_contexts = m
            elif m != self.max_contexts:
                raise ValueError(
                    f"{self.path}: shard {entry['path']} has max_contexts="
                    f"{m}, corpus has {self.max_contexts}; re-pack it.")
            shard_fp = _shard_meta_fingerprint(shard)
            if shard_fp and shard_fp != fp:
                raise ValueError(
                    f"{shard} was packed with different vocabularies "
                    f"(fingerprint {shard_fp} != {fp}); re-pack it.")
            self._recs.append(np.memmap(shard, dtype=np.int32, mode="r",
                                        offset=_HEADER.size,
                                        shape=(n, 1 + 3 * m)))
            self._shard_paths.append(shard)
            self._offsets = np.append(self._offsets, self._offsets[-1] + n)
        self.num_rows_total = int(self._offsets[-1])
        self.row_ids = np.arange(self.shard_index, self.num_rows_total,
                                 self.num_shards)
        self._filtered_cache.clear()
        self._target_strings = None

    @staticmethod
    def read_manifest_rows(manifest_path: str) -> int:
        """Total row count recorded by a manifest, without opening any
        shard memmap (the facade's example-count fast path)."""
        return sum(entry["rows"]
                   for entry in load_manifest(manifest_path)["shards"])

    @property
    def num_shard_files(self) -> int:
        return len(self._recs)

    def __len__(self) -> int:
        return len(self.row_ids)

    def adopt_appended_shards(self) -> int:
        """Pick up shards appended to the manifest since open (or since
        the last adoption). Legal only BETWEEN epochs: mid-epoch the
        global permutation is already drawn over the current row set,
        so growing it would silently change the epoch's batches — the
        exact corruption the cursor laws forbid. Returns the number of
        shards adopted."""
        if self._mid_epoch:
            raise RuntimeError(
                f"{self.path}: delta-shard adoption refused mid-epoch; the "
                f"epoch's global permutation is already drawn — retry at "
                f"the next epoch boundary.")
        manifest = load_manifest(self.path)
        entries = manifest["shards"]
        if len(entries) < len(self._recs):
            raise ValueError(f"{self.path}: manifest shrank while open "
                             f"({len(entries)} shards < {len(self._recs)} "
                             f"adopted); rebuild the corpus.")
        for i, shard in enumerate(self._shard_paths):
            listed = _manifest_shard_path(self.path, entries[i])
            if os.path.abspath(listed) != os.path.abspath(shard):
                raise ValueError(
                    f"{self.path}: manifest rewrote shard {i} "
                    f"({entries[i]['path']}) while open; only pure appends "
                    f"can be adopted — rebuild the corpus.")
        new = entries[len(self._recs):]
        if new:
            self._open_shards(manifest, new)
        return len(new)

    @property
    def target_strings(self) -> Optional[List[str]]:
        """Concatenated per-shard `.targets` sidecars, in shard order —
        global indexing matches the row id space. All-or-nothing: a
        corpus where only some shards carry sidecars cannot label every
        row, so it reports None (same contract as a missing sidecar)."""
        if self._target_strings is None:
            strings: List[str] = []
            for shard, rec in zip(self._shard_paths, self._recs):
                sidecar = shard + ".targets"
                if not os.path.exists(sidecar):
                    return None
                with open(sidecar, "r") as f:
                    part = f.read().splitlines()
                if len(part) != rec.shape[0]:
                    raise ValueError(
                        f"{sidecar} has {len(part)} rows but {shard} has "
                        f"{rec.shape[0]}; re-pack the shard.")
                strings.extend(part)
            self._target_strings = strings
        return self._target_strings

    def _gather_rec(self, rows: np.ndarray) -> np.ndarray:
        """Copy the records for GLOBAL row ids `rows` out of the shard
        memmaps, preserving request order (the permutation order IS the
        training order)."""
        rows = np.asarray(rows, dtype=np.int64)
        rec = np.empty((len(rows), 1 + 3 * self.max_contexts),
                       dtype=np.int32)
        shard_of = np.searchsorted(self._offsets, rows, side="right") - 1
        local = rows - self._offsets[shard_of]
        for s in np.unique(shard_of):
            idx = np.nonzero(shard_of == s)[0]
            rec[idx] = self._recs[s][local[idx]]
        return rec

    def gather(self, rows: np.ndarray,
               with_target_strings: bool = False) -> RowBatch:
        m = self.max_contexts
        rec = self._gather_rec(rows)
        src = rec[:, 1:1 + m]
        pth = rec[:, 1 + m:1 + 2 * m]
        tgt = rec[:, 1 + 2 * m:]
        token_pad = self.vocabs.token_vocab.pad_index
        path_pad = self.vocabs.path_vocab.pad_index
        mask = ((src != token_pad) | (tgt != token_pad) | (pth != path_pad))
        strings = None
        if with_target_strings and self.target_strings is not None:
            strings = [self.target_strings[r] for r in rows]
        return RowBatch(
            source_token_indices=src,
            path_indices=pth,
            target_token_indices=tgt,
            context_valid_mask=mask.astype(np.float32),
            target_index=rec[:, 0],
            example_valid=np.ones((len(rows),), dtype=bool),
            target_strings=strings,
        )

    def _filter_rows(self, rows: np.ndarray,
                     estimator_action: EstimatorAction) -> np.ndarray:
        """The PackedDataset row filter over GLOBAL ids, chunked so one
        chunk's records are gathered across shards at most once."""
        m = self.max_contexts
        token_pad = self.vocabs.token_vocab.pad_index
        path_pad = self.vocabs.path_vocab.pad_index
        keep_chunks = []
        for start in range(0, len(rows), 1 << 18):
            chunk = np.asarray(rows[start:start + (1 << 18)], dtype=np.int64)
            rec = self._gather_rec(chunk)
            src = rec[:, 1:1 + m]
            pth = rec[:, 1 + m:1 + 2 * m]
            tgt = rec[:, 1 + 2 * m:]
            any_valid = ((src != token_pad) | (tgt != token_pad)
                         | (pth != path_pad)).any(axis=1)
            if estimator_action.is_train:
                any_valid &= rec[:, 0] > self.vocabs.target_vocab.oov_index
            keep_chunks.append(chunk[any_valid])
        return (np.concatenate(keep_chunks) if keep_chunks
                else np.empty((0,), np.int64))

    def _filtered_row_ids(self,
                          estimator_action: EstimatorAction) -> np.ndarray:
        cached = self._filtered_cache.get(estimator_action)
        if cached is None:
            cached = self._filter_rows(self.row_ids, estimator_action)
            self._filtered_cache[estimator_action] = cached
        return cached

    def _global_filtered_row_ids(
            self, estimator_action: EstimatorAction) -> np.ndarray:
        if self.num_shards == 1:
            return self._filtered_row_ids(estimator_action)
        key = ("global", estimator_action)
        cached = self._filtered_cache.get(key)
        if cached is None:
            cached = self._filter_rows(
                np.arange(self.num_rows_total, dtype=np.int64),
                estimator_action)
            self._filtered_cache[key] = cached
        return cached

    def steps_per_epoch(self, batch_size: int,
                        estimator_action: EstimatorAction,
                        skip_rows: int = 0) -> int:
        if estimator_action.is_train:
            n = len(self._global_filtered_row_ids(estimator_action))
            steps = n // (batch_size * self.num_shards)
            if skip_rows:
                skip_local = min(skip_rows // self.num_shards,
                                 steps * batch_size)
                return (steps * batch_size - skip_local) // batch_size
            return steps
        n = len(self._filtered_row_ids(estimator_action))
        return -(-n // batch_size)  # eval pads the tail batch

    def iter_batches(self, batch_size: int,
                     estimator_action: EstimatorAction,
                     num_epochs: int = 1, seed: int = 0,
                     repeat_endlessly: bool = False,
                     with_target_strings: bool = False,
                     yield_epoch_markers: bool = False,
                     start_epoch: int = 0,
                     skip_rows: int = 0) -> Iterator[RowBatch]:
        """PackedDataset.iter_batches, verbatim, over the manifest's
        global row space — same epoch keying, same truncate-then-stride
        host split, same skip_rows remap, so every cursor law carries
        over unchanged. Marks the corpus mid-epoch while an epoch's
        batches are in flight (what `adopt_appended_shards` checks)."""
        if estimator_action.is_train:
            epoch = 0
            while repeat_endlessly or epoch < num_epochs:
                # re-read per epoch (a cache hit unless shards were
                # adopted at the boundary): an adopted delta shard joins
                # the NEXT epoch's permutation, never a drawn one
                rows = self._global_filtered_row_ids(estimator_action)
                steps = len(rows) // (batch_size * self.num_shards)
                perm = _epoch_rng(seed, start_epoch + epoch).permutation(rows)
                seq = perm[self.shard_index::self.num_shards][
                    :steps * batch_size]
                if epoch == 0 and skip_rows:
                    seq = seq[skip_rows // self.num_shards:]
                n_full = (len(seq) // batch_size) * batch_size
                self._mid_epoch = True
                try:
                    for start in range(0, n_full, batch_size):
                        yield self.gather(seq[start:start + batch_size],
                                          with_target_strings)
                finally:
                    self._mid_epoch = False
                epoch += 1
                if yield_epoch_markers:
                    yield EpochEnd(epoch)
            return
        rows = self._filtered_row_ids(estimator_action)
        epoch = 0
        while repeat_endlessly or epoch < num_epochs:
            n_full = (len(rows) // batch_size) * batch_size
            for start in range(0, n_full, batch_size):
                yield self.gather(rows[start:start + batch_size],
                                  with_target_strings)
            tail = len(rows) - n_full
            if tail:
                batch = self.gather(rows[n_full:], with_target_strings)
                yield reader_mod._pad_rows(batch, batch_size)
            epoch += 1
            if yield_epoch_markers:
                yield EpochEnd(epoch)
