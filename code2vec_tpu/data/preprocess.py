"""Offline preprocessing: raw extractor output -> `.c2v` + `.dict.c2v`.

Combines the reference's awk histogram step (reference: preprocess.sh:56-58
— targets from field 1, tokens from context fields 1 and 3, paths from
field 2) and `preprocess.py` (context sampling with in-vocab preference,
space padding, dict pickling; reference: preprocess.py:23-74, 12-20) into
one Python module. Run-once and I/O-bound, so Python is the right tool
(SURVEY.md §7 step 8); the hot training-time path uses the packed reader.
"""

from __future__ import annotations

import os
import pickle
import random
import subprocess
import sys
from collections import Counter
from typing import Dict, Optional, Tuple


def build_histograms(raw_path: str) -> Tuple[Counter, Counter, Counter]:
    """Frequency histograms over a raw extractor-output file.

    Equivalent of the reference's three awk passes (preprocess.sh:56-58):
    every occurrence counts, including duplicates within a line.
    """
    targets: Counter = Counter()
    tokens: Counter = Counter()
    paths: Counter = Counter()
    with open(raw_path, "r", buffering=16 * 1024 * 1024) as f:
        for line in f:
            parts = line.rstrip("\n").split(" ")
            if not parts or not parts[0]:
                continue
            targets[parts[0]] += 1
            for ctx in parts[1:]:
                if not ctx:
                    continue
                pieces = ctx.split(",")
                if len(pieces) != 3:
                    continue
                tokens[pieces[0]] += 1
                paths[pieces[1]] += 1
                tokens[pieces[2]] += 1
    return tokens, paths, targets


def truncate_histogram(histogram: Dict[str, int], max_size: Optional[int]) -> Dict[str, int]:
    """Keep words whose count is >= one plus the max_size'th largest count
    when the histogram exceeds max_size (reference: common.py:47-58 —
    min-count thresholding, which may keep slightly fewer than max_size).
    """
    if max_size is None or len(histogram) <= max_size:
        return dict(histogram)
    min_count = sorted(histogram.values(), reverse=True)[max_size] + 1
    return {w: c for w, c in histogram.items() if c >= min_count}


def _context_full_found(parts, word_to_count, path_to_count) -> bool:
    # reference: preprocess.py:77-79
    return (parts[0] in word_to_count and parts[1] in path_to_count
            and parts[2] in word_to_count)


def _context_partial_found(parts, word_to_count, path_to_count) -> bool:
    # reference: preprocess.py:82-84
    return (parts[0] in word_to_count or parts[1] in path_to_count
            or parts[2] in word_to_count)


def process_file(file_path: str, data_file_role: str, dataset_name: str,
                 word_to_count: Dict[str, int], path_to_count: Dict[str, int],
                 max_contexts: int, rng: Optional[random.Random] = None,
                 log=print) -> int:
    """Sample/truncate each method's contexts to `max_contexts`, preferring
    fully-in-vocab then partially-in-vocab contexts, pad with spaces, write
    `<dataset>.<role>.c2v`. Returns the number of non-empty examples.

    reference: preprocess.py:23-74.
    """
    rng = rng or random.Random(0)
    sum_total = sum_sampled = total = empty = max_unfiltered = 0
    output_path = f"{dataset_name}.{data_file_role}.c2v"
    with open(output_path, "w") as outfile, open(file_path, "r") as file:
        for line in file:
            parts = line.rstrip("\n").split(" ")
            target_name = parts[0]
            contexts = parts[1:]
            max_unfiltered = max(max_unfiltered, len(contexts))
            sum_total += len(contexts)

            if len(contexts) > max_contexts:
                context_parts = [c.split(",") for c in contexts]
                full = [c for i, c in enumerate(contexts)
                        if _context_full_found(context_parts[i], word_to_count,
                                               path_to_count)]
                partial = [c for i, c in enumerate(contexts)
                           if _context_partial_found(context_parts[i], word_to_count,
                                                     path_to_count)
                           and not _context_full_found(context_parts[i],
                                                       word_to_count, path_to_count)]
                if len(full) > max_contexts:
                    contexts = rng.sample(full, max_contexts)
                elif len(full) + len(partial) > max_contexts:
                    contexts = full + rng.sample(partial, max_contexts - len(full))
                else:
                    contexts = full + partial

            if len(contexts) == 0:
                empty += 1
                continue
            sum_sampled += len(contexts)
            padding = " " * (max_contexts - len(contexts))
            outfile.write(target_name + " " + " ".join(contexts) + padding + "\n")
            total += 1

    log(f"File: {file_path}")
    log(f"Average total contexts: {float(sum_total) / max(total, 1)}")
    log(f"Average final (after sampling) contexts: {float(sum_sampled) / max(total, 1)}")
    log(f"Total examples: {total}")
    log(f"Empty examples: {empty}")
    log(f"Max number of contexts per word: {max_unfiltered}")
    return total


def save_dictionaries(dataset_name: str, word_to_count: Dict[str, int],
                      path_to_count: Dict[str, int], target_to_count: Dict[str, int],
                      num_training_examples: int, log=print) -> str:
    """Pickle the freq dicts + train count to `<dataset>.dict.c2v`
    (reference: preprocess.py:12-20)."""
    path = f"{dataset_name}.dict.c2v"
    with open(path, "wb") as f:
        pickle.dump(word_to_count, f)
        pickle.dump(path_to_count, f)
        pickle.dump(target_to_count, f)
        pickle.dump(num_training_examples, f)
    log(f"Dictionaries saved to: {path}")
    return path


def preprocess(train_raw: str, val_raw: str, test_raw: str, output_name: str,
               max_contexts: int = 200, word_vocab_size: int = 1301136,
               path_vocab_size: int = 911417, target_vocab_size: int = 261245,
               seed: int = 0, log=print) -> str:
    """Full offline pipeline: histograms from the raw train split, vocab
    truncation, context sampling for all three splits, dict pickling.

    Mirrors preprocess.sh:42-63 + preprocess.py:87-141 end-to-end.
    """
    tokens, paths, targets = build_histograms(train_raw)
    word_to_count = truncate_histogram(tokens, word_vocab_size)
    path_to_count = truncate_histogram(paths, path_vocab_size)
    target_to_count = truncate_histogram(targets, target_vocab_size)

    rng = random.Random(seed)
    num_training_examples = 0
    for file_path, role in zip([test_raw, val_raw, train_raw],
                               ["test", "val", "train"]):
        n = process_file(file_path, role, output_name, word_to_count,
                         path_to_count, max_contexts, rng=rng, log=log)
        if role == "train":
            num_training_examples = n
    save_dictionaries(output_name, word_to_count, path_to_count,
                      target_to_count, num_training_examples, log=log)
    return output_name


# --------------------------------------------------------------- extraction

def _native_extractor(language: str) -> str:
    binary = {"java": "c2v-extract", "csharp": "c2v-extract-cs"}[language]
    here = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    path = os.path.join(here, "cpp", "build", binary)
    if not os.path.exists(path):
        raise FileNotFoundError(
            f"native extractor `{path}` not built; run `make -C cpp`.")
    return path


def extract_dir(source_dir: str, out_path: str, language: str = "java",
                max_path_length: int = 8, max_path_width: int = 2,
                num_threads: int = 32, shuffle: bool = False,
                seed: int = 0, log=print) -> str:
    """Run the native AST path extractor over a source tree, writing raw
    context lines to `out_path` (optionally shuffled, as the reference
    pipes the train split through `shuf`, preprocess.sh:42-48).
    """
    extractor = _native_extractor(language)
    if language == "java":
        command = [extractor, "--max_path_length", str(max_path_length),
                   "--max_path_width", str(max_path_width),
                   "--dir", source_dir, "--num_threads", str(num_threads)]
    else:
        command = [extractor, "--path", source_dir,
                   "--max_length", str(max_path_length),
                   "--max_width", str(max_path_width),
                   "--threads", str(num_threads)]
    log(f"Extracting {source_dir} -> {out_path} ({language})")
    with open(out_path + ".tmp", "w") as out:
        result = subprocess.run(command, stdout=out, stderr=subprocess.PIPE,
                                text=True)
    if result.returncode != 0:
        raise RuntimeError(
            f"extractor failed ({result.returncode}): {result.stderr[-2000:]}")
    if result.stderr:
        skipped = result.stderr.count("failed to extract")
        if skipped:
            log(f"  ({skipped} files skipped as unparseable)")
    if shuffle:
        # like the reference's `| shuf`: whole-file shuffle of the raw
        # train split (training also reshuffles per epoch from the
        # packed dataset, so this only decorrelates the histogram pass)
        with open(out_path + ".tmp", "r") as f:
            lines = f.readlines()
        random.Random(seed).shuffle(lines)
        with open(out_path + ".tmp", "w") as f:
            f.writelines(lines)
    os.replace(out_path + ".tmp", out_path)
    return out_path


def main(argv=None) -> None:
    """End-to-end offline preprocessing CLI (the preprocess.sh equivalent):

      python -m code2vec_tpu.data.preprocess \\
          --train_dir DIR --val_dir DIR --test_dir DIR \\
          --output_name data/java-small/java-small [--language java]

    or, from already-extracted raw context files:

      python -m code2vec_tpu.data.preprocess \\
          --train_raw F --val_raw F --test_raw F --output_name NAME
    """
    import argparse
    parser = argparse.ArgumentParser(
        prog="code2vec_tpu.preprocess", description=main.__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    parser.add_argument("--train_dir")
    parser.add_argument("--val_dir")
    parser.add_argument("--test_dir")
    parser.add_argument("--train_raw")
    parser.add_argument("--val_raw")
    parser.add_argument("--test_raw")
    parser.add_argument("--output_name", required=True)
    parser.add_argument("--language", choices=["java", "csharp"],
                        default="java")
    parser.add_argument("--max_contexts", type=int, default=200)
    parser.add_argument("--max_path_length", type=int, default=8)
    parser.add_argument("--max_path_width", type=int, default=2)
    parser.add_argument("--word_vocab_size", type=int, default=1301136)
    parser.add_argument("--path_vocab_size", type=int, default=911417)
    parser.add_argument("--target_vocab_size", type=int, default=261245)
    parser.add_argument("--num_threads", type=int, default=32)
    parser.add_argument("--seed", type=int, default=0)
    args = parser.parse_args(argv)

    from_dirs = args.train_dir or args.val_dir or args.test_dir
    from_raws = args.train_raw or args.val_raw or args.test_raw
    if bool(from_dirs) == bool(from_raws):
        parser.error("provide either --{train,val,test}_dir or "
                     "--{train,val,test}_raw (not both)")
    if from_dirs and not (args.train_dir and args.val_dir and args.test_dir):
        parser.error("--train_dir, --val_dir and --test_dir are all required")
    if from_raws and not (args.train_raw and args.val_raw and args.test_raw):
        parser.error("--train_raw, --val_raw and --test_raw are all required")

    out_dir = os.path.dirname(args.output_name)
    if out_dir:
        os.makedirs(out_dir, exist_ok=True)

    if from_dirs:
        raws = {}
        for role, source_dir in (("train", args.train_dir),
                                 ("val", args.val_dir),
                                 ("test", args.test_dir)):
            raws[role] = extract_dir(
                source_dir, f"{args.output_name}.{role}.raw.txt",
                language=args.language, max_path_length=args.max_path_length,
                max_path_width=args.max_path_width,
                num_threads=args.num_threads, shuffle=role == "train",
                seed=args.seed)
    else:
        raws = {"train": args.train_raw, "val": args.val_raw,
                "test": args.test_raw}

    preprocess(raws["train"], raws["val"], raws["test"], args.output_name,
               max_contexts=args.max_contexts,
               word_vocab_size=args.word_vocab_size,
               path_vocab_size=args.path_vocab_size,
               target_vocab_size=args.target_vocab_size, seed=args.seed)


if __name__ == "__main__":
    main(sys.argv[1:])
