"""Offline preprocessing: raw extractor output -> `.c2v` + `.dict.c2v`.

Combines the reference's awk histogram step (reference: preprocess.sh:56-58
— targets from field 1, tokens from context fields 1 and 3, paths from
field 2) and `preprocess.py` (context sampling with in-vocab preference,
space padding, dict pickling; reference: preprocess.py:23-74, 12-20) into
one Python module. Run-once and I/O-bound, so Python is the right tool
(SURVEY.md §7 step 8); the hot training-time path uses the packed reader.
"""

from __future__ import annotations

import heapq
import math
import os
import pickle
import random
import shutil
import subprocess
import sys
import tempfile
import time
from collections import Counter
from typing import Dict, List, Optional, Tuple

from code2vec_tpu import obs

# ------------------------------------------------------------ parallelism
#
# The offline pipeline is a one-shot compile over a multi-GB corpus
# (java14m: 32 GB raw, reference README:69-75), so it map-reduces over
# host cores: the raw file is split into byte ranges aligned to line
# boundaries and each range is processed by a `multiprocessing` worker.
# Workers are pure host-side code (numpy + dicts, no jax), so `fork` is
# the zero-copy fast path; once the XLA backend (or any other thread) is
# live in this process (tests, a trainer that packs on demand), forking
# is unsafe and `spawn` is used instead — worker modules import cleanly
# under both, and spawn workers skip the package's jax import entirely
# (the C2V_HOST_WORKER gate in code2vec_tpu/__init__.py).


def _jax_backend_live() -> bool:
    # `import jax` alone starts no runtime threads; an initialized XLA
    # backend does. The package __init__ always imports jax, so mere
    # presence in sys.modules would force spawn everywhere.
    xb = sys.modules.get("jax._src.xla_bridge")
    return bool(getattr(xb, "_backends", None))


def _mp_context():
    import multiprocessing as mp
    import threading
    if ("fork" in mp.get_all_start_methods()
            and threading.active_count() == 1 and not _jax_backend_live()):
        return mp.get_context("fork")
    return mp.get_context("spawn")


class _worker_pool:
    """`Pool` wrapper: picks fork/spawn per `_mp_context`, and marks the
    children as host-side data workers (C2V_HOST_WORKER) so spawned ones
    skip the package's jax import."""

    def __init__(self, num_workers: int, initializer=None, initargs=()):
        ctx = _mp_context()
        prev = os.environ.get("C2V_HOST_WORKER")
        os.environ["C2V_HOST_WORKER"] = "1"
        try:
            self._pool = ctx.Pool(num_workers, initializer=initializer,
                                  initargs=initargs)
        finally:
            if prev is None:
                os.environ.pop("C2V_HOST_WORKER", None)
            else:
                os.environ["C2V_HOST_WORKER"] = prev

    def __enter__(self):
        return self._pool.__enter__()

    def __exit__(self, *exc):
        return self._pool.__exit__(*exc)


def line_aligned_ranges(path: str, n_shards: int) -> List[Tuple[int, int]]:
    """Split `[0, filesize)` into up to `n_shards` contiguous byte ranges
    whose boundaries fall on line starts, so every worker sees whole
    lines and the concatenation of ranges is exactly the file."""
    size = os.path.getsize(path)
    if size == 0 or n_shards <= 1:
        return [(0, size)]
    bounds = [0]
    with open(path, "rb") as f:
        for i in range(1, n_shards):
            target = size * i // n_shards
            if target <= bounds[-1]:
                continue
            f.seek(target)
            f.readline()  # finish the line straddling the cut
            pos = f.tell()
            if bounds[-1] < pos < size:
                bounds.append(pos)
    bounds.append(size)
    return list(zip(bounds[:-1], bounds[1:]))


def iter_range_line_chunks(path: str, start: int, end: int,
                           chunk_bytes: int = 32 * 1024 * 1024):
    """Yield lists of newline-stripped bytes lines covering `[start, end)`
    of `path`. `start`/`end` must fall on line boundaries
    (`line_aligned_ranges` guarantees it). Chunked binary reads + one
    C-level split keep the per-line Python overhead near zero."""
    with open(path, "rb") as f:
        f.seek(start)
        remaining = end - start
        carry = b""
        while remaining > 0:
            blob = f.read(min(chunk_bytes, remaining))
            if not blob:
                break
            remaining -= len(blob)
            lines = (carry + blob).split(b"\n")
            carry = lines.pop()
            if lines:
                yield lines
        if carry:
            yield [carry]  # unterminated final line


def _count_range_newlines(args) -> int:
    path, start, end = args
    count = 0
    with open(path, "rb") as f:
        f.seek(start)
        remaining = end - start
        while remaining > 0:
            blob = f.read(min(32 * 1024 * 1024, remaining))
            if not blob:
                break
            remaining -= len(blob)
            count += blob.count(b"\n")
    return count


def range_start_ordinals(path: str, ranges: List[Tuple[int, int]],
                         pool=None) -> List[int]:
    """Line ordinal of the first line of each range (ranges start at line
    boundaries, so lines-before == newlines-before). One cheap parallel
    byte-counting pass; this is what lets every worker seed each method's
    sampling RNG from its GLOBAL line ordinal, making the output
    independent of the worker count."""
    if len(ranges) == 1:
        return [0]
    tasks = [(path, s, e) for s, e in ranges[:-1]]  # last range not needed
    counts = (pool.map(_count_range_newlines, tasks) if pool is not None
              else [_count_range_newlines(t) for t in tasks])
    ordinals = [0]
    for c in counts:
        ordinals.append(ordinals[-1] + c)
    return ordinals


# Bound on the per-worker distinct-string memo Counters/caches: real
# corpora repeat contexts heavily, so memoizing per distinct context
# collapses most per-occurrence Python work to one C-level dict hit —
# but an adversarial corpus of all-distinct contexts must not grow RSS
# without bound, so memos are drained/cleared past this many entries.
_MEMO_CAP = 2_000_000


def _drain_ctx_counts(ctx_counts: Counter, tokens: Counter,
                      paths: Counter) -> None:
    """Fold per-distinct-context occurrence counts into the token/path
    histograms: each context splits ONCE however many times it occurred."""
    for ctx, count in ctx_counts.items():
        pieces = ctx.split(b",")
        if len(pieces) != 3:
            continue
        tokens[pieces[0]] += count
        paths[pieces[1]] += count
        tokens[pieces[2]] += count
    ctx_counts.clear()


def _read_count_dump(path: str) -> Counter:
    """Parse a native "count word" histogram dump (bytes keys)."""
    out: Counter = Counter()
    with open(path, "rb", buffering=8 * 1024 * 1024) as f:
        for line in f:
            count, word = line.rstrip(b"\n").split(b" ", 1)
            out[word] = int(count)
    return out


def _histogram_shard(args) -> Tuple[Counter, Counter, Counter]:
    """Map step: histograms over one byte range of the raw file.

    Uses the native GIL-releasing split core (`c2v_histogram_range`)
    when libc2vdata.so is built: C++ does the per-occurrence counting
    and Python only reads back one "count word" line per DISTINCT word.

    The pure-Python fallback counts whole context strings first (a
    C-speed `Counter.update`) and splits only the distinct ones —
    corpora repeat contexts heavily, so this collapses most
    per-occurrence Python work; the distinct-context Counter is drained
    past `_MEMO_CAP` so worker RSS stays bounded on any corpus. Keys
    are bytes either way; the reduce step decodes once."""
    path, start, end = args
    from code2vec_tpu.data import native
    if native.has_histogram_range():
        dump_dir = tempfile.mkdtemp(prefix="c2v_hist_",
                                    dir=os.path.dirname(path) or ".")
        try:
            outs = [os.path.join(dump_dir, name)
                    for name in ("tokens", "paths", "targets")]
            native.histogram_range(path, start, end, *outs)
            return tuple(_read_count_dump(p) for p in outs)
        finally:
            shutil.rmtree(dump_dir, ignore_errors=True)
    tokens: Counter = Counter()
    paths: Counter = Counter()
    targets: Counter = Counter()
    ctx_counts: Counter = Counter()
    for lines in iter_range_line_chunks(path, start, end):
        names: List[bytes] = []
        ctxs: List[bytes] = []
        for line in lines:
            parts = line.split(b" ")
            if not parts[0]:
                continue
            names.append(parts[0])
            ctxs += parts[1:]
        targets.update(names)
        ctx_counts.update(ctxs)
        # empty fields (double spaces) split to one piece and are
        # skipped by the drain, like the serial loop's `if not ctx`
        if len(ctx_counts) > _MEMO_CAP:
            _drain_ctx_counts(ctx_counts, tokens, paths)
    _drain_ctx_counts(ctx_counts, tokens, paths)
    return tokens, paths, targets


def _decode_counter(counter: Counter) -> Counter:
    return Counter({k.decode("utf-8", "surrogateescape"): v
                    for k, v in counter.items()})


def build_histograms(raw_path: str,
                     num_workers: int = 0) -> Tuple[Counter, Counter, Counter]:
    """Frequency histograms over a raw extractor-output file.

    Equivalent of the reference's three awk passes (preprocess.sh:56-58):
    every occurrence counts, including duplicates within a line.

    `num_workers == 0` runs the original in-process serial loop;
    `num_workers >= 1` map-reduces over line-aligned byte ranges in that
    many `multiprocessing` workers (1 runs the sharded algorithm
    in-process — the fused pipeline's serial reference point). The merged
    result equals the serial loop's for any worker count
    (tests/test_preprocess_pipeline.py pins it).
    """
    if num_workers >= 1:
        t0 = time.perf_counter()
        ranges = line_aligned_ranges(raw_path, num_workers)
        tasks = [(raw_path, s, e) for s, e in ranges]
        if len(tasks) == 1:
            shards = [_histogram_shard(tasks[0])]
        else:
            with _worker_pool(len(tasks)) as pool:
                shards = pool.map(_histogram_shard, tasks)
        tokens: Counter = Counter()
        paths: Counter = Counter()
        targets: Counter = Counter()
        for tok, pth, tgt in shards:
            tokens.update(tok)
            paths.update(pth)
            targets.update(tgt)
        dur = time.perf_counter() - t0
        obs.histogram("preprocess_phase_seconds",
                      "wall time of one offline-pipeline phase",
                      phase="histograms").observe(dur)
        n_lines = sum(targets.values())
        obs.counter("preprocess_rows_total", "raw lines consumed per phase",
                    phase="histograms").inc(n_lines)
        obs.gauge("preprocess_rows_per_sec", "phase throughput",
                  phase="histograms").set(n_lines / max(dur, 1e-9))
        return (_decode_counter(tokens), _decode_counter(paths),
                _decode_counter(targets))

    targets = Counter()
    tokens = Counter()
    paths = Counter()
    # utf-8/surrogateescape pinned (not the locale default) so the serial
    # and sharded paths tokenize identical bytes identically.
    with open(raw_path, "r", buffering=16 * 1024 * 1024,
              encoding="utf-8", errors="surrogateescape") as f:
        for line in f:
            parts = line.rstrip("\n").split(" ")
            if not parts or not parts[0]:
                continue
            targets[parts[0]] += 1
            for ctx in parts[1:]:
                if not ctx:
                    continue
                pieces = ctx.split(",")
                if len(pieces) != 3:
                    continue
                tokens[pieces[0]] += 1
                paths[pieces[1]] += 1
                tokens[pieces[2]] += 1
    return tokens, paths, targets


def truncate_histogram(histogram: Dict[str, int], max_size: Optional[int]) -> Dict[str, int]:
    """Keep words whose count is >= one plus the max_size'th largest count
    when the histogram exceeds max_size (reference: common.py:47-58 —
    min-count thresholding, which may keep slightly fewer than max_size).
    """
    if max_size is None or len(histogram) <= max_size:
        return dict(histogram)
    # The (max_size+1)'th largest count via a bounded heap: O(V log K)
    # and O(K) extra memory instead of sorting all V values (V is 1.3M
    # for the java14m token histogram).
    min_count = heapq.nlargest(max_size + 1, histogram.values())[-1] + 1
    return {w: c for w, c in histogram.items() if c >= min_count}


def canonical_freq_dict(histogram: Dict[str, int]) -> Dict[str, int]:
    """Re-key a frequency dict in (count desc, word asc) order.

    Dict iteration order is what breaks count ties downstream
    (`Vocab.create_from_freq_dict`'s stable sort), and a merged
    map-reduce histogram's insertion order depends on the worker count —
    canonicalizing here is part of what makes the fused pipeline's
    output byte-identical at any worker count."""
    return dict(sorted(histogram.items(), key=lambda kv: (-kv[1], kv[0])))


def _context_full_found(parts, word_to_count, path_to_count) -> bool:
    # reference: preprocess.py:77-79; missing pieces (malformed/empty
    # context fields) count as not-found instead of crashing the
    # sampling tiers (the reference would IndexError on such input)
    return (len(parts) > 2 and parts[0] in word_to_count
            and parts[1] in path_to_count and parts[2] in word_to_count)


def _context_partial_found(parts, word_to_count, path_to_count) -> bool:
    # reference: preprocess.py:82-84
    return (parts[0] in word_to_count
            or (len(parts) > 1 and parts[1] in path_to_count)
            or (len(parts) > 2 and parts[2] in word_to_count))


def process_file(file_path: str, data_file_role: str, dataset_name: str,
                 word_to_count: Dict[str, int], path_to_count: Dict[str, int],
                 max_contexts: int, rng: Optional[random.Random] = None,
                 log=print) -> int:
    """Sample/truncate each method's contexts to `max_contexts`, preferring
    fully-in-vocab then partially-in-vocab contexts, pad with spaces, write
    `<dataset>.<role>.c2v`. Returns the number of non-empty examples.

    reference: preprocess.py:23-74.
    """
    rng = rng or random.Random(0)
    contexts_seen = contexts_kept = written = skipped_empty = 0
    widest_method = 0
    output_path = f"{dataset_name}.{data_file_role}.c2v"
    with open(output_path, "w") as outfile, open(file_path, "r") as infile:
        for line in infile:
            fields = line.rstrip("\n").split(" ")
            method_name, contexts = fields[0], fields[1:]
            widest_method = max(widest_method, len(contexts))
            contexts_seen += len(contexts)

            if len(contexts) > max_contexts:
                # Over-budget methods keep their fully-in-vocab contexts
                # first, then partially-in-vocab ones, sampling at random
                # within the tier that crosses the budget — the sampling
                # contract the reference preprocessor defines
                # (preprocess.py:41-56), which the vocab hit rate of the
                # trained model depends on.
                split = [c.split(",") for c in contexts]
                in_vocab, mixed = [], []
                for ctx, parts in zip(contexts, split):
                    if _context_full_found(parts, word_to_count,
                                           path_to_count):
                        in_vocab.append(ctx)
                    elif _context_partial_found(parts, word_to_count,
                                                path_to_count):
                        mixed.append(ctx)
                if len(in_vocab) > max_contexts:
                    contexts = rng.sample(in_vocab, max_contexts)
                elif len(in_vocab) + len(mixed) > max_contexts:
                    contexts = in_vocab + rng.sample(
                        mixed, max_contexts - len(in_vocab))
                else:
                    contexts = in_vocab + mixed

            if not contexts:
                skipped_empty += 1
                continue
            contexts_kept += len(contexts)
            padding = " " * (max_contexts - len(contexts))
            outfile.write(method_name + " " + " ".join(contexts) + padding + "\n")
            written += 1

    denom = max(written, 1)
    log(f"{output_path}: {written} examples written, {skipped_empty} "
        f"skipped (no contexts)")
    log(f"  contexts/method: {contexts_seen / denom:.1f} raw -> "
        f"{contexts_kept / denom:.1f} after sampling "
        f"(widest method: {widest_method})")
    return written


def save_dictionaries(dataset_name: str, word_to_count: Dict[str, int],
                      path_to_count: Dict[str, int], target_to_count: Dict[str, int],
                      num_training_examples: int, log=print) -> str:
    """Pickle the freq dicts + train count to `<dataset>.dict.c2v`
    (reference: preprocess.py:12-20)."""
    path = f"{dataset_name}.dict.c2v"
    with open(path, "wb") as f:
        pickle.dump(word_to_count, f)
        pickle.dump(path_to_count, f)
        pickle.dump(target_to_count, f)
        pickle.dump(num_training_examples, f)
    log(f"Dictionaries saved to: {path}")
    return path


def preprocess(train_raw: str, val_raw: str, test_raw: str, output_name: str,
               max_contexts: int = 200, word_vocab_size: int = 1301136,
               path_vocab_size: int = 911417, target_vocab_size: int = 261245,
               seed: int = 0, log=print) -> str:
    """Full offline pipeline: histograms from the raw train split, vocab
    truncation, context sampling for all three splits, dict pickling.

    Mirrors preprocess.sh:42-63 + preprocess.py:87-141 end-to-end.
    """
    tokens, paths, targets = build_histograms(train_raw)
    word_to_count = truncate_histogram(tokens, word_vocab_size)
    path_to_count = truncate_histogram(paths, path_vocab_size)
    target_to_count = truncate_histogram(targets, target_vocab_size)

    rng = random.Random(seed)
    num_training_examples = 0
    for file_path, role in zip([test_raw, val_raw, train_raw],
                               ["test", "val", "train"]):
        n = process_file(file_path, role, output_name, word_to_count,
                         path_to_count, max_contexts, rng=rng, log=log)
        if role == "train":
            num_training_examples = n
    save_dictionaries(output_name, word_to_count, path_to_count,
                      target_to_count, num_training_examples, log=log)
    return output_name


def compile_corpus(train_raw: str, val_raw: str, test_raw: str,
                   output_name: str, max_contexts: int = 200,
                   word_vocab_size: int = 1301136,
                   path_vocab_size: int = 911417,
                   target_vocab_size: int = 261245, seed: int = 0,
                   num_workers: int = 1, emit_c2v: bool = False,
                   stats_out: Optional[dict] = None, log=print) -> str:
    """Fused multiprocess offline compile: raw extractor output ->
    `.c2vb` memmaps (+`.targets` sidecars) + `.dict.c2v`, with no padded
    `.c2v` text intermediate (that text is LARGER than the raw input and
    the old pack stage re-parsed every byte of it).

    Map-reduce histograms over the train split, vocab truncation, then a
    fused sample+lookup+pack pass per split (`data/packed.py pack_raw`)
    that applies the reference's two-tier in-vocab sampling contract
    (reference: preprocess.py:41-56) and writes int32 rows directly.

    Output is byte-identical at ANY worker count: each method's sampling
    RNG is seeded from (global seed, method ordinal), histograms are
    canonicalized before tie-breaking, and per-shard segments are
    stitched in file order. `emit_c2v` additionally writes the padded
    `.c2v` text files (compat path for reference tooling; same format
    and sampling contract, per-method RNG instead of one serial stream).

    `stats_out`, when given, is filled with per-phase wall times and row
    counts (the preprocessing bench reads it).
    """
    from code2vec_tpu.data import packed

    stats = stats_out if stats_out is not None else {}
    t0 = time.perf_counter()
    workers = max(1, num_workers)
    tokens, paths, targets = build_histograms(train_raw, num_workers=workers)
    stats["histograms_s"] = round(time.perf_counter() - t0, 2)
    log(f"histograms: {len(tokens)} tokens, {len(paths)} paths, "
        f"{len(targets)} targets ({stats['histograms_s']}s, "
        f"{workers} workers)")

    t1 = time.perf_counter()
    word_to_count = canonical_freq_dict(
        truncate_histogram(tokens, word_vocab_size))
    path_to_count = canonical_freq_dict(
        truncate_histogram(paths, path_vocab_size))
    target_to_count = canonical_freq_dict(
        truncate_histogram(targets, target_vocab_size))
    del tokens, paths, targets

    from code2vec_tpu.vocab import Code2VecVocabs, WordFreqDicts
    vocabs = Code2VecVocabs.create_from_freq_dicts(
        WordFreqDicts(word_to_count, path_to_count, target_to_count, 0),
        max_token_vocab_size=word_vocab_size,
        max_path_vocab_size=path_vocab_size,
        max_target_vocab_size=target_vocab_size)
    stats["vocab_s"] = round(time.perf_counter() - t1, 2)

    t2 = time.perf_counter()
    num_training_examples = 0
    total_rows = 0
    for file_path, role in zip([test_raw, val_raw, train_raw],
                               ["test", "val", "train"]):
        out_path = f"{output_name}.{role}.c2vb"
        c2v_out = f"{output_name}.{role}.c2v" if emit_c2v else None
        rows = packed.pack_raw(
            file_path, out_path, vocabs, word_to_count, path_to_count,
            max_contexts, seed=seed, num_workers=workers, c2v_out=c2v_out,
            log=log)
        obs.counter("preprocess_rows_total", "raw lines consumed per phase",
                    phase=f"pack_{role}").inc(rows)
        total_rows += rows
        if role == "train":
            num_training_examples = rows
    dur = time.perf_counter() - t2
    stats["pack_s"] = round(dur, 2)
    stats["rows"] = total_rows
    obs.histogram("preprocess_phase_seconds",
                  "wall time of one offline-pipeline phase",
                  phase="fused_pack").observe(dur)
    obs.gauge("preprocess_rows_per_sec", "phase throughput",
              phase="fused_pack").set(total_rows / max(dur, 1e-9))

    save_dictionaries(output_name, word_to_count, path_to_count,
                      target_to_count, num_training_examples, log=log)
    stats["wall_s"] = round(time.perf_counter() - t0, 2)
    log(f"fused compile: {total_rows} rows packed in {stats['pack_s']}s "
        f"({workers} workers); end-to-end {stats['wall_s']}s")
    return output_name


# --------------------------------------------------------------- extraction

def _native_extractor(language: str) -> str:
    binary = {"java": "c2v-extract", "csharp": "c2v-extract-cs",
              "cs": "c2v-extract-cs"}[language]
    here = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    path = os.path.join(here, "cpp", "build", binary)
    if not os.path.exists(path):
        raise FileNotFoundError(
            f"native extractor `{path}` not built; run `make -C cpp`.")
    return path


def _extractor_command(extractor: str, language: str, target_flag: str,
                       target: str, max_path_length: int,
                       max_path_width: int, num_threads: int):
    if language == "java":
        return [extractor, "--max_path_length", str(max_path_length),
                "--max_path_width", str(max_path_width),
                target_flag, target, "--num_threads", str(num_threads)]
    # the C# extractor takes --path for both files and directories
    return [extractor, "--path", target,
            "--max_length", str(max_path_length),
            "--max_width", str(max_path_width),
            "--threads", str(num_threads)]


def _child_targets(source_dir: str, language: str):
    """Extraction units under `source_dir`: subdirectories and loose
    source files of the target language, sorted for determinism. Shared
    by the sequential retry descent and the parallel project pool so
    both extract the same file set."""
    suffix = ".java" if language == "java" else ".cs"
    return [os.path.join(source_dir, name)
            for name in sorted(os.listdir(source_dir))
            if os.path.isdir(os.path.join(source_dir, name))
            or name.endswith(suffix)]


def _run_extractor_tree(out, extractor: str, language: str, target: str,
                        max_path_length: int, max_path_width: int,
                        num_threads: int, timeout: Optional[float],
                        log, _retrying: bool = False) -> int:
    """Extract `target` (a directory or file) into the open binary `out`
    stream, with a kill-timer and recursive per-subdirectory retry: if the
    whole tree times out, descend and extract each child separately so one
    pathological file cannot stall the run — the reference driver's
    resilience strategy (JavaExtractor/extract.py:38-58: kill-timer +
    per-subdir re-extraction, partial output discarded). During a retry
    descent, nonzero child exits are also skipped-and-logged rather than
    fatal (a file that crashes the parser must not abort the run); a
    nonzero exit on the original whole-tree attempt stays a hard error
    (that is a broken setup, not a bad input file).
    Returns the number of targets skipped after exhausting retries."""
    is_dir = os.path.isdir(target)
    flag = "--dir" if is_dir else "--file"
    command = _extractor_command(extractor, language, flag, target,
                                 max_path_length, max_path_width,
                                 num_threads)
    # stdout streams straight into `out` (no buffering of multi-GB
    # extractions); on kill/failure the file is truncated back so a
    # partial line from a killed run never survives (the reference
    # deletes partial outputs, JavaExtractor/extract.py:56-58). `out` is
    # binary-mode and only ever written through child fds, so tell() is
    # the true fd offset.
    out.flush()
    pos = out.tell()

    def descend() -> int:
        skipped = 0
        for child in _child_targets(target, language):
            skipped += _run_extractor_tree(
                out, extractor, language, child, max_path_length,
                max_path_width, num_threads, timeout, log, _retrying=True)
        return skipped

    try:
        result = subprocess.run(command, stdout=out, stderr=subprocess.PIPE,
                                text=True, timeout=timeout)
    except subprocess.TimeoutExpired:
        out.truncate(pos)
        out.seek(pos)
        if not is_dir:
            log(f"  TIMEOUT: skipping unextractable file {target}")
            return 1
        log(f"  TIMEOUT extracting {target}; retrying per child")
        return descend()
    if result.returncode != 0:
        out.truncate(pos)
        out.seek(pos)
        if _retrying:
            if is_dir:
                log(f"  extractor failed on {target} "
                    f"({result.returncode}); retrying per child")
                return descend()
            log(f"  extractor failed on {target} ({result.returncode}); "
                f"skipping")
            return 1
        raise RuntimeError(
            f"extractor failed ({result.returncode}): {result.stderr[-2000:]}")
    if result.stderr:
        unparseable = result.stderr.count("failed to extract")
        if unparseable:
            log(f"  ({unparseable} files skipped as unparseable)")
    return 0


def _extract_tree_parallel(out, extractor: str, language: str,
                           source_dir: str, max_path_length: int,
                           max_path_width: int, num_threads: int,
                           timeout: Optional[float], num_workers: int,
                           log) -> int:
    """Project-level extraction parallelism: a pool of `num_workers`
    workers over the top-level entries of `source_dir` — the reference
    driver's `multiprocessing.Pool(4)` over project dirs
    (reference: JavaExtractor/extract.py:61-76). Threads suffice here
    (each worker blocks in a `subprocess.run` of the internally-threaded
    native extractor); every child keeps the same kill-timer +
    per-child-retry protection, spilled to its own file and concatenated
    in deterministic (sorted) order. Returns total skipped targets."""
    from concurrent.futures import ThreadPoolExecutor

    children = _child_targets(source_dir, language)
    if not children:
        return 0
    # Don't oversubscribe the host: num_workers concurrent extractors x
    # num_threads each would run workers*threads native threads (the
    # reference's Pool(4) drove single-threaded JVMs). Split the thread
    # budget across the workers that will actually run concurrently.
    num_threads = max(1, num_threads // min(num_workers, len(children)))
    # spill next to the output file, not the system /tmp (often a small
    # tmpfs; the corpora this pipeline targets run to tens of GB)
    out_dir = os.path.dirname(getattr(out, "name", "") or "") or "."
    spill_dir = tempfile.mkdtemp(prefix="c2v_extract_", dir=out_dir)

    def extract_child(item) -> int:
        index, child = item
        with open(os.path.join(spill_dir, f"s{index:06d}"), "w+b") as spill:
            return _run_extractor_tree(
                spill, extractor, language, child, max_path_length,
                max_path_width, num_threads, timeout, log)

    try:
        with ThreadPoolExecutor(max_workers=num_workers) as pool:
            skipped = sum(pool.map(extract_child, enumerate(children)))
        for index in range(len(children)):
            with open(os.path.join(spill_dir, f"s{index:06d}"), "rb") as f:
                shutil.copyfileobj(f, out, 16 * 1024 * 1024)
    finally:
        shutil.rmtree(spill_dir, ignore_errors=True)
    return skipped


def extract_dir(source_dir: str, out_path: str, language: str = "java",
                max_path_length: int = 8, max_path_width: int = 2,
                num_threads: int = 32, shuffle: bool = False,
                seed: int = 0, timeout: Optional[float] = 600.0,
                num_workers: int = 1, log=print) -> str:
    """Run the native AST path extractor over a source tree, writing raw
    context lines to `out_path` (optionally shuffled, as the reference
    pipes the train split through `shuf`, preprocess.sh:42-48). A hung
    extraction is killed after `timeout` seconds and retried per
    subdirectory/file (reference: JavaExtractor/extract.py:38-58 — whose
    `Timer(600000, kill)` is in seconds, ~7 days, so its kill-timer never
    fires in practice; 600s here keeps the protection real and matches
    the CLI's --extract_timeout default). `num_workers > 1` extracts
    top-level children of `source_dir` concurrently, the reference
    driver's project-level `Pool(4)` (JavaExtractor/extract.py:61-76).
    """
    extractor = _native_extractor(language)
    log(f"Extracting {source_dir} -> {out_path} ({language})")
    with open(out_path + ".tmp", "wb") as out:
        if num_workers > 1 and os.path.isdir(source_dir):
            skipped = _extract_tree_parallel(
                out, extractor, language, source_dir, max_path_length,
                max_path_width, num_threads, timeout, num_workers, log)
        else:
            skipped = _run_extractor_tree(
                out, extractor, language, source_dir, max_path_length,
                max_path_width, num_threads, timeout, log)
        if skipped:
            log(f"  {skipped} targets skipped after timeout/failure")
    if shuffle:
        # like the reference's `| shuf`: whole-file shuffle of the raw
        # train split (training also reshuffles per epoch from the
        # packed dataset, so this only decorrelates the histogram pass)
        external_shuffle(out_path + ".tmp", seed=seed, log=log)
    os.replace(out_path + ".tmp", out_path)
    return out_path


def external_shuffle(path: str, seed: int = 0,
                     mem_budget_bytes: int = 1 << 30,
                     tmp_dir: Optional[str] = None, log=print) -> str:
    """Uniform in-place line shuffle of `path` in bounded memory.

    The reference pipes the raw train split through `shuf`
    (reference: preprocess.sh:44-48) and its docs size the extracted
    java14m corpus at ~32 GB (reference: README.md:69-75) — far past
    what a `readlines()` shuffle can hold. Two passes, `shuf`-style
    statistics in O(mem_budget) RAM:

      1. deal each line to one of K spill buckets, the bucket drawn
         iid uniformly per line;
      2. load each bucket (≈ file_size/K bytes), shuffle it in RAM,
         and append buckets to the output in order.

    Dealing iid-uniform buckets then permuting uniformly within each
    is exactly a uniform random permutation of the whole file (it is
    sorting by an iid uniform key whose high bits are the bucket id),
    so the result is statistically identical to `shuf`, at ~2x file
    size of extra disk and ~file_size/K peak RAM.

    Files at or under half of `mem_budget_bytes` take the direct
    in-memory path (a loaded file costs ~2x its bytes in line objects,
    so the halved threshold is what actually honors the budget).
    Deterministic for a fixed (seed, file, budget). Returns `path`.
    """
    size = os.path.getsize(path)
    rng = random.Random(seed)
    if size <= mem_budget_bytes // 2:
        with open(path, "rb") as f:
            lines = f.readlines()
        if lines and not lines[-1].endswith(b"\n"):
            # `shuf` newline-terminates every output line; without this a
            # final unterminated line would merge into its successor.
            lines[-1] += b"\n"
        rng.shuffle(lines)
        with open(path, "wb") as f:
            f.writelines(lines)
        return path

    # Bucket target well under the budget: Python str/list overhead plus
    # the shuffle's index churn make a loaded bucket cost ~2x its bytes.
    # n_buckets is capped so open fds and write-buffer RAM stay bounded;
    # a bucket that still exceeds the budget (inputs > ~128x the budget)
    # is shuffled recursively instead of loaded, so the memory bound
    # holds at any input size.
    n_buckets = min(512, max(2, math.ceil(size / (mem_budget_bytes // 4))))
    buffering = max(64 * 1024, min(4 * 1024 * 1024,
                                   mem_budget_bytes // (4 * n_buckets)))
    work_dir = tempfile.mkdtemp(prefix="c2v_shuf_",
                                dir=tmp_dir or os.path.dirname(path) or ".")
    log(f"  external shuffle: {size / 1e9:.2f} GB across {n_buckets} "
        f"spill buckets ({work_dir})")
    try:
        buckets = []
        try:
            for i in range(n_buckets):
                buckets.append(open(os.path.join(work_dir, f"b{i:05d}"),
                                    "wb", buffering=buffering))
            with open(path, "rb", buffering=16 * 1024 * 1024) as f:
                for line in f:
                    if not line.endswith(b"\n"):
                        line += b"\n"  # shuf-style: terminate the last line
                    buckets[rng.randrange(n_buckets)].write(line)
        finally:
            for b in buckets:
                b.close()
        out_tmp = path + ".shuf"
        with open(out_tmp, "wb", buffering=16 * 1024 * 1024) as out:
            for i in range(n_buckets):
                bucket_path = os.path.join(work_dir, f"b{i:05d}")
                if os.path.getsize(bucket_path) > mem_budget_bytes // 2:
                    # still over budget: permute the bucket recursively
                    # (uniform within the bucket is all pass 2 needs),
                    # then stream it through without loading
                    external_shuffle(bucket_path,
                                     seed=rng.randrange(1 << 63),
                                     mem_budget_bytes=mem_budget_bytes,
                                     tmp_dir=work_dir, log=log)
                    with open(bucket_path, "rb") as f:
                        shutil.copyfileobj(f, out, 16 * 1024 * 1024)
                else:
                    with open(bucket_path, "rb") as f:
                        lines = f.readlines()
                    rng.shuffle(lines)
                    out.writelines(lines)
                os.unlink(bucket_path)  # free disk before the next load
        os.replace(out_tmp, path)
    finally:
        shutil.rmtree(work_dir, ignore_errors=True)
    return path


def main(argv=None) -> None:
    """End-to-end offline preprocessing CLI (the preprocess.sh equivalent):

      python -m code2vec_tpu.data.preprocess \\
          --train_dir DIR --val_dir DIR --test_dir DIR \\
          --output_name data/java-small/java-small [--language java]

    or, from already-extracted raw context files:

      python -m code2vec_tpu.data.preprocess \\
          --train_raw F --val_raw F --test_raw F --output_name NAME
    """
    import argparse
    parser = argparse.ArgumentParser(
        prog="code2vec_tpu.preprocess", description=main.__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    parser.add_argument("--train_dir")
    parser.add_argument("--val_dir")
    parser.add_argument("--test_dir")
    parser.add_argument("--train_raw")
    parser.add_argument("--val_raw")
    parser.add_argument("--test_raw")
    parser.add_argument("--output_name", required=True)
    parser.add_argument("--language", choices=["java", "csharp"],
                        default="java")
    parser.add_argument("--max_contexts", type=int, default=200)
    parser.add_argument("--max_path_length", type=int, default=8)
    parser.add_argument("--max_path_width", type=int, default=2)
    parser.add_argument("--word_vocab_size", type=int, default=1301136)
    parser.add_argument("--path_vocab_size", type=int, default=911417)
    parser.add_argument("--target_vocab_size", type=int, default=261245)
    parser.add_argument("--num_threads", type=int, default=32)
    parser.add_argument("--num_workers", type=int, default=4,
                        help="concurrent top-level project extractions "
                             "(reference driver: Pool(4), "
                             "JavaExtractor/extract.py:61-76); the "
                             "--num_threads budget is divided across "
                             "workers so workers*threads never "
                             "oversubscribes the host")
    parser.add_argument("--extract_timeout", type=float, default=600.0,
                        help="seconds before a hung extraction is killed "
                             "and retried per subdirectory/file")
    parser.add_argument("--preprocess_workers", type=int, default=0,
                        help="host worker processes for the fused "
                             "histogram+sample+pack compile that emits "
                             ".c2vb memmaps directly (output is "
                             "byte-identical at any worker count); 0 "
                             "runs the original serial .c2v text "
                             "pipeline")
    parser.add_argument("--emit_c2v", action="store_true",
                        help="with --preprocess_workers >= 1, also write "
                             "the padded .c2v text files (compat path "
                             "for reference tooling)")
    parser.add_argument("--seed", type=int, default=0)
    args = parser.parse_args(argv)

    from_dirs = args.train_dir or args.val_dir or args.test_dir
    from_raws = args.train_raw or args.val_raw or args.test_raw
    if bool(from_dirs) == bool(from_raws):
        parser.error("provide either --{train,val,test}_dir or "
                     "--{train,val,test}_raw (not both)")
    if from_dirs and not (args.train_dir and args.val_dir and args.test_dir):
        parser.error("--train_dir, --val_dir and --test_dir are all required")
    if from_raws and not (args.train_raw and args.val_raw and args.test_raw):
        parser.error("--train_raw, --val_raw and --test_raw are all required")

    out_dir = os.path.dirname(args.output_name)
    if out_dir:
        os.makedirs(out_dir, exist_ok=True)

    if from_dirs:
        raws = {}
        for role, source_dir in (("train", args.train_dir),
                                 ("val", args.val_dir),
                                 ("test", args.test_dir)):
            raws[role] = extract_dir(
                source_dir, f"{args.output_name}.{role}.raw.txt",
                language=args.language, max_path_length=args.max_path_length,
                max_path_width=args.max_path_width,
                num_threads=args.num_threads, shuffle=role == "train",
                seed=args.seed, timeout=args.extract_timeout,
                num_workers=args.num_workers)
    else:
        raws = {"train": args.train_raw, "val": args.val_raw,
                "test": args.test_raw}

    if args.preprocess_workers >= 1:
        compile_corpus(raws["train"], raws["val"], raws["test"],
                       args.output_name, max_contexts=args.max_contexts,
                       word_vocab_size=args.word_vocab_size,
                       path_vocab_size=args.path_vocab_size,
                       target_vocab_size=args.target_vocab_size,
                       seed=args.seed, num_workers=args.preprocess_workers,
                       emit_c2v=args.emit_c2v)
    else:
        preprocess(raws["train"], raws["val"], raws["test"],
                   args.output_name, max_contexts=args.max_contexts,
                   word_vocab_size=args.word_vocab_size,
                   path_vocab_size=args.path_vocab_size,
                   target_vocab_size=args.target_vocab_size, seed=args.seed)

    # Same side-channel contract as bench.py: a CI runner pointing
    # C2V_METRICS_FILE at a node-exporter textfile dir gets the phase
    # timings/throughput Prometheus-side.
    metrics_file = os.environ.get("C2V_METRICS_FILE")
    if metrics_file:
        from code2vec_tpu.obs import exporters
        exporters.write_prometheus(metrics_file)


if __name__ == "__main__":
    main(sys.argv[1:])
