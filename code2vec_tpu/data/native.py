"""ctypes bridge to the native host data-pipeline core (libc2vdata.so).

The C library implements the text hot loop — per-line split, vocab
lookup, pad/mask — with the exact semantics of the Python path
(`data/reader.py parse_context_lines`, itself mirroring the reference's
in-graph pipeline, reference: path_context_reader.py:184-228). Python
keeps orchestration (shuffling, batching, filtering, device transfer);
C++ does the byte crunching. Falls back cleanly when the library is not
built (`make -C cpp`).
"""

from __future__ import annotations

import ctypes
import os
import threading
import weakref
from typing import Optional, Sequence

import numpy as np

_LIB_ENV = "C2V_NATIVE_DATALOADER"
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_lib_checked = False


def _library_path() -> str:
    env = os.environ.get(_LIB_ENV)
    if env:
        return env
    here = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return os.path.join(here, "cpp", "build", "libc2vdata.so")


def load_library() -> Optional[ctypes.CDLL]:
    """Loads and signature-checks libc2vdata.so once; None if unavailable."""
    global _lib, _lib_checked
    with _lock:
        if _lib_checked:
            return _lib
        _lib_checked = True
        path = _library_path()
        if not os.path.exists(path):
            return None
        lib = ctypes.CDLL(path)
        i32, i64, p = ctypes.c_int32, ctypes.c_int64, ctypes.c_void_p
        lib.c2v_tables_create.restype = p
        lib.c2v_tables_create.argtypes = [i32, i32, i32, i32, i32]
        lib.c2v_tables_destroy.argtypes = [p]
        lib.c2v_tables_load.argtypes = [p, i32, ctypes.c_char_p, i64,
                                        ctypes.POINTER(i32), i64]
        lib.c2v_parse_text.restype = i64
        lib.c2v_parse_text.argtypes = [p, ctypes.c_char_p, i64, i32,
                                       ctypes.POINTER(i32), ctypes.POINTER(i32),
                                       ctypes.POINTER(i32), ctypes.POINTER(i32),
                                       ctypes.c_void_p, i64]
        lib.c2v_pack_file.restype = i64
        lib.c2v_pack_file.argtypes = [p, ctypes.c_char_p, ctypes.c_char_p,
                                      ctypes.c_char_p, i32, i32]
        try:
            lib.c2v_parse_rows.restype = i64
            lib.c2v_parse_rows.argtypes = [p, ctypes.c_char_p, i64, i32,
                                           ctypes.POINTER(i32), i64]
        except AttributeError:
            pass  # pre-parse_rows build; parse_blob stays available
        try:
            lib.c2v_histogram_range.restype = i64
            lib.c2v_histogram_range.argtypes = [
                ctypes.c_char_p, i64, i64, ctypes.c_char_p,
                ctypes.c_char_p, ctypes.c_char_p]
        except AttributeError:
            # library built before the histogram entry point existed;
            # histogram_range() raises and callers fall back to Python
            pass
        _lib = lib
        return _lib


def histogram_range(raw_path: str, start: int, end: int, tokens_out: str,
                    paths_out: str, targets_out: str) -> int:
    """Token/path/target occurrence histograms over one line-aligned byte
    range of a raw extractor file, dumped as "count word" lines — the
    map step of the multiprocess histogram build (needs no vocab tables).
    Returns the number of lines consumed."""
    lib = load_library()
    if lib is None or not hasattr(lib, "c2v_histogram_range"):
        raise RuntimeError(
            "libc2vdata.so with c2v_histogram_range not built "
            "(run `make -C cpp`)")
    n = lib.c2v_histogram_range(raw_path.encode(), start, end,
                                tokens_out.encode(), paths_out.encode(),
                                targets_out.encode())
    if n < 0:
        raise IOError(f"native histogram failed for {raw_path} "
                      f"[{start}:{end})")
    return n


def has_histogram_range() -> bool:
    lib = load_library()
    return lib is not None and hasattr(lib, "c2v_histogram_range")


def _i32ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))


class NativeTables:
    """Native string->id tables for one `Code2VecVocabs` instance (or, via
    `from_tables`, for raw bytes->id dicts — the multiprocess pack workers
    carry plain dicts instead of a pickled vocab object)."""

    def __init__(self, vocabs):
        lib = load_library()
        if lib is None:
            raise RuntimeError("libc2vdata.so not built (run `make -C cpp`)")
        tok, pth, tgt = (vocabs.token_vocab, vocabs.path_vocab,
                         vocabs.target_vocab)

        def encode(vocab):
            return {w.encode("utf-8", "surrogateescape"): i
                    for w, i in vocab.word_to_index.items()}

        self._init_from(lib, encode(tok), encode(pth), encode(tgt),
                        tok.pad_index, tok.oov_index, pth.pad_index,
                        pth.oov_index, tgt.oov_index)

    @classmethod
    def from_tables(cls, token_b2i, path_b2i, target_b2i, *, token_pad,
                    token_oov, path_pad, path_oov,
                    target_oov) -> "NativeTables":
        """Build tables from bytes->id dicts directly (no vocab object)."""
        lib = load_library()
        if lib is None:
            raise RuntimeError("libc2vdata.so not built (run `make -C cpp`)")
        self = cls.__new__(cls)
        self._init_from(lib, token_b2i, path_b2i, target_b2i, token_pad,
                        token_oov, path_pad, path_oov, target_oov)
        return self

    def _init_from(self, lib, token_b2i, path_b2i, target_b2i, token_pad,
                   token_oov, path_pad, path_oov, target_oov) -> None:
        self._lib = lib
        self._handle = lib.c2v_tables_create(
            token_pad, token_oov, path_pad, path_oov, target_oov)
        for which, table in enumerate((token_b2i, path_b2i, target_b2i)):
            items = sorted(table.items(), key=lambda kv: kv[1])
            words = b"\n".join(w for w, _ in items)
            ids = np.asarray([i for _, i in items], dtype=np.int32)
            lib.c2v_tables_load(self._handle, which, words, len(words),
                                _i32ptr(ids), len(items))

    def __del__(self):
        handle = getattr(self, "_handle", None)
        if handle and getattr(self, "_lib", None) is not None:
            self._lib.c2v_tables_destroy(handle)
            self._handle = None

    # ------------------------------------------------------------------

    def parse_lines(self, lines: Sequence[str], max_contexts: int):
        """Parse context lines to (src, pth, tgt, label, mask) arrays,
        or None when the input needs the Python path (a "line" with an
        interior newline would shift every following row)."""
        # one '\n' terminator per line so blank lines still yield a row
        text = "".join(line if line.endswith("\n") else line + "\n"
                       for line in lines)
        data = text.encode("utf-8", "surrogateescape")
        n = len(lines)
        if data.count(b"\n") != n:
            return None
        return self.parse_blob(data, n, max_contexts)

    def parse_blob(self, data: bytes, n: int, max_contexts: int):
        """Parse `n` newline-terminated context lines, pre-encoded as one
        bytes blob, to (src, pth, tgt, label, mask) arrays. The pack
        workers' entry point: they hold bytes lines already, so there is
        no per-line join/re-encode. Caller guarantees `data` holds
        exactly `n` lines, each ending in b"\\n"."""
        m = max_contexts
        src = np.empty((n, m), dtype=np.int32)
        pth = np.empty((n, m), dtype=np.int32)
        tgt = np.empty((n, m), dtype=np.int32)
        label = np.empty((n,), dtype=np.int32)
        mask = np.empty((n, m), dtype=np.float32)
        parsed = self._lib.c2v_parse_text(
            self._handle, data, len(data), m, _i32ptr(src), _i32ptr(pth),
            _i32ptr(tgt), _i32ptr(label),
            mask.ctypes.data_as(ctypes.c_void_p), n)
        # newline-terminated input never yields extra rows; a short count
        # means a bug.
        assert parsed == n, (parsed, n)
        return src, pth, tgt, label, mask

    def parse_rows_blob(self, data: bytes, n: int,
                        max_contexts: int) -> np.ndarray:
        """Parse `n` newline-terminated lines (one bytes blob) straight
        into an `(n, 1 + 3*m)` int32 array in the `.c2vb` interleaved row
        layout — the pack workers write this buffer to disk with no
        further copy. Requires a libc2vdata.so with `c2v_parse_rows`
        (raises AttributeError on older builds; callers fall back to
        `parse_blob` + explicit interleave)."""
        m = max_contexts
        rec = np.empty((n, 1 + 3 * m), dtype=np.int32)
        parsed = self._lib.c2v_parse_rows(self._handle, data, len(data), m,
                                          _i32ptr(rec), n)
        assert parsed == n, (parsed, n)
        return rec

    def pack_file(self, c2v_path: str, out_path: str, max_contexts: int,
                  targets_path: Optional[str] = None,
                  num_threads: int = 0) -> int:
        """Compile `.c2v` -> `.c2vb`; returns the row count."""
        rows = self._lib.c2v_pack_file(
            self._handle, c2v_path.encode(), out_path.encode(),
            targets_path.encode() if targets_path else None,
            max_contexts, num_threads)
        if rows < 0:
            raise IOError(f"native pack failed for {c2v_path} -> {out_path}")
        return rows


# Weak-keyed so dropping a Code2VecVocabs frees its (large) native
# tables; NativeTables holds no back-reference to the key.
_tables_cache: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


def tables_for(vocabs) -> Optional[NativeTables]:
    """Returns (cached) native tables for `vocabs`, or None if the
    library isn't built."""
    if load_library() is None:
        return None
    tables = _tables_cache.get(vocabs)
    if tables is None:
        tables = NativeTables(vocabs)
        _tables_cache[vocabs] = tables
    return tables
