"""Host-side streaming reader for `.c2v` path-context files.

TPU-first redesign of the reference's in-graph tf.data pipeline
(reference: path_context_reader.py:119-228): strings never reach the
device. The host tokenizes, looks up vocab ids, pads and masks into fixed
`(B, MAX_CONTEXTS)` int32 arrays; XLA only ever sees integers. Row
semantics are reproduced exactly:

- a context is valid iff any of its three parts is not PAD
  (reference: path_context_reader.py:209-214);
- training rows are dropped when the target is OOV/PAD or no context is
  valid; eval rows only when no context is valid; predict rows never
  (reference: path_context_reader.py:153-177, 100);
- missing trailing fields behave like padding contexts (the reference's
  CsvDataset record_defaults, path_context_reader.py:82-83).

Shuffling uses a bounded reservoir-style buffer like tf.data's
`shuffle(buffer_size)` (reference: path_context_reader.py:139), and the
file can be sharded across hosts (`shard_index`/`num_shards`) for
multi-host TPU pods — each host reads a disjoint subset of rows.
"""

from __future__ import annotations

import dataclasses
import enum
import hashlib
import os
import random
import struct
import time
from typing import Iterable, Iterator, List, Optional, Sequence

import numpy as np

from code2vec_tpu import obs
from code2vec_tpu.vocab import Code2VecVocabs

# Handles cached at module scope: _parse_chunk is the reader's hot path
# (called from the worker pool threads; the registry's metrics are
# thread-safe, the lookup lock is what we avoid per chunk).
_H_PARSE = obs.histogram(
    "data_parse_seconds",
    "parse+filter of one reader chunk (parse_chunk_lines raw lines)")
_C_ROWS_READ = obs.counter("data_rows_read_total",
                           "raw .c2v lines parsed")
_C_ROWS_DROPPED = obs.counter(
    "data_rows_dropped_total",
    "parsed rows removed by the reference row filter (OOV target / no "
    "valid context)")


@dataclasses.dataclass(frozen=True)
class EpochEnd:
    """Marker yielded between epochs when a batch stream is constructed
    with `yield_epoch_markers=True`.

    This is the data-pass boundary itself — the trainer drives per-epoch
    checkpointing/evaluation off these markers instead of a raw-line
    `train_steps_per_epoch` estimate, so the schedule cannot drift when
    rows are filtered out (the reference counts raw lines,
    config.py:165-167, and its step math is therefore approximate).
    `epoch` is 1-based: the marker follows the epoch's last batch.
    """
    epoch: int


class EstimatorAction(enum.Enum):
    Train = "train"
    Evaluate = "evaluate"
    Predict = "predict"

    @property
    def is_train(self) -> bool:
        return self is EstimatorAction.Train

    @property
    def is_evaluate(self) -> bool:
        return self is EstimatorAction.Evaluate

    @property
    def is_predict(self) -> bool:
        return self is EstimatorAction.Predict


@dataclasses.dataclass
class RowBatch:
    """One batch of model inputs (host numpy; device transfer elsewhere).

    `example_valid` marks rows that are real examples (the final batch of an
    eval epoch is padded up to the fixed batch size so shapes stay static
    under jit; metrics must ignore padded rows).
    """
    source_token_indices: np.ndarray   # (B, M) int32
    path_indices: np.ndarray           # (B, M) int32
    target_token_indices: np.ndarray   # (B, M) int32
    context_valid_mask: np.ndarray     # (B, M) float32
    target_index: np.ndarray           # (B,) int32
    example_valid: np.ndarray          # (B,) bool
    target_strings: Optional[List[str]] = None      # (B,) for eval/predict
    # Raw string triples, only materialized for predict (attention display).
    source_strings: Optional[np.ndarray] = None     # (B, M) object
    path_strings: Optional[np.ndarray] = None       # (B, M) object
    target_token_strings: Optional[np.ndarray] = None  # (B, M) object

    @property
    def num_valid(self) -> int:
        return int(self.example_valid.sum())

    def model_inputs(self):
        return (self.source_token_indices, self.path_indices,
                self.target_token_indices, self.context_valid_mask)


def parse_context_lines(
    lines: Sequence[str],
    vocabs: Code2VecVocabs,
    max_contexts: int,
    estimator_action: EstimatorAction,
    keep_strings: bool = False,
    out: Optional[RowBatch] = None,
    row_offset: int = 0,
) -> RowBatch:
    """Parse raw `.c2v` lines into a RowBatch (unfiltered).

    With `out`, parse straight into rows [row_offset, row_offset+len)
    of an existing keep-strings RowBatch (the serving slot buffer from
    `empty_predict_batch`) instead of allocating a fresh batch — the
    zero-copy request path. Rows are reset to PAD first (buffers are
    pooled/reused), `example_valid` flips True for the filled rows, and
    `out` itself is returned. The write is row-local, so concurrent
    callers may fill DISJOINT row ranges of one buffer without a lock.

    Reference row parse: path_context_reader.py:184-228.
    """
    n = len(lines)
    m = max_contexts
    keep = keep_strings or estimator_action.is_predict
    if not keep:
        # Hot path: the native C++ core does split+lookup+mask when built
        # (identical semantics; tests/test_native_dataloader.py pins it).
        from code2vec_tpu.data import native
        tables = native.tables_for(vocabs)
        parsed = tables.parse_lines(lines, m) if tables is not None else None
        if parsed is not None:
            src, pth, tgt, label, mask = parsed
            return RowBatch(
                source_token_indices=src,
                path_indices=pth,
                target_token_indices=tgt,
                context_valid_mask=mask,
                target_index=label,
                example_valid=np.ones((n,), dtype=bool),
                # only evaluation reads the raw targets; training must not
                # pay a per-line Python loop after the C call
                target_strings=(
                    [line.split(" ", 1)[0].rstrip("\n") for line in lines]
                    if estimator_action.is_evaluate else None),
            )
    token_w2i = vocabs.token_vocab.word_to_index
    path_w2i = vocabs.path_vocab.word_to_index
    token_oov = vocabs.token_vocab.oov_index
    path_oov = vocabs.path_vocab.oov_index
    token_pad = vocabs.token_vocab.pad_index
    path_pad = vocabs.path_vocab.pad_index

    if out is None:
        src = np.full((n, m), token_pad, dtype=np.int32)
        pth = np.full((n, m), path_pad, dtype=np.int32)
        tgt = np.full((n, m), token_pad, dtype=np.int32)
        target_index = np.empty((n,), dtype=np.int32)
        if keep:
            src_s = np.full((n, m), "", dtype=object)
            pth_s = np.full((n, m), "", dtype=object)
            tgt_s = np.full((n, m), "", dtype=object)
    else:
        if not keep:
            raise ValueError("out= requires the keep-strings parse path")
        if out.source_token_indices.shape[1] != m:
            raise ValueError(
                f"out buffer context width "
                f"{out.source_token_indices.shape[1]} != {m}")
        sl = slice(row_offset, row_offset + n)
        src = out.source_token_indices[sl]
        pth = out.path_indices[sl]
        tgt = out.target_token_indices[sl]
        target_index = out.target_index[sl]
        src_s = out.source_strings[sl]
        pth_s = out.path_strings[sl]
        tgt_s = out.target_token_strings[sl]
        src[:] = token_pad
        pth[:] = path_pad
        tgt[:] = token_pad
        src_s[:] = ""
        pth_s[:] = ""
        tgt_s[:] = ""
    target_strings: List[str] = []

    target_lookup = vocabs.target_vocab.lookup_index
    for i, line in enumerate(lines):
        parts = line.rstrip("\n").split(" ")
        target_str = parts[0] if parts else ""
        target_strings.append(target_str)
        target_index[i] = target_lookup(target_str)
        row_contexts = parts[1:m + 1]
        for j, ctx in enumerate(row_contexts):
            if not ctx:
                continue
            pieces = ctx.split(",")
            # Malformed contexts (< 3 fields) behave like the reference's
            # sparse->dense fill: missing parts are PAD
            # (path_context_reader.py:190-196).
            a = pieces[0] if len(pieces) > 0 else ""
            b = pieces[1] if len(pieces) > 1 else ""
            c = pieces[2] if len(pieces) > 2 else ""
            src[i, j] = token_w2i.get(a, token_pad if a == "" else token_oov)
            pth[i, j] = path_w2i.get(b, path_pad if b == "" else path_oov)
            tgt[i, j] = token_w2i.get(c, token_pad if c == "" else token_oov)
            if keep:
                src_s[i, j], pth_s[i, j], tgt_s[i, j] = a, b, c

    # Context valid iff any part is not PAD (reference:
    # path_context_reader.py:209-214). Note that in the joined PAD/OOV
    # scheme an all-OOV context is treated as invalid — intentionally
    # identical to the reference.
    mask = ((src != token_pad) | (tgt != token_pad) | (pth != path_pad))
    context_valid_mask = mask.astype(np.float32)

    if out is not None:
        sl = slice(row_offset, row_offset + n)
        out.context_valid_mask[sl] = context_valid_mask
        out.example_valid[sl] = True
        for i, t in enumerate(target_strings):
            out.target_strings[row_offset + i] = t
        return out

    return RowBatch(
        source_token_indices=src,
        path_indices=pth,
        target_token_indices=tgt,
        context_valid_mask=context_valid_mask,
        target_index=target_index,
        example_valid=np.ones((n,), dtype=bool),
        target_strings=target_strings,
        source_strings=src_s if keep else None,
        path_strings=pth_s if keep else None,
        target_token_strings=tgt_s if keep else None,
    )


def row_filter_mask(batch: RowBatch, vocabs: Code2VecVocabs,
                    estimator_action: EstimatorAction) -> np.ndarray:
    """Vectorized reference row filter (path_context_reader.py:153-177)."""
    any_valid = batch.context_valid_mask.any(axis=1)
    if estimator_action.is_train:
        target_known = batch.target_index > vocabs.target_vocab.oov_index
        return any_valid & target_known
    return any_valid


def _select_rows(batch: RowBatch, idx: np.ndarray) -> RowBatch:
    def sel(x):
        if x is None:
            return None
        if isinstance(x, list):
            return [x[i] for i in idx]
        return x[idx]
    return RowBatch(**{f.name: sel(getattr(batch, f.name))
                       for f in dataclasses.fields(RowBatch)})


def invalid_batch(batch_size: int, max_contexts: int) -> RowBatch:
    """A batch of nothing: every row invalid, every context masked.

    Multi-host eval pads short hosts' streams with these so all hosts
    run the same number of collective eval steps
    (parallel/distributed.py lockstep_eval_stream); index 0 is the pad
    row in every vocab, matching `_pad_rows`' fill."""
    return RowBatch(
        source_token_indices=np.zeros((batch_size, max_contexts), np.int32),
        path_indices=np.zeros((batch_size, max_contexts), np.int32),
        target_token_indices=np.zeros((batch_size, max_contexts), np.int32),
        context_valid_mask=np.zeros((batch_size, max_contexts), np.float32),
        target_index=np.zeros((batch_size,), np.int32),
        example_valid=np.zeros((batch_size,), bool),
        target_strings=[""] * batch_size,
    )


def empty_predict_batch(batch_size: int, max_contexts: int,
                        vocabs: Code2VecVocabs) -> RowBatch:
    """Pad-filled keep-strings RowBatch — the serving slot buffer.

    Every row starts invalid (PAD indices, zero mask); requests reserve
    disjoint row ranges and `parse_context_lines(out=...)` fills them in
    place, so a coalesced device batch ships without any per-request
    array intermediate. PAD fill (not zeros) matters: an unclaimed row
    must look exactly like `_pad_rows`' padding so the device step's
    row-local math is identical to the collect-then-dispatch path."""
    m = max_contexts
    token_pad = vocabs.token_vocab.pad_index
    path_pad = vocabs.path_vocab.pad_index
    return RowBatch(
        source_token_indices=np.full((batch_size, m), token_pad,
                                     dtype=np.int32),
        path_indices=np.full((batch_size, m), path_pad, dtype=np.int32),
        target_token_indices=np.full((batch_size, m), token_pad,
                                     dtype=np.int32),
        context_valid_mask=np.zeros((batch_size, m), np.float32),
        target_index=np.zeros((batch_size,), np.int32),
        example_valid=np.zeros((batch_size,), bool),
        target_strings=[""] * batch_size,
        source_strings=np.full((batch_size, m), "", dtype=object),
        path_strings=np.full((batch_size, m), "", dtype=object),
        target_token_strings=np.full((batch_size, m), "", dtype=object),
    )


def slice_contexts(batch: RowBatch, m: int) -> RowBatch:
    """Truncate the context axis to the first `m` columns (bucketed
    predict: serving/batcher.py picks the smallest configured bucket
    that still holds every VALID context of the batch, so the slice
    never drops a real context — only padding columns)."""
    if batch.source_token_indices.shape[1] <= m:
        return batch

    def cut(x):
        return None if x is None else x[:, :m]

    return RowBatch(
        source_token_indices=cut(batch.source_token_indices),
        path_indices=cut(batch.path_indices),
        target_token_indices=cut(batch.target_token_indices),
        context_valid_mask=cut(batch.context_valid_mask),
        target_index=batch.target_index,
        example_valid=batch.example_valid,
        target_strings=batch.target_strings,
        source_strings=cut(batch.source_strings),
        path_strings=cut(batch.path_strings),
        target_token_strings=cut(batch.target_token_strings),
    )


def truncate_rows(batch: RowBatch, rows: int) -> RowBatch:
    """Drop trailing rows (basic slices -> views, no copies). Callers
    guarantee the dropped rows are padding/invalid — the serving head
    dispatch trims a full-width slot buffer down to the smaller row
    shape the MIPS step compiled at."""
    if batch.target_index.shape[0] <= rows:
        return batch

    def cut(x):
        return None if x is None else x[:rows]

    return RowBatch(**{f.name: cut(getattr(batch, f.name))
                       for f in dataclasses.fields(RowBatch)})


def _pad_rows(batch: RowBatch, batch_size: int) -> RowBatch:
    """Pad with invalid rows up to `batch_size` (static shapes under jit)."""
    n = batch.target_index.shape[0]
    if n == batch_size:
        return batch
    pad = batch_size - n

    def pad_arr(x, fill=0):
        if x is None:
            return None
        if isinstance(x, list):
            return x + [""] * pad
        shape = (pad,) + x.shape[1:]
        return np.concatenate([x, np.full(shape, fill, dtype=x.dtype)], axis=0)

    out = RowBatch(
        source_token_indices=pad_arr(batch.source_token_indices),
        path_indices=pad_arr(batch.path_indices),
        target_token_indices=pad_arr(batch.target_token_indices),
        context_valid_mask=pad_arr(batch.context_valid_mask),
        target_index=pad_arr(batch.target_index),
        example_valid=np.concatenate([batch.example_valid,
                                      np.zeros((pad,), dtype=bool)]),
        target_strings=pad_arr(batch.target_strings),
        source_strings=pad_arr(batch.source_strings, fill=""),
        path_strings=pad_arr(batch.path_strings, fill=""),
        target_token_strings=pad_arr(batch.target_token_strings, fill=""),
    )
    return out


def _iter_file_lines(path: str, shard_index: int, num_shards: int,
                     buffer_size: int = 16 * 1024 * 1024) -> Iterator[str]:
    # buffer_size plays the role of the reference's CsvDataset buffer
    # (config.csv_buffer_size; reference: path_context_reader.py:122-125).
    with open(path, "r", buffering=buffer_size) as f:
        for i, line in enumerate(f):
            if num_shards > 1 and i % num_shards != shard_index:
                continue
            yield line


def _epoch_shuffle_rng(seed: int, epoch: int) -> random.Random:
    """Shuffle RNG for one absolute epoch index: a stable blake2b hash
    of (seed, epoch), NOT a tuple seed (tuple seeding routes through
    hash(), which PYTHONHASHSEED randomizes across processes). Keyed
    per epoch so a resumed run shuffles epoch e exactly like an
    uninterrupted run would — the text-reader counterpart of the packed
    dataset's elastic epoch-keyed permutation."""
    digest = hashlib.blake2b(struct.pack("<qq", seed, epoch),
                             digest_size=16).digest()
    return random.Random(int.from_bytes(digest, "little"))


class PathContextReader:
    """Streaming batched reader with reference-equivalent semantics.

    Yields `RowBatch`es of exactly `batch_size` rows. In training the final
    partial batch (across all epochs) is dropped — static shapes are worth
    far more on TPU than the reference's single ragged tail batch
    (path_context_reader.py:148 allows a ragged final batch; the deviation
    is at most one batch per run). In evaluation the tail is padded and
    marked invalid instead so every example is scored.
    """

    def __init__(self, vocabs: Code2VecVocabs, config,
                 estimator_action: EstimatorAction,
                 data_path: Optional[str] = None,
                 shard_index: int = 0, num_shards: int = 1,
                 repeat_endlessly: bool = False,
                 parse_chunk_lines: int = 4096,
                 batch_size: Optional[int] = None,
                 num_epochs: Optional[int] = None,
                 yield_epoch_markers: bool = False,
                 start_epoch: int = 0,
                 skip_rows: int = 0):
        self.vocabs = vocabs
        self.config = config
        self.estimator_action = estimator_action
        self.data_path = data_path if data_path is not None else \
            config.data_path(is_evaluating=estimator_action.is_evaluate)
        self.shard_index = shard_index
        self.num_shards = num_shards
        self.repeat_endlessly = repeat_endlessly
        self.parse_chunk_lines = parse_chunk_lines
        # per-host batch override for multi-host runs
        self.batch_size_override = batch_size
        # epoch-count override (resume trains only the remaining budget)
        self.num_epochs_override = num_epochs
        # Emit EpochEnd markers at file-pass boundaries (training only).
        # With a bounded shuffle buffer the boundary is smeared by up to
        # `shuffle_buffer_size` lines — the same smear the reference's
        # `.repeat(epochs).shuffle(buffer)` pipeline has
        # (path_context_reader.py:134-139).
        self.yield_epoch_markers = yield_epoch_markers
        # Absolute index of the first epoch this reader will stream
        # (resumed runs pass their completed-epoch count): the shuffle
        # RNG is keyed per absolute epoch, so the resumed pass orders
        # its lines exactly as an uninterrupted run would have.
        self.start_epoch = start_epoch
        # Resume data cursor (training only): drop this host's share of
        # the first epoch's already-consumed POST-FILTER rows from the
        # epoch-keyed shuffled order — the text-reader counterpart of
        # PackedDataset.iter_batches(skip_rows=...), obeying the same
        # cursor laws (the resumed stream is exactly the uninterrupted
        # stream minus its first skip_rows rows; later epochs are
        # untouched). The facade rounds the cursor down to a global
        # batch multiple before it gets here.
        self.skip_rows = skip_rows

    # ------------------------------------------------------------------

    def process_input_rows(self, lines: Sequence[str]) -> RowBatch:
        """Single-shot parse used by predict (no filtering; reference:
        path_context_reader.py:96-107)."""
        return parse_context_lines(
            lines, self.vocabs, self.config.max_contexts,
            self.estimator_action, keep_strings=True)

    def __iter__(self) -> Iterator[RowBatch]:
        batch_size = self.batch_size_override or self.config.batch_size(
            is_evaluating=self.estimator_action.is_evaluate)
        if self.estimator_action.is_train:
            if self.repeat_endlessly:
                epochs = None
            elif self.num_epochs_override is not None:
                epochs = self.num_epochs_override
            else:
                epochs = self.config.num_train_epochs
            line_iter = self._shuffled_lines(epochs)
            yield from self._batched(
                line_iter, batch_size,
                skip_rows=self.skip_rows // max(self.num_shards, 1))
            return
        line_iter = _iter_file_lines(self.data_path, self.shard_index,
                                     self.num_shards,
                                     self.config.csv_buffer_size)
        yield from self._batched(line_iter, batch_size)

    # ------------------------------------------------------------------

    def _shuffled_lines(self, epochs: Optional[int]) -> Iterator:
        """Repeat + bounded shuffle buffer (reference semantics of
        `.repeat(epochs).shuffle(buffer)`, path_context_reader.py:134-139).
        Yields an EpochEnd marker after every file pass."""
        buf: List[str] = []
        buf_size = self.config.shuffle_buffer_size
        epoch = 0
        while epochs is None or epoch < epochs:
            rng = _epoch_shuffle_rng(self.config.seed,
                                     self.start_epoch + epoch)
            for line in _iter_file_lines(self.data_path, self.shard_index,
                                         self.num_shards,
                                         self.config.csv_buffer_size):
                if len(buf) < buf_size:
                    buf.append(line)
                    continue
                j = rng.randrange(buf_size)
                out, buf[j] = buf[j], line
                yield out
            epoch += 1
            if epochs is not None and epoch == epochs:
                # drain the buffer before the final marker
                rng.shuffle(buf)
                yield from buf
                buf = []
            yield EpochEnd(epoch)

    def _parse_chunk(self, chunk: List[str]) -> RowBatch:
        t0 = time.perf_counter()
        raw = parse_context_lines(chunk, self.vocabs, self.config.max_contexts,
                                  self.estimator_action)
        keep = row_filter_mask(raw, self.vocabs, self.estimator_action)
        out = _select_rows(raw, np.nonzero(keep)[0])
        dur = time.perf_counter() - t0
        _H_PARSE.observe(dur)
        _C_ROWS_READ.inc(len(chunk))
        _C_ROWS_DROPPED.inc(len(chunk) - out.target_index.shape[0])
        obs.default_tracer().maybe_record("data_parse_chunk", t0, dur)
        return out

    def _parsed_chunks(self, line_iter: Iterator) -> Iterator:
        """Yield filtered RowBatch chunks (and EpochEnd markers, in order)
        with up to `config.reader_num_workers` chunks parsed concurrently —
        the role of the reference's `num_parallel_calls=reader_num_workers`
        dataset map (path_context_reader.py:141-142). The native split+
        lookup core releases the GIL, so worker threads scale the hot
        parse; EpochEnd markers act as ordering barriers."""
        import collections
        from concurrent.futures import ThreadPoolExecutor

        workers = max(1, self.config.reader_num_workers)
        chunk: List[str] = []
        with ThreadPoolExecutor(max_workers=workers) as pool:
            inflight: collections.deque = collections.deque()
            for line in line_iter:
                if isinstance(line, EpochEnd):
                    # flush the partial chunk so every line of the pass is
                    # emitted before its boundary marker
                    if chunk:
                        inflight.append(pool.submit(self._parse_chunk, chunk))
                        chunk = []
                    while inflight:
                        yield inflight.popleft().result()
                    yield line
                    continue
                chunk.append(line)
                if len(chunk) >= self.parse_chunk_lines:
                    inflight.append(pool.submit(self._parse_chunk, chunk))
                    chunk = []
                    while len(inflight) > workers:
                        yield inflight.popleft().result()
            if chunk:
                inflight.append(pool.submit(self._parse_chunk, chunk))
            while inflight:
                yield inflight.popleft().result()

    def _batched(self, line_iter: Iterator, batch_size: int,
                 skip_rows: int = 0) -> Iterator[RowBatch]:
        pending: List[RowBatch] = []
        pending_rows = 0
        # Cursor resume: discard the first `skip_rows` POST-FILTER rows
        # of the stream — they are the rows the interrupted epoch
        # already consumed, in exactly this (epoch-keyed, deterministic)
        # order. Applies to the FIRST streamed epoch only; the boundary
        # marker clears any leftover skip (a stale over-long cursor
        # must not eat into the next epoch's rows).
        remaining_skip = max(int(skip_rows), 0)

        def pop_batches() -> Iterator[RowBatch]:
            nonlocal pending, pending_rows
            while pending_rows >= batch_size:
                merged = _concat_batches(pending)
                pending = []
                pending_rows = 0
                n = merged.target_index.shape[0]
                for start in range(0, n - batch_size + 1, batch_size):
                    yield _select_rows(merged, np.arange(start, start + batch_size))
                tail = n % batch_size
                if tail:
                    pending = [_select_rows(merged, np.arange(n - tail, n))]
                    pending_rows = tail

        for item in self._parsed_chunks(line_iter):
            if isinstance(item, EpochEnd):
                remaining_skip = 0
                yield from pop_batches()
                if self.yield_epoch_markers:
                    yield item
                continue
            if remaining_skip:
                n = item.target_index.shape[0]
                if n <= remaining_skip:
                    remaining_skip -= n
                    continue
                item = _select_rows(item,
                                    np.arange(remaining_skip, n))
                remaining_skip = 0
            if item.target_index.shape[0]:
                pending.append(item)
                pending_rows += item.target_index.shape[0]
            yield from pop_batches()
        yield from pop_batches()
        if pending_rows:
            merged = _concat_batches(pending)
            if self.estimator_action.is_train:
                return  # drop ragged tail (see class docstring)
            yield _pad_rows(merged, batch_size)


def _concat_batches(batches: List[RowBatch]) -> RowBatch:
    if len(batches) == 1:
        return batches[0]

    def cat(name):
        vals = [getattr(b, name) for b in batches]
        if vals[0] is None:
            return None
        if isinstance(vals[0], list):
            return [x for v in vals for x in v]
        return np.concatenate(vals, axis=0)

    return RowBatch(**{f.name: cat(f.name) for f in dataclasses.fields(RowBatch)})
