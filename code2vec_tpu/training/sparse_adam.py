"""Touched-rows (lazy) Adam for the giant embedding tables.

The reference's TF1 `AdamOptimizer` applies *sparse* slot updates for
embedding gathers (reference: tensorflow_model.py:231 + TF sparse-apply
semantics): only rows referenced by the batch are touched. A dense optax
Adam update instead streams all ~285M token+path parameters (plus both
moments) through HBM every step — the single largest cost of the flagship
step. This module restores the sparse behavior TPU-natively:

- gradients are taken w.r.t. the *gathered rows* (B*M rows, not the
  (V, d) table), so no dense-shaped gradient ever materializes;
- duplicate ids within the batch are combined by sort + segment-sum
  (Adam is nonlinear in the gradient, so duplicates must be summed
  before the moment update, matching what a dense update of the
  scatter-added gradient would see);
- the table and both moments are updated by scatter-add of *deltas*
  (non-representative duplicate positions contribute exact zeros, so
  scatter ordering is irrelevant).

Semantics are **lazy Adam** (TF's `tf.train.AdamOptimizer._apply_sparse`
family): moments of untouched rows do not decay, and untouched rows
receive no momentum-driven update. This deviates from dense Adam only on
rows absent from the batch; the first update of any row from zero-init
moments is bit-identical (see tests/test_sparse_adam.py). Bias
correction uses the global step count, like TF.

Update math mirrors optax.scale_by_adam + scale_by_learning_rate so the
dense and sparse paths agree on touched rows:

  mu' = b1*mu + (1-b1)*g;  nu' = b2*nu + (1-b2)*g^2
  p' = p - lr * (mu'/(1-b1^t)) / (sqrt(nu'/(1-b2^t)) + eps)
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import flax.struct
import jax
import jax.numpy as jnp


@flax.struct.dataclass
class RowAdamSlots:
    """Adam moments for one embedding table (same leading shape)."""
    mu: jax.Array
    nu: jax.Array


@flax.struct.dataclass
class HybridOptState:
    """Optimizer state: optax state over the dense subtree + per-table
    row-sparse Adam slots for the embedding tables."""
    dense: Any
    slots: Dict[str, RowAdamSlots]


def init_slots(table: jax.Array, mu_dtype=jnp.float32) -> RowAdamSlots:
    return RowAdamSlots(
        mu=jnp.zeros(table.shape, dtype=mu_dtype),
        nu=jnp.zeros(table.shape, dtype=jnp.float32))


def combine_duplicate_rows(ids: jax.Array, grads: jax.Array
                           ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Sort ids and sum gradient rows of duplicates onto the first
    occurrence. Returns (ids_sorted, summed_grads, is_representative):
    non-representative positions carry an exactly-zero gradient row.

    Static shapes throughout (jit/XLA friendly): output length equals
    input length; dedup is expressed with a segment-sum, not jnp.unique.
    """
    n = ids.shape[0]
    order = jnp.argsort(ids)
    ids_s = jnp.take(ids, order)
    g_s = jnp.take(grads, order, axis=0)
    first = jnp.concatenate(
        [jnp.ones((1,), jnp.bool_), ids_s[1:] != ids_s[:-1]])
    seg = jnp.cumsum(first.astype(jnp.int32)) - 1
    g_sum = jax.ops.segment_sum(g_s, seg, num_segments=n)
    g_u = jnp.where(first[:, None], jnp.take(g_sum, seg, axis=0),
                    jnp.zeros_like(g_s))
    return ids_s, g_u, first


def sparse_adam_rows(table: jax.Array, slots: RowAdamSlots,
                     ids: jax.Array, grads: jax.Array, *,
                     t: jax.Array, lr: float, b1: float, b2: float,
                     eps: float) -> Tuple[jax.Array, RowAdamSlots]:
    """Lazy-Adam-update the rows of `table` named by `ids` (duplicates
    allowed) with gradient rows `grads`; `t` is the 1-based global step.

    Ids may lie outside [0, table.shape[0]) — such positions are dropped
    (used by the tensor-parallel path, where each shard owns a row range
    and remaps foreign ids past the end of its local shard).
    """
    ids = ids.astype(jnp.int32)
    ids_s, g_u, first = combine_duplicate_rows(ids, grads)

    # Reads clamp (out-of-range rows are read but their delta is dropped
    # at the scatter below); writes drop out-of-range indices.
    mu_rows = jnp.take(slots.mu, ids_s, axis=0, mode="clip").astype(jnp.float32)
    nu_rows = jnp.take(slots.nu, ids_s, axis=0, mode="clip")

    new_mu = b1 * mu_rows + (1.0 - b1) * g_u
    new_nu = b2 * nu_rows + (1.0 - b2) * (g_u * g_u)
    tf32 = t.astype(jnp.float32)
    mu_hat = new_mu / (1.0 - jnp.power(b1, tf32))
    nu_hat = new_nu / (1.0 - jnp.power(b2, tf32))
    delta_p = (-lr * mu_hat / (jnp.sqrt(nu_hat) + eps)).astype(table.dtype)

    fm = first[:, None]
    zeros = jnp.zeros_like(delta_p)
    table = table.at[ids_s].add(jnp.where(fm, delta_p, zeros), mode="drop")
    # The scatter must be an `add` (duplicate ids: non-representatives
    # carry zero), but the value that ends up stored should equal what
    # the dense optimizer stores: cast(new_mu, mu_dtype). So compute the
    # delta against the *storage-dtype* target: old + (target - old) is
    # exact whenever target - old is representable (common for nearby
    # bf16 values), and within 1 ulp otherwise — no compounding drift
    # from rounding an f32 delta, which is what accumulating
    # bf16(new_mu - mu_rows) per step would produce.
    mu_target = new_mu.astype(slots.mu.dtype).astype(jnp.float32)
    mu = slots.mu.at[ids_s].add(
        jnp.where(fm, mu_target - mu_rows, jnp.zeros_like(new_mu))
        .astype(slots.mu.dtype), mode="drop")
    nu = slots.nu.at[ids_s].add(
        jnp.where(fm, new_nu - nu_rows, jnp.zeros_like(new_nu)),
        mode="drop")
    return table, RowAdamSlots(mu=mu, nu=nu)
