"""Training state: params + Adam state, with mesh-sharded initialization.

The reference's trainable state is four TF variables plus Adam slots
managed by the session (tensorflow_model.py:204-231); here it's an
explicit pytree initialized directly into its target sharding via
jit(out_shardings=...) so a pod-scale model never materializes unsharded
on one host.
"""

from __future__ import annotations

import functools
from typing import Any, Optional

import flax.struct
import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh

from code2vec_tpu.models.code2vec import Code2VecModule
from code2vec_tpu.parallel import mesh as mesh_lib
from code2vec_tpu.training.sparse_adam import HybridOptState, init_slots


@flax.struct.dataclass
class TrainState:
    step: jax.Array         # scalar int32
    params: Any             # flax param dict
    opt_state: Any          # optax state, or HybridOptState (sparse mode)


# Tables updated by the touched-rows sparse Adam path
# (training/sparse_adam.py) when config.use_sparse_embedding_update.
# target_embedding stays dense: its gradient flows through the full
# softmax, so every row is touched every step.
SPARSE_PARAM_NAMES = ("token_embedding", "path_embedding")


def split_sparse_dense(params):
    """Partition a flax param dict into (sparse tables, dense rest)."""
    sparse = {k: v for k, v in params.items() if k in SPARSE_PARAM_NAMES}
    dense = {k: v for k, v in params.items() if k not in SPARSE_PARAM_NAMES}
    return sparse, dense


def uses_sparse_update(config) -> bool:
    return bool(config is not None
                and getattr(config, "use_sparse_embedding_update", False))


# optax renamed safe_int32_increment -> safe_increment; the image may
# carry either vintage. Resolved at import so an optax with neither
# name fails HERE with the real attribute error, not as a NoneType call
# deep inside the jitted update.
try:
    _safe_increment = optax.safe_increment
except AttributeError:
    _safe_increment = optax.safe_int32_increment


def _scale_by_adam_nu_dtype(b1: float, b2: float, eps: float,
                            mu_dtype, nu_dtype) -> optax.GradientTransformation:
    """optax.scale_by_adam with a storage dtype for the SECOND moment as
    well (optax only exposes mu_dtype). Math is performed in the
    gradient's dtype (f32 here); only storage is cast — exactly how
    optax handles mu. Used when config.adam_nu_dtype != float32."""
    mu_dtype, nu_dtype = jnp.dtype(mu_dtype), jnp.dtype(nu_dtype)

    def init_fn(params):
        return optax.ScaleByAdamState(
            count=jnp.zeros([], jnp.int32),
            mu=jax.tree.map(lambda p: jnp.zeros_like(p, dtype=mu_dtype),
                            params),
            nu=jax.tree.map(lambda p: jnp.zeros_like(p, dtype=nu_dtype),
                            params))

    def update_fn(updates, state, params=None):
        del params
        count = _safe_increment(state.count)
        mu = jax.tree.map(
            lambda g, m: b1 * m.astype(g.dtype) + (1.0 - b1) * g,
            updates, state.mu)
        nu = jax.tree.map(
            lambda g, n: b2 * n.astype(g.dtype) + (1.0 - b2) * (g * g),
            updates, state.nu)
        b1c = 1.0 - b1 ** count.astype(jnp.float32)
        b2c = 1.0 - b2 ** count.astype(jnp.float32)
        new_updates = jax.tree.map(
            lambda m, n: (m / b1c) / (jnp.sqrt(n / b2c) + eps), mu, nu)
        return new_updates, optax.ScaleByAdamState(
            count=count,
            mu=jax.tree.map(lambda m: m.astype(mu_dtype), mu),
            nu=jax.tree.map(lambda n: n.astype(nu_dtype), nu))

    return optax.GradientTransformation(init_fn, update_fn)


def make_optimizer(config) -> optax.GradientTransformation:
    # reference uses tf.compat.v1.train.AdamOptimizer() defaults
    # (tensorflow_model.py:231): lr 1e-3, b1 .9, b2 .999, eps 1e-8.
    # mu/nu storage dtypes are throughput knobs (config.adam_mu_dtype /
    # config.adam_nu_dtype); plain optax.adam whenever nu stays f32, so
    # the default path is bit-identical to stock optax.
    nu_dtype = jnp.dtype(getattr(config, "adam_nu_dtype", "float32"))
    if nu_dtype == jnp.float32:
        return optax.adam(
            learning_rate=config.learning_rate,
            b1=config.adam_beta1, b2=config.adam_beta2, eps=config.adam_eps,
            mu_dtype=jnp.dtype(config.adam_mu_dtype))
    return optax.chain(
        _scale_by_adam_nu_dtype(
            b1=config.adam_beta1, b2=config.adam_beta2, eps=config.adam_eps,
            mu_dtype=jnp.dtype(config.adam_mu_dtype), nu_dtype=nu_dtype),
        optax.scale(-config.learning_rate))


def dropout_rng(config, salt: int = 2) -> jax.Array:
    """Per-run dropout key using the configured PRNG implementation (the
    hardware `rbg` generator by default — see config.dropout_prng_impl)."""
    return jax.random.key(config.seed + salt, impl=config.dropout_prng_impl)


def init_params(module: Code2VecModule, rng: jax.Array):
    """Initialize the param dict with throwaway token shapes (params do not
    depend on batch shapes)."""
    dummy = jnp.zeros((1, 1), dtype=jnp.int32)
    dummy_mask = jnp.zeros((1, 1), dtype=jnp.float32)
    variables = module.init({"params": rng}, dummy, dummy, dummy, dummy_mask)
    return variables["params"]


def state_spec_tree(state: Any):
    """PartitionSpec tree for a TrainState (params + optimizer slots follow
    the same layout; the Adam counter and `step` are replicated)."""
    return mesh_lib.tree_param_specs(state)


def create_train_state(
    module: Code2VecModule,
    optimizer: optax.GradientTransformation,
    rng: jax.Array,
    mesh: Optional[Mesh] = None,
    config=None,
) -> TrainState:
    """Build a TrainState; with a mesh, every leaf is created directly into
    its NamedSharding (no host-side full materialization).

    With `config.use_sparse_embedding_update`, `optimizer` covers only the
    dense subtree and the token/path tables get RowAdamSlots."""
    sparse = uses_sparse_update(config)
    mu_dtype = (jnp.dtype(config.adam_mu_dtype) if sparse else None)

    def init_fn(rng):
        params = init_params(module, rng)
        if sparse:
            sparse_params, dense_params = split_sparse_dense(params)
            opt_state = HybridOptState(
                dense=optimizer.init(dense_params),
                slots={name: init_slots(table, mu_dtype)
                       for name, table in sparse_params.items()})
        else:
            opt_state = optimizer.init(params)
        return TrainState(
            step=jnp.zeros((), dtype=jnp.int32),
            params=params,
            opt_state=opt_state)

    if mesh is None:
        return jax.jit(init_fn)(rng)

    abstract = jax.eval_shape(init_fn, rng)
    shardings = mesh_lib.shardings(mesh, state_spec_tree(abstract))
    return jax.jit(init_fn, out_shardings=shardings)(rng)


def num_params(state: TrainState) -> int:
    return sum(int(x.size) for x in jax.tree.leaves(state.params))
