"""Checkpointing via Orbax: trainable vs released artifacts + vocab sidecar.

Reference behavior being reproduced (TPU-natively, not with TF Savers):
- per-epoch checkpoints `<save>_iter<N>` with `max_to_keep` rotation
  (tensorflow_model.py:57, 90-94; config.py:57);
- vocabs stored next to the model as `dictionaries.bin`
  (model_base.py:102-109, config.py:191-194);
- `--release` strips optimizer state for a ~3x smaller inference-only
  artifact (tensorflow_model.py:131-135, keras_model.py:230-234) — here a
  released checkpoint simply omits `opt_state`;
- resume-for-training requires the full artifact (keras_model.py:245-262).

Orbax gives async, sharded, multi-host-safe saves (SURVEY.md §5 plan:
preemption-tolerant checkpointing for TPU pods).

Crash-atomic commit protocol (no reference analog — the reference loses
work on any failure; here the preemption path itself must survive a kill
landing mid-save, since a grace window that expires during `save_model`
would otherwise leave a half-written `_iter<N>` directory that the next
`--load` resume picks by name and dies on):

1. every file is written into a `<base>.tmp-<pid>` staging directory;
2. a manifest (file list + sizes, sha256 of `dictionaries.bin` and the
   meta JSON, an Orbax-completion marker) is recorded LAST, after
   `wait_until_finished`, so its presence certifies the whole artifact;
3. the staging dir is `os.rename`d into place — atomic on POSIX, so a
   crash leaves either the old artifact or the new one, never a blend;
4. orphaned staging dirs from killed saves are swept by checkpoint
   rotation (model_facade._rotate_epoch_checkpoints).

Restore is integrity-verified: `verify_checkpoint` re-checks the
manifest, `latest_valid_checkpoint` walks newest -> oldest past any
candidate that fails it, and `load_model` verifies before handing the
directory to Orbax so truncation fails fast with a named file instead of
an opaque pytree error deep in the restore.
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
from typing import Callable, Optional

import numpy as np
import orbax.checkpoint as ocp

from code2vec_tpu import obs
from code2vec_tpu.training.state import TrainState
from code2vec_tpu.utils.faults import fault_point

_STATE_DIR = "state"
_META_NAME = "code2vec_meta.json"
MANIFEST_NAME = "code2vec_manifest.json"
MANIFEST_FORMAT = 1
RELEASED_SUFFIX = ".release"
# Commit-protocol working dirs: `.tmp-<pid>` is the staging dir a save
# builds in; `.old-<pid>` briefly holds the previous artifact while a
# same-path overwrite swaps the new one in.
STAGING_INFIX = ".tmp-"
BACKUP_INFIX = ".old-"

# Small files worth a full content hash in the manifest at save time.
# The Orbax state files are covered by existence+size in the commit-path
# manifest — hashing multi-GB shards before the commit would dominate
# checkpoint time, and Orbax already checksums its own payloads
# internally. Opt-in `config.checkpoint_hash_content` adds full-content
# hashes for everything AFTER the commit (`hash_artifact_content`),
# verified on resume.
_HASHED_FILES = ("dictionaries.bin", _META_NAME)


class CheckpointIntegrityError(RuntimeError):
    """An artifact failed its manifest/structure check. The message names
    the offending file so a truncated/corrupt checkpoint is diagnosable
    without spelunking Orbax internals."""


def _abs(path: str) -> str:
    return os.path.abspath(path)


def is_staging_path(path: str) -> bool:
    """True for commit-protocol working dirs (`<base>.tmp-<pid>` staging,
    `<base>.old-<pid>` overwrite backups) that must never be treated as
    artifacts."""
    name = os.path.basename(path.rstrip(os.sep))
    return STAGING_INFIX in name or BACKUP_INFIX in name


def staging_owner_alive(path: str) -> bool:
    """Does the process that created this staging/backup dir still run?
    Used by the sweeper so a concurrent save's in-flight staging dir is
    left alone while leftovers of killed saves are reclaimed. Unparseable
    names are treated as orphaned."""
    name = os.path.basename(path.rstrip(os.sep))
    for infix in (STAGING_INFIX, BACKUP_INFIX):
        if infix in name:
            tail = name.rsplit(infix, 1)[1]
            break
    else:
        return False
    try:
        pid = int(tail)
    except ValueError:
        return False
    if pid == os.getpid():
        return True
    try:
        os.kill(pid, 0)
        return True
    except ProcessLookupError:
        return False
    except PermissionError:
        return True  # exists, owned by another user


def parse_iter_name(path: str):
    """Parse a `<base>_iter<N>[_preempt]` artifact path into
    (epoch, is_preempt), or None if the tail is not of that form. Single
    source of truth for the epoch-checkpoint naming convention (written
    by model_facade's save_fn; consumed by rotation and resume). Staging
    dirs (`..._iter<N>.tmp-<pid>`) parse as None, so every consumer
    ignores them for free."""
    if "_iter" not in path:
        return None
    tail = path.rsplit("_iter", 1)[1]
    preempt = tail.endswith("_preempt")
    if preempt:
        tail = tail[: -len("_preempt")]
    try:
        return int(tail), preempt
    except ValueError:
        return None


def _sha256_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def hash_artifact_content(base: str, max_threads: int = 4) -> dict:
    """Record a full-content sha256 for EVERY manifest-listed file —
    including the multi-GB Orbax shards the manifest otherwise only
    size-checks — and rewrite the manifest atomically (tmp + rename).

    Meant to run AFTER the atomic commit (`config.checkpoint_hash_content`
    in save_model), so the hashing of large shards never extends the
    window in which a kill loses the save: a crash mid-hash just leaves a
    valid artifact without content hashes. Incremental 1 MB chunks on a
    thread pool (hashlib releases the GIL, so hashing overlaps I/O and
    scales past one core). Returns the updated manifest."""
    from concurrent.futures import ThreadPoolExecutor

    with obs.span("checkpoint_content_hash",
                  hist=obs.histogram(
                      "checkpoint_content_hash_seconds",
                      "post-commit full-content sha256 of one artifact")):
        manifest_path = os.path.join(base, MANIFEST_NAME)
        with open(manifest_path) as f:
            manifest = json.load(f)
        rels = sorted(manifest["files"])
        with ThreadPoolExecutor(max_workers=max_threads) as pool:
            digests = pool.map(
                lambda rel: _sha256_file(os.path.join(base, rel)), rels)
        for rel, digest in zip(rels, digests):
            manifest["files"][rel]["content_sha256"] = digest
        manifest["content_hashed"] = True
        tmp = manifest_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(manifest, f, indent=2)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, manifest_path)
        return manifest


def _fsync_dir(path: str) -> None:
    """Durably record a directory entry (the rename commit). Best-effort:
    some filesystems refuse O_RDONLY fsync on directories."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _write_manifest(base: str, epoch: int, released: bool) -> None:
    """Record every file in the (staged) artifact with its size, plus
    content hashes for the small sidecars. Written last: its presence is
    the Orbax-completion marker — `save_model` only writes it after
    `wait_until_finished`, so a manifest-bearing directory is a fully
    flushed artifact."""
    files = {}
    for root, _dirs, names in os.walk(base):
        for name in names:
            p = os.path.join(root, name)
            rel = os.path.relpath(p, base)
            if rel == MANIFEST_NAME:
                continue
            entry = {"size": os.path.getsize(p)}
            if rel in _HASHED_FILES:
                entry["sha256"] = _sha256_file(p)
            files[rel] = entry
    manifest = {
        "format": MANIFEST_FORMAT,
        "epoch": epoch,
        "released": released,
        "orbax_complete": True,
        "files": files,
    }
    path = os.path.join(base, MANIFEST_NAME)
    with open(path, "w") as f:
        json.dump(manifest, f, indent=2)
        f.flush()
        os.fsync(f.fileno())


def _commit_staging(staging: str, base: str) -> None:
    """Atomically promote a fully written staging dir to the final path.
    Overwrites swap through a `.old-<pid>` backup so there is never a
    moment with no artifact at `base`; a kill mid-swap leaves the backup
    for the sweeper and the verifier-guided fallback to sort out."""
    fault_point("checkpoint_commit")
    if os.path.isdir(base):
        backup = f"{base}{BACKUP_INFIX}{os.getpid()}"
        if os.path.isdir(backup):
            shutil.rmtree(backup)
        os.rename(base, backup)
        # A kill in this window leaves NOTHING at `base` but two intact
        # copies (`.tmp-` new, `.old-` previous); the sweeper promotes
        # whichever verifies (reclaim_orphan) instead of deleting them.
        fault_point("checkpoint_swap")
        os.rename(staging, base)
        shutil.rmtree(backup, ignore_errors=True)
    else:
        os.rename(staging, base)
    _fsync_dir(os.path.dirname(base) or ".")


def reclaim_orphan(path: str,
                   log: Optional[Callable[[str], None]] = None) -> str:
    """Reclaim one orphaned commit-protocol dir (a `.tmp-`/`.old-` whose
    owning process is gone). If the final name is unoccupied and the
    orphan passes verification — the kill-between-swap-renames window
    leaves exactly that — it is PROMOTED back via rename (a complete
    artifact must never be deleted while its slot sits empty); anything
    else is removed. Returns "promoted" or "removed"."""
    dirpart, name = os.path.split(os.path.abspath(path.rstrip(os.sep)))
    for infix in (STAGING_INFIX, BACKUP_INFIX):
        if infix in name:
            base = os.path.join(dirpart, name.rsplit(infix, 1)[0])
            break
    else:
        return "removed"  # not a commit-protocol dir; caller filtered wrong
    if not os.path.exists(base):
        try:
            verify_checkpoint(path)
        except CheckpointIntegrityError:
            pass
        else:
            os.rename(path, base)
            _fsync_dir(dirpart)
            if log is not None:
                log(f"Promoted orphaned-but-complete checkpoint {path} "
                    f"back to {base} (save was killed mid-commit)")
            return "promoted"
    shutil.rmtree(path, ignore_errors=True)
    return "removed"


def verify_checkpoint(model_path: str, check_content: bool = False) -> dict:
    """Probe an artifact against its manifest; returns the parsed meta on
    success, raises CheckpointIntegrityError naming the first offending
    file otherwise. Cheap by design (stat per file, hash only the small
    sidecars), so resume can probe a fallback chain and rotation can
    re-check candidates without meaningful cost.

    `check_content=True` additionally re-hashes every file carrying a
    post-commit `content_sha256` (written when the save ran with
    `checkpoint_hash_content`) — the resume path's deep probe; the
    rotation/fallback walks keep the cheap default.

    Pre-manifest (legacy) artifacts get a structural probe instead:
    required files present, meta parseable, Orbax state dir non-empty —
    enough to reject the blatant half-writes the old layout could leave.
    """
    with obs.span("checkpoint_verify",
                  hist=obs.histogram("checkpoint_verify_seconds",
                                     "manifest probe of one artifact")):
        try:
            return _verify_checkpoint_inner(model_path, check_content)
        except CheckpointIntegrityError:
            obs.counter("checkpoint_verify_failures_total",
                        "artifacts that failed their integrity check "
                        "(resume fallback walked past them)").inc()
            raise


def _verify_checkpoint_inner(model_path: str,
                             check_content: bool = False) -> dict:
    base = _abs(model_path)
    if not os.path.isdir(base):
        raise CheckpointIntegrityError(f"{base}: not a directory")
    manifest_path = os.path.join(base, MANIFEST_NAME)
    if not os.path.isfile(manifest_path):
        return _verify_legacy(base)
    try:
        with open(manifest_path) as f:
            manifest = json.load(f)
    except (OSError, ValueError) as e:
        raise CheckpointIntegrityError(
            f"{manifest_path}: unreadable or corrupt manifest ({e})")
    if not isinstance(manifest, dict) or not isinstance(
            manifest.get("files"), dict):
        raise CheckpointIntegrityError(
            f"{manifest_path}: malformed manifest (no file table)")
    if not manifest.get("orbax_complete"):
        raise CheckpointIntegrityError(
            f"{manifest_path}: Orbax completion marker missing — the save "
            f"was interrupted before wait_until_finished")
    for rel, entry in manifest["files"].items():
        p = os.path.join(base, rel)
        if not os.path.isfile(p):
            raise CheckpointIntegrityError(f"{p}: listed in manifest but missing")
        try:
            size = os.path.getsize(p)
            if size != entry.get("size"):
                raise CheckpointIntegrityError(
                    f"{p}: size {size} != manifest size {entry.get('size')} "
                    f"(truncated or partially written)")
            want_hash = entry.get("sha256")
            content_hash = (entry.get("content_sha256") if check_content
                            else None)
            if want_hash or content_hash:
                digest = _sha256_file(p)  # one pass serves both checks
                if want_hash and digest != want_hash:
                    raise CheckpointIntegrityError(
                        f"{p}: sha256 mismatch against manifest (corrupt)")
                if content_hash and digest != content_hash:
                    raise CheckpointIntegrityError(
                        f"{p}: content sha256 mismatch against manifest "
                        f"(bit-rot or size-preserving corruption)")
        except OSError as e:
            # A file that vanishes BETWEEN the isfile() probe and the
            # stat/hash is an artifact being swapped underneath us — on a
            # multi-host pod every host runs the same commit (staging
            # rename + backup swap) on the same final path, so a peer's
            # commit window can briefly empty the directory a rotation
            # probe is walking (the cross-host save barrier is a known
            # ROADMAP item). Degrade to the integrity error the callers
            # are built to tolerate (fallback walks skip the candidate;
            # resume retries older) instead of crashing the trainer.
            raise CheckpointIntegrityError(
                f"{p}: vanished or became unreadable mid-probe ({e}) — "
                f"concurrent commit/rotation by another process")
    return _load_meta_checked(base)


def _verify_legacy(base: str) -> dict:
    for rel in ("dictionaries.bin", _META_NAME):
        if not os.path.isfile(os.path.join(base, rel)):
            raise CheckpointIntegrityError(
                f"{os.path.join(base, rel)}: required file missing "
                f"(no manifest to consult; pre-manifest artifact)")
    meta = _load_meta_checked(base)
    state_dir = os.path.join(base, _STATE_DIR)
    if not os.path.isdir(state_dir) or not os.listdir(state_dir):
        raise CheckpointIntegrityError(
            f"{state_dir}: Orbax state directory missing or empty")
    return meta


def _load_meta_checked(base: str) -> dict:
    meta_path = os.path.join(base, _META_NAME)
    try:
        with open(meta_path) as f:
            return json.load(f)
    except (OSError, ValueError) as e:
        raise CheckpointIntegrityError(
            f"{meta_path}: unreadable or corrupt meta ({e})")


def latest_valid_checkpoint(save_base: str,
                            log: Optional[Callable[[str], None]] = None):
    """Newest `<save_base>_iter<N>[_preempt]` artifact that PASSES its
    integrity check (None if no candidate does). Walks newest -> oldest
    past corrupt/partial artifacts, logging each skip, so a save killed
    mid-write (or a disk that ate a file) costs at most the epochs since
    the last valid artifact instead of the whole run.

    At equal N the preemption artifact wins: it was written mid-epoch
    N+1, so its params are strictly more trained than the clean
    end-of-epoch-N save."""
    import glob
    candidates = []  # ((epoch, is_preempt), path)
    for p in glob.glob(save_base + "_iter*"):
        parsed = parse_iter_name(p)
        if parsed is None:
            continue
        candidates.append((parsed, p))
    for _parsed, path in sorted(candidates, reverse=True):
        try:
            verify_checkpoint(path)
            return path
        except CheckpointIntegrityError as e:
            if log is not None:
                log(f"Skipping corrupt/partial checkpoint {path}: {e}")
    return None


# Back-compat name: the pre-manifest API returned the newest artifact by
# name alone; every caller now gets the verified walk.
latest_checkpoint = latest_valid_checkpoint


def resolve_load_path(model_load_path: str,
                      log: Optional[Callable[[str], None]] = None) -> str:
    """Resolve a `--load` argument: a concrete artifact directory is
    returned as-is; anything else is treated as a save base and resolved
    to its newest VALID `_iter<N>` artifact, so resuming after a crash
    never requires the operator to guess which directory survived."""
    base = _abs(model_load_path)
    if os.path.isdir(base) and (
            os.path.isfile(os.path.join(base, _META_NAME))
            or os.path.isfile(os.path.join(base, MANIFEST_NAME))):
        return base
    found = latest_valid_checkpoint(base, log=log)
    return found if found is not None else base


def save_model(model_save_path: str, state: TrainState, vocabs, config,
               epoch: int = 0, released: bool = False) -> str:
    """Save a standalone model artifact at `<model_save_path>` (a directory
    is created): Orbax state + `dictionaries.bin` + config meta. Mirrors
    `Code2VecModelBase.save` (model_base.py:102-109).

    Crash-atomic: everything lands in a `.tmp-<pid>` staging dir, the
    manifest is recorded last, and the staging dir is renamed into place
    (see the commit protocol in the module docstring). The `save` fault
    points between the steps are inert in production and let
    tests/test_chaos.py kill the save at every interesting boundary."""
    with obs.span("checkpoint_save",
                  hist=obs.histogram("checkpoint_save_seconds",
                                     "full save: stage + flush + commit")):
        out = _save_model_inner(model_save_path, state, vocabs, config,
                                epoch, released)
    obs.counter("checkpoint_saves_total",
                "committed checkpoint artifacts").inc()
    obs.gauge("checkpoint_last_save_unixtime",
              "wall clock of the last committed save").set_to_current_time()
    obs.gauge("checkpoint_last_save_epoch",
              "epoch recorded in the last committed save").set(epoch)
    return out


def _save_model_inner(model_save_path: str, state: TrainState, vocabs,
                      config, epoch: int, released: bool) -> str:
    base = _abs(model_save_path) + (RELEASED_SUFFIX if released else "")
    staging = f"{base}{STAGING_INFIX}{os.getpid()}"
    if os.path.isdir(staging):
        shutil.rmtree(staging)  # leftover from a failed save by this pid
    os.makedirs(staging)
    fault_point("save")   # 1: staging created, nothing written
    vocabs.save(os.path.join(staging, "dictionaries.bin"))
    fault_point("save")   # 2: vocab written, meta missing
    with open(os.path.join(staging, _META_NAME), "w") as f:
        json.dump({
            "released": released,
            "epoch": epoch,
            "step": int(np.asarray(state.step)),
            "token_vocab_size": vocabs.token_vocab.size,
            "path_vocab_size": vocabs.path_vocab.size,
            "target_vocab_size": vocabs.target_vocab.size,
            "token_embeddings_size": config.token_embeddings_size,
            "path_embeddings_size": config.path_embeddings_size,
            "separate_oov_and_pad": config.separate_oov_and_pad,
            # opt_state pytree structure depends on the update mode;
            # recorded so a mode mismatch fails with a clear error at
            # restore time instead of an opaque Orbax structure mismatch.
            "use_sparse_embedding_update": bool(
                getattr(config, "use_sparse_embedding_update", False)),
            # Adam moment dtypes shape the opt_state arrays; a restore
            # into a template with different dtypes can error or silently
            # cast depending on the Orbax version, so they're recorded
            # and checked like the sparse-mode flag above.
            "adam_mu_dtype": str(getattr(config, "adam_mu_dtype", "float32")),
            "adam_nu_dtype": str(getattr(config, "adam_nu_dtype", "float32")),
        }, f, indent=2)
    fault_point("save")   # 3: meta written, Orbax state missing
    with obs.span("checkpoint_orbax_flush",
                  hist=obs.histogram(
                      "checkpoint_orbax_flush_seconds",
                      "Orbax save + wait_until_finished (the bulk bytes)")):
        ckptr = ocp.StandardCheckpointer()
        target = {"params": state.params, "step": state.step}
        if not released:
            target["opt_state"] = state.opt_state
        state_dir = os.path.join(staging, _STATE_DIR)
        ckptr.save(state_dir, target, force=True)
        ckptr.wait_until_finished()
        ckptr.close()
    fault_point("save")   # 4: Orbax flushed, manifest missing
    _write_manifest(staging, epoch, released)
    fault_point("save")   # 5: fully staged, not yet committed
    _commit_staging(staging, base)
    if getattr(config, "checkpoint_hash_content", False):
        # Post-commit by design: the artifact is already durable, so
        # hashing the multi-GB shards never widens the crash window —
        # a kill mid-hash leaves a valid artifact without content
        # hashes (which resume then simply doesn't check).
        try:
            hash_artifact_content(base)
        except OSError:
            # a peer host's commit swapped the artifact mid-hash (the
            # same race verify_checkpoint degrades gracefully); the
            # surviving copy is covered by its own writer's hash pass
            obs.counter(
                "checkpoint_content_hash_races_total",
                "post-commit hash passes abandoned because a peer "
                "swapped the artifact underneath them").inc()
    return base


def load_model_meta(model_load_path: str) -> dict:
    base = _abs(model_load_path)
    with open(os.path.join(base, _META_NAME)) as f:
        return json.load(f)


def load_model(model_load_path: str, state_like: TrainState,
               config=None, params_only: bool = False) -> TrainState:
    """Restore a standalone artifact saved by `save_model`. `state_like`
    provides structure/shardings; released artifacts keep `state_like`'s
    (fresh) optimizer state. `params_only` restores just params+step and
    never touches the saved optimizer state — the `--release` path, which
    must load artifacts regardless of their optimizer layout/dtypes (it
    is the advertised escape hatch for every optimizer-mismatch error
    below, so it cannot itself run those checks).

    The artifact is manifest-verified FIRST, so a truncated or
    half-written directory fails fast with the offending file named
    instead of surfacing as an opaque Orbax pytree error mid-restore.
    Resume is the deep probe: post-commit content hashes (saves made
    with `checkpoint_hash_content`) are re-checked here when present."""
    base = _abs(model_load_path)
    meta = verify_checkpoint(base, check_content=True)
    if params_only:
        template = {"params": state_like.params, "step": state_like.step}
        restore_args = ocp.checkpoint_utils.construct_restore_args(template)
        try:
            restore = ocp.args.PyTreeRestore(item=template,
                                             restore_args=restore_args,
                                             partial_restore=True)
        except TypeError:
            # orbax < 0.6 has no partial_restore kwarg; empty `transforms`
            # is that vintage's way to restore a subtree of the saved item
            # (drop the artifact's opt_state, keep params+step).
            restore = ocp.args.PyTreeRestore(item=template,
                                             restore_args=restore_args,
                                             transforms={})
        with ocp.PyTreeCheckpointer() as ckptr:
            restored = ckptr.restore(os.path.join(base, _STATE_DIR),
                                     args=restore)
        return TrainState(step=restored["step"], params=restored["params"],
                          opt_state=state_like.opt_state)
    if config is not None and not meta.get("released", False):
        saved_sparse = bool(meta.get("use_sparse_embedding_update", False))
        want_sparse = bool(getattr(config, "use_sparse_embedding_update",
                                   False))
        if saved_sparse != want_sparse:
            raise ValueError(
                f"{base} was saved with use_sparse_embedding_update="
                f"{saved_sparse} but this run has "
                f"use_sparse_embedding_update={want_sparse}; the optimizer "
                f"state layouts are incompatible. Either set the flag to "
                f"match, or `--release` the artifact first (a released "
                f"model carries no optimizer state and loads under either "
                f"mode).")
        for knob in ("adam_mu_dtype", "adam_nu_dtype"):
            saved = meta.get(knob)
            want = str(getattr(config, knob, "float32"))
            # artifacts predating this meta entry carry no record (the
            # default changed over time) — nothing to check against
            if saved is not None and saved != want:
                raise ValueError(
                    f"{base} was saved with {knob}={saved} but this run "
                    f"has {knob}={want}; the optimizer-moment dtypes "
                    f"differ and a restore would corrupt or miscast the "
                    f"moments. Pass --{knob} {saved} to resume this "
                    f"artifact, or `--release` it first (released models "
                    f"carry no optimizer state).")
    template = {"params": state_like.params, "step": state_like.step}
    if not meta.get("released", False):
        template["opt_state"] = state_like.opt_state
    ckptr = ocp.StandardCheckpointer()
    restored = ckptr.restore(os.path.join(base, _STATE_DIR), template)
    ckptr.close()
    return TrainState(
        step=restored["step"],
        params=restored["params"],
        opt_state=restored.get("opt_state", state_like.opt_state))


def release_model(model_load_path: str, model_save_path: Optional[str],
                  state_like: TrainState, vocabs, config) -> str:
    """Load a trainable artifact and re-save it weights-only
    (reference: tensorflow_model.py:131-135 saves `<load>.release`).
    Loads params-only: releasing discards the optimizer state, so a
    saved-vs-current optimizer layout/dtype mismatch must not block it."""
    state = load_model(model_load_path, state_like, params_only=True)
    out = model_save_path or model_load_path
    return save_model(out, state, vocabs, config, released=True)
