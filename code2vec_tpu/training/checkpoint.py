"""Checkpointing via Orbax: trainable vs released artifacts + vocab sidecar.

Reference behavior being reproduced (TPU-natively, not with TF Savers):
- per-epoch checkpoints `<save>_iter<N>` with `max_to_keep` rotation
  (tensorflow_model.py:57, 90-94; config.py:57);
- vocabs stored next to the model as `dictionaries.bin`
  (model_base.py:102-109, config.py:191-194);
- `--release` strips optimizer state for a ~3x smaller inference-only
  artifact (tensorflow_model.py:131-135, keras_model.py:230-234) — here a
  released checkpoint simply omits `opt_state`;
- resume-for-training requires the full artifact (keras_model.py:245-262).

Orbax gives async, sharded, multi-host-safe saves (SURVEY.md §5 plan:
preemption-tolerant checkpointing for TPU pods).
"""

from __future__ import annotations

import json
import os
from typing import Optional

import numpy as np
import orbax.checkpoint as ocp

from code2vec_tpu.training.state import TrainState

_STATE_DIR = "state"
_META_NAME = "code2vec_meta.json"
RELEASED_SUFFIX = ".release"


def _abs(path: str) -> str:
    return os.path.abspath(path)


def parse_iter_name(path: str):
    """Parse a `<base>_iter<N>[_preempt]` artifact path into
    (epoch, is_preempt), or None if the tail is not of that form. Single
    source of truth for the epoch-checkpoint naming convention (written
    by model_facade's save_fn; consumed by rotation and resume)."""
    if "_iter" not in path:
        return None
    tail = path.rsplit("_iter", 1)[1]
    preempt = tail.endswith("_preempt")
    if preempt:
        tail = tail[: -len("_preempt")]
    try:
        return int(tail), preempt
    except ValueError:
        return None


def latest_checkpoint(save_base: str):
    """Newest `<save_base>_iter<N>[_preempt]` artifact path (None if no
    artifacts exist). At equal N the preemption artifact wins: it was
    written mid-epoch N+1, so its params are strictly more trained than
    the clean end-of-epoch-N save."""
    import glob
    best = None  # ((epoch, is_preempt), path)
    for p in glob.glob(save_base + "_iter*"):
        parsed = parse_iter_name(p)
        if parsed is None:
            continue
        if best is None or parsed > best[0]:
            best = (parsed, p)
    return best[1] if best else None


def save_model(model_save_path: str, state: TrainState, vocabs, config,
               epoch: int = 0, released: bool = False) -> str:
    """Save a standalone model artifact at `<model_save_path>` (a directory
    is created): Orbax state + `dictionaries.bin` + config meta. Mirrors
    `Code2VecModelBase.save` (model_base.py:102-109)."""
    base = _abs(model_save_path) + (RELEASED_SUFFIX if released else "")
    os.makedirs(base, exist_ok=True)
    vocabs.save(os.path.join(base, "dictionaries.bin"))
    with open(os.path.join(base, _META_NAME), "w") as f:
        json.dump({
            "released": released,
            "epoch": epoch,
            "step": int(np.asarray(state.step)),
            "token_vocab_size": vocabs.token_vocab.size,
            "path_vocab_size": vocabs.path_vocab.size,
            "target_vocab_size": vocabs.target_vocab.size,
            "token_embeddings_size": config.token_embeddings_size,
            "path_embeddings_size": config.path_embeddings_size,
            "separate_oov_and_pad": config.separate_oov_and_pad,
            # opt_state pytree structure depends on the update mode;
            # recorded so a mode mismatch fails with a clear error at
            # restore time instead of an opaque Orbax structure mismatch.
            "use_sparse_embedding_update": bool(
                getattr(config, "use_sparse_embedding_update", False)),
            # Adam moment dtypes shape the opt_state arrays; a restore
            # into a template with different dtypes can error or silently
            # cast depending on the Orbax version, so they're recorded
            # and checked like the sparse-mode flag above.
            "adam_mu_dtype": str(getattr(config, "adam_mu_dtype", "float32")),
            "adam_nu_dtype": str(getattr(config, "adam_nu_dtype", "float32")),
        }, f, indent=2)
    ckptr = ocp.StandardCheckpointer()
    target = {"params": state.params, "step": state.step}
    if not released:
        target["opt_state"] = state.opt_state
    state_dir = os.path.join(base, _STATE_DIR)
    ckptr.save(state_dir, target, force=True)
    ckptr.wait_until_finished()
    ckptr.close()
    return base


def load_model_meta(model_load_path: str) -> dict:
    base = _abs(model_load_path)
    with open(os.path.join(base, _META_NAME)) as f:
        return json.load(f)


def load_model(model_load_path: str, state_like: TrainState,
               config=None, params_only: bool = False) -> TrainState:
    """Restore a standalone artifact saved by `save_model`. `state_like`
    provides structure/shardings; released artifacts keep `state_like`'s
    (fresh) optimizer state. `params_only` restores just params+step and
    never touches the saved optimizer state — the `--release` path, which
    must load artifacts regardless of their optimizer layout/dtypes (it
    is the advertised escape hatch for every optimizer-mismatch error
    below, so it cannot itself run those checks)."""
    base = _abs(model_load_path)
    meta = load_model_meta(base)
    if params_only:
        template = {"params": state_like.params, "step": state_like.step}
        restore_args = ocp.checkpoint_utils.construct_restore_args(template)
        with ocp.PyTreeCheckpointer() as ckptr:
            restored = ckptr.restore(
                os.path.join(base, _STATE_DIR),
                args=ocp.args.PyTreeRestore(item=template,
                                            restore_args=restore_args,
                                            partial_restore=True))
        return TrainState(step=restored["step"], params=restored["params"],
                          opt_state=state_like.opt_state)
    if config is not None and not meta.get("released", False):
        saved_sparse = bool(meta.get("use_sparse_embedding_update", False))
        want_sparse = bool(getattr(config, "use_sparse_embedding_update",
                                   False))
        if saved_sparse != want_sparse:
            raise ValueError(
                f"{base} was saved with use_sparse_embedding_update="
                f"{saved_sparse} but this run has "
                f"use_sparse_embedding_update={want_sparse}; the optimizer "
                f"state layouts are incompatible. Either set the flag to "
                f"match, or `--release` the artifact first (a released "
                f"model carries no optimizer state and loads under either "
                f"mode).")
        for knob in ("adam_mu_dtype", "adam_nu_dtype"):
            saved = meta.get(knob)
            want = str(getattr(config, knob, "float32"))
            # artifacts predating this meta entry carry no record (the
            # default changed over time) — nothing to check against
            if saved is not None and saved != want:
                raise ValueError(
                    f"{base} was saved with {knob}={saved} but this run "
                    f"has {knob}={want}; the optimizer-moment dtypes "
                    f"differ and a restore would corrupt or miscast the "
                    f"moments. Pass --{knob} {saved} to resume this "
                    f"artifact, or `--release` it first (released models "
                    f"carry no optimizer state).")
    template = {"params": state_like.params, "step": state_like.step}
    if not meta.get("released", False):
        template["opt_state"] = state_like.opt_state
    ckptr = ocp.StandardCheckpointer()
    restored = ckptr.restore(os.path.join(base, _STATE_DIR), template)
    ckptr.close()
    return TrainState(
        step=restored["step"],
        params=restored["params"],
        opt_state=restored.get("opt_state", state_like.opt_state))


def release_model(model_load_path: str, model_save_path: Optional[str],
                  state_like: TrainState, vocabs, config) -> str:
    """Load a trainable artifact and re-save it weights-only
    (reference: tensorflow_model.py:131-135 saves `<load>.release`).
    Loads params-only: releasing discards the optimizer state, so a
    saved-vs-current optimizer layout/dtype mismatch must not block it."""
    state = load_model(model_load_path, state_like, params_only=True)
    out = model_save_path or model_load_path
    return save_model(out, state, vocabs, config, released=True)
