"""Checkpointing via Orbax: trainable vs released artifacts + vocab sidecar.

Reference behavior being reproduced (TPU-natively, not with TF Savers):
- per-epoch checkpoints `<save>_iter<N>` with `max_to_keep` rotation
  (tensorflow_model.py:57, 90-94; config.py:57);
- vocabs stored next to the model as `dictionaries.bin`
  (model_base.py:102-109, config.py:191-194);
- `--release` strips optimizer state for a ~3x smaller inference-only
  artifact (tensorflow_model.py:131-135, keras_model.py:230-234) — here a
  released checkpoint simply omits `opt_state`;
- resume-for-training requires the full artifact (keras_model.py:245-262).

Orbax gives async, sharded, multi-host-safe saves (SURVEY.md §5 plan:
preemption-tolerant checkpointing for TPU pods).

Crash-atomic commit protocol (no reference analog — the reference loses
work on any failure; here the preemption path itself must survive a kill
landing mid-save, since a grace window that expires during `save_model`
would otherwise leave a half-written `_iter<N>` directory that the next
`--load` resume picks by name and dies on):

1. every file is written into a `<base>.tmp-<pid>` staging directory
   (multi-host: ONE shared `<base>.tmp-mh<pid0>` staging dir, named by
   process 0 and broadcast over the coordination KV store — Orbax's
   collective save writes every host's shards into the same tree, which
   per-host staging dirs would tear apart);
2. a manifest (file list + sizes, sha256 of `dictionaries.bin` and the
   meta JSON, an Orbax-completion marker) is recorded LAST, after
   `wait_until_finished`, so its presence certifies the whole artifact;
3. the staging dir is `os.rename`d into place — atomic on POSIX, so a
   crash leaves either the old artifact or the new one, never a blend;
4. orphaned staging dirs from killed saves are swept by checkpoint
   rotation (model_facade._rotate_epoch_checkpoints).

Multi-host pods add a commit-barrier protocol on top (manifest format 2;
ROADMAP's deferred cross-host save-barrier item). All barriers ride the
jax.distributed coordination service (parallel/distributed.py
`commit_barrier`): host-side RPCs with real timeouts, safe on the async
commit thread.

    stage      proc 0 prepares the shared staging dir, broadcasts its
               name; barrier `stage` before any host writes into it
    flush      Orbax collective save + per-host wait_until_finished
    barrier    `commit` — NO host proceeds toward the manifest/rename
               until EVERY host's Orbax flush finished (a host killed
               here fails the barrier on the survivors, the save errors
               out manifest-less, and resume rejects the artifact)
    ack        each host writes `commit_ack.<process_index>` into the
               staged artifact; barrier `acks`
    commit     proc 0 alone writes the manifest (recording
               process_count + the ack set) and performs the atomic
               rename; barrier `committed` releases the peers
    verify     resume rejects any manifest whose recorded ack set is
               not exactly {0..process_count-1}

Async commits (`config.async_checkpointing`) defer everything after the
Orbax dispatch onto an `AsyncCommitter` thread: the step loop's save
stall shrinks to staging + array dispatch, while the barrier + manifest
+ rename + content-hash pass run behind it with bounded in-flight depth
and back-pressure. `drain()` (called in the trainer's `finally` and on
preemption) completes the pipeline deterministically before exit.

Restore is integrity-verified: `verify_checkpoint` re-checks the
manifest, `latest_valid_checkpoint` walks newest -> oldest past any
candidate that fails it, and `load_model` verifies before handing the
directory to Orbax so truncation fails fast with a named file instead of
an opaque pytree error deep in the restore. On a multi-host pod the
fallback walk is COLLECTIVE: hosts agree (min over local bests, re-voted
until unanimous) on one artifact, because each host walking backward
independently can land on different steps and deadlock the pod's
restore-time collectives.

Elastic topology-change restore (manifest format 3): any COMMITTED
artifact is restorable on any host count and mesh shape. The manifest
additionally records the save-time mesh plan (dp/tp/cp), the GLOBAL
parameter-tree structure/shapes/dtypes, and the data-pipeline cursor
(epoch + global row ordinal). `verify_checkpoint` stays strict about
COMMIT completeness (the ack set is checked against the manifest's own
recorded `process_count`, never the restore-time one) — an incomplete
commit is rejected on any topology, while a complete commit made at a
DIFFERENT topology verifies fine and is routed to the resharded-restore
path: `classify_restore` labels it `exact` or `resharded`, and
`load_model` builds its restore targets from the CURRENT mesh's
abstract-array metadata (shape/dtype/sharding of the live state
template) rather than the saved layout, so Orbax reshards params and
optimizer state on read. The collective fallback vote additionally
asserts every host reached the same reshard decision for the agreed
artifact.
"""

from __future__ import annotations

import hashlib
import itertools
import json
import os
import shutil
import threading
from typing import Callable, Optional

import jax
import numpy as np
import orbax.checkpoint as ocp

from code2vec_tpu import obs
from code2vec_tpu.parallel import distributed
from code2vec_tpu.parallel.distributed import BarrierTimeout  # re-export
from code2vec_tpu.parallel.mesh import MeshPlan
from code2vec_tpu.training.state import TrainState
from code2vec_tpu.utils.faults import fault_point

_STATE_DIR = "state"
_META_NAME = "code2vec_meta.json"
MANIFEST_NAME = "code2vec_manifest.json"
# Format 2 added the multi-host commit-protocol fields: `process_count`
# and `commit_acks` (the participant set that reached the post-flush
# barrier). Format 3 adds the elastic-restore topology record:
# `mesh_plan` (dp/tp/cp at save time), `param_tree` (global shapes and
# dtypes of every state leaf) and `data_cursor` (epoch + global row
# ordinal of the input pipeline). Every addition is strictly additive:
# format-1 (pre-barrier) and format-2 manifests remain loadable, and a
# format-3 manifest read by format-2 code just carries unknown keys.
MANIFEST_FORMAT = 3
ACK_PREFIX = "commit_ack."
RELEASED_SUFFIX = ".release"
# Commit-protocol working dirs: `.tmp-<pid>` is the staging dir a save
# builds in (`.tmp-mh<pid0>` when the pod shares one staging dir);
# `.old-<pid>` briefly holds the previous artifact while a same-path
# overwrite swaps the new one in.
STAGING_INFIX = ".tmp-"
BACKUP_INFIX = ".old-"
_SHARED_STAGING_TAG = "mh"

# Lockstep save ordinal: save_model is a collective call on a pod, so
# every process draws the same ordinal for the same save — it keys the
# barrier/KV names, making each rendezvous unique per save.
_save_ordinal = itertools.count()

# Default cross-host barrier timeout when the config carries none.
DEFAULT_BARRIER_TIMEOUT_S = 600.0

# Small files worth a full content hash in the manifest at save time.
# The Orbax state files are covered by existence+size in the commit-path
# manifest — hashing multi-GB shards before the commit would dominate
# checkpoint time, and Orbax already checksums its own payloads
# internally. Opt-in `config.checkpoint_hash_content` adds full-content
# hashes for everything AFTER the commit (`hash_artifact_content`),
# verified on resume.
_HASHED_FILES = ("dictionaries.bin", _META_NAME)


class CheckpointIntegrityError(RuntimeError):
    """An artifact failed its manifest/structure check. The message names
    the offending file so a truncated/corrupt checkpoint is diagnosable
    without spelunking Orbax internals."""


def _abs(path: str) -> str:
    return os.path.abspath(path)


def is_staging_path(path: str) -> bool:
    """True for commit-protocol working dirs (`<base>.tmp-<pid>` staging,
    `<base>.old-<pid>` overwrite backups) that must never be treated as
    artifacts."""
    name = os.path.basename(path.rstrip(os.sep))
    return STAGING_INFIX in name or BACKUP_INFIX in name


def staging_owner_alive(path: str) -> bool:
    """Does the process that created this staging/backup dir still run?
    Used by the sweeper so a concurrent save's in-flight staging dir is
    left alone while leftovers of killed saves are reclaimed. Unparseable
    names are treated as orphaned. Shared multi-host staging dirs
    (`.tmp-mh<pid0>`) are owned by process 0 — which is also the only
    process that runs the sweeper on a pod, so the liveness probe always
    runs on the machine that owns the pid."""
    name = os.path.basename(path.rstrip(os.sep))
    for infix in (STAGING_INFIX, BACKUP_INFIX):
        if infix in name:
            tail = name.rsplit(infix, 1)[1]
            break
    else:
        return False
    if tail.startswith(_SHARED_STAGING_TAG):
        tail = tail[len(_SHARED_STAGING_TAG):]
    try:
        pid = int(tail)
    except ValueError:
        return False
    if pid == os.getpid():
        return True
    try:
        os.kill(pid, 0)
        return True
    except ProcessLookupError:
        return False
    except PermissionError:
        return True  # exists, owned by another user


def parse_iter_name(path: str):
    """Parse a `<base>_iter<N>[_preempt]` artifact path into
    (epoch, is_preempt), or None if the tail is not of that form. Single
    source of truth for the epoch-checkpoint naming convention (written
    by model_facade's save_fn; consumed by rotation and resume). Staging
    dirs (`..._iter<N>.tmp-<pid>`) parse as None, so every consumer
    ignores them for free."""
    if "_iter" not in path:
        return None
    tail = path.rsplit("_iter", 1)[1]
    preempt = tail.endswith("_preempt")
    if preempt:
        tail = tail[: -len("_preempt")]
    try:
        return int(tail), preempt
    except ValueError:
        return None


def _sha256_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def hash_artifact_content(base: str, max_threads: int = 4) -> dict:
    """Record a full-content sha256 for EVERY manifest-listed file —
    including the multi-GB Orbax shards the manifest otherwise only
    size-checks — and rewrite the manifest atomically (tmp + rename).

    Meant to run AFTER the atomic commit (`config.checkpoint_hash_content`
    in save_model), so the hashing of large shards never extends the
    window in which a kill loses the save: a crash mid-hash just leaves a
    valid artifact without content hashes. Incremental 1 MB chunks on a
    thread pool (hashlib releases the GIL, so hashing overlaps I/O and
    scales past one core). Returns the updated manifest."""
    from concurrent.futures import ThreadPoolExecutor

    with obs.span("checkpoint_content_hash",
                  hist=obs.histogram(
                      "checkpoint_content_hash_seconds",
                      "post-commit full-content sha256 of one artifact")):
        manifest_path = os.path.join(base, MANIFEST_NAME)
        with open(manifest_path) as f:
            manifest = json.load(f)
        rels = sorted(manifest["files"])
        with ThreadPoolExecutor(max_workers=max_threads) as pool:
            digests = pool.map(
                lambda rel: _sha256_file(os.path.join(base, rel)), rels)
        for rel, digest in zip(rels, digests):
            manifest["files"][rel]["content_sha256"] = digest
        manifest["content_hashed"] = True
        tmp = manifest_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(manifest, f, indent=2)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, manifest_path)
        return manifest


def _fsync_dir(path: str) -> None:
    """Durably record a directory entry (the rename commit). Best-effort:
    some filesystems refuse O_RDONLY fsync on directories."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def write_commit_ack(staging: str, index: int) -> str:
    """Record this host's commit acknowledgment inside the staged
    artifact: a tiny `commit_ack.<process_index>` file proving the host
    survived to the post-flush barrier. The manifest (written after the
    ack barrier) records the full ack set; resume rejects artifacts
    whose recorded participant set is incomplete."""
    path = os.path.join(staging, f"{ACK_PREFIX}{index}")
    with open(path, "w") as f:
        json.dump({"process_index": index, "pid": os.getpid()}, f)
        f.flush()
        os.fsync(f.fileno())
    obs.counter("checkpoint_commit_acks_total",
                "per-host commit acknowledgments written after the "
                "post-flush barrier").inc()
    return path


def tree_summary(tree) -> dict:
    """Flatten a state pytree into {leaf path: {shape, dtype}} with
    GLOBAL shapes (a sharded jax.Array's `.shape` is its global shape).
    Recorded into the format-3 manifest so a restore onto any topology
    can check structural compatibility up front — a mismatched
    embedding size or optimizer layout fails with the offending leaf
    named instead of an opaque Orbax pytree error mid-restore."""
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    out = {}
    for path, leaf in flat:
        out[jax.tree_util.keystr(path)] = {
            "shape": [int(d) for d in getattr(leaf, "shape", ())],
            "dtype": str(getattr(leaf, "dtype", type(leaf).__name__)),
        }
    return out


def load_manifest(model_path: str) -> Optional[dict]:
    """The artifact's manifest dict, or None for pre-manifest (legacy)
    artifacts / unreadable files. Read-only convenience for the elastic
    restore path (topology classification + data cursor); integrity
    checking stays `verify_checkpoint`'s job."""
    path = os.path.join(_abs(model_path), MANIFEST_NAME)
    try:
        with open(path) as f:
            manifest = json.load(f)
    except (OSError, ValueError):
        return None
    return manifest if isinstance(manifest, dict) else None


def _config_mesh_plan(config) -> MeshPlan:
    """The run's mesh plan, tolerating config-like objects without the
    mesh knobs (missing axes default to 1, like an unset config)."""
    return MeshPlan(dp=int(getattr(config, "dp", 1)),
                    tp=int(getattr(config, "tp", 1)),
                    cp=int(getattr(config, "cp", 1)))


def classify_restore(manifest: Optional[dict], config=None) -> str:
    """Label a restore of a COMMITTED artifact under the current
    topology: "exact" (same process count and — when `config` is given —
    same dp/tp/cp mesh plan as at save time) or "resharded" (any
    difference; Orbax rebuilds the arrays against the current mesh's
    shardings). Legacy manifests without topology fields classify as
    "exact": they carry no record to differ from.

    Completeness is NOT judged here — `verify_checkpoint` rejects
    incomplete commits against the manifest's own recorded process
    count; this function only routes complete ones."""
    if not manifest:
        return "exact"
    saved_procs = manifest.get("process_count")
    if (saved_procs is not None
            and int(saved_procs) != distributed.process_count()):
        return "resharded"
    plan = manifest.get("mesh_plan")
    if (isinstance(plan, dict) and config is not None
            and MeshPlan.from_dict(plan) != _config_mesh_plan(config)):
        return "resharded"
    return "exact"


def _check_param_tree(manifest: Optional[dict], template, base: str) -> None:
    """Compare the manifest's recorded global parameter tree against the
    restore template; raise ValueError naming the first offending leaf.
    Only leaves the template wants are checked (a released load ignores
    the artifact's opt_state record and vice versa); manifests without
    the record (formats 1/2) skip the check."""
    saved = manifest.get("param_tree") if manifest else None
    if not isinstance(saved, dict):
        return
    want = tree_summary(template)
    missing = sorted(set(want) - set(saved))
    if missing:
        raise ValueError(
            f"{base}: restore template expects leaf {missing[0]} but the "
            f"artifact's recorded parameter tree has no such leaf — the "
            f"saved model/optimizer structure differs from this run's "
            f"configuration ({len(missing)} leaves missing in total).")
    for key, entry in sorted(want.items()):
        rec = saved[key]
        if list(rec.get("shape", ())) != entry["shape"]:
            raise ValueError(
                f"{base}: leaf {key} was saved with global shape "
                f"{rec.get('shape')} but this run expects "
                f"{entry['shape']}; the model configuration (vocab or "
                f"embedding sizes) differs from the artifact's. Note "
                f"that table rows are padded to a multiple of tp — a "
                f"mesh reshape needs a tp under which the padded shapes "
                f"agree with the artifact's.")
        if rec.get("dtype") != entry["dtype"]:
            raise ValueError(
                f"{base}: leaf {key} was saved as {rec.get('dtype')} but "
                f"this run expects {entry['dtype']}; match the precision "
                f"flags the artifact was saved with.")


def _write_manifest(base: str, epoch: int, released: bool,
                    process_count: int = 1,
                    topology: Optional[dict] = None) -> None:
    """Record every file in the (staged) artifact with its size, plus
    content hashes for the small sidecars. Written last: its presence is
    the Orbax-completion marker — `save_model` only writes it after
    `wait_until_finished` (and, on a pod, after the cross-host commit
    barrier), so a manifest-bearing directory is a fully flushed
    artifact. Records the participating process count and the commit-ack
    set found on disk; a manifest whose ack set is short of its
    process_count is rejected at verify time."""
    files = {}
    acks = []
    for root, _dirs, names in os.walk(base):
        for name in names:
            p = os.path.join(root, name)
            rel = os.path.relpath(p, base)
            if rel == MANIFEST_NAME:
                continue
            if rel.startswith(ACK_PREFIX) and os.sep not in rel:
                try:
                    acks.append(int(rel[len(ACK_PREFIX):]))
                except ValueError:
                    pass
            entry = {"size": os.path.getsize(p)}
            if rel in _HASHED_FILES:
                entry["sha256"] = _sha256_file(p)
            files[rel] = entry
    if process_count == 1 and not acks:
        acks = [0]  # single-process saves carry no ack files
    manifest = {
        "format": MANIFEST_FORMAT,
        "epoch": epoch,
        "released": released,
        "orbax_complete": True,
        "process_count": process_count,
        "commit_acks": sorted(acks),
        "files": files,
    }
    if topology:
        manifest.update(topology)
    path = os.path.join(base, MANIFEST_NAME)
    with open(path, "w") as f:
        json.dump(manifest, f, indent=2)
        f.flush()
        os.fsync(f.fileno())


def _commit_staging(staging: str, base: str) -> None:
    """Atomically promote a fully written staging dir to the final path.
    Overwrites swap through a `.old-<pid>` backup so there is never a
    moment with no artifact at `base`; a kill mid-swap leaves the backup
    for the sweeper and the verifier-guided fallback to sort out."""
    fault_point("checkpoint_commit")
    if os.path.isdir(base):
        backup = f"{base}{BACKUP_INFIX}{os.getpid()}"
        if os.path.isdir(backup):
            shutil.rmtree(backup)
        os.rename(base, backup)
        # A kill in this window leaves NOTHING at `base` but two intact
        # copies (`.tmp-` new, `.old-` previous); the sweeper promotes
        # whichever verifies (reclaim_orphan) instead of deleting them.
        fault_point("checkpoint_swap")
        os.rename(staging, base)
        shutil.rmtree(backup, ignore_errors=True)
    else:
        os.rename(staging, base)
    _fsync_dir(os.path.dirname(base) or ".")


def reclaim_orphan(path: str,
                   log: Optional[Callable[[str], None]] = None) -> str:
    """Reclaim one orphaned commit-protocol dir (a `.tmp-`/`.old-` whose
    owning process is gone). If the final name is unoccupied and the
    orphan passes verification — the kill-between-swap-renames window
    leaves exactly that — it is PROMOTED back via rename (a complete
    artifact must never be deleted while its slot sits empty); anything
    else is removed. Returns "promoted" or "removed"."""
    dirpart, name = os.path.split(os.path.abspath(path.rstrip(os.sep)))
    for infix in (STAGING_INFIX, BACKUP_INFIX):
        if infix in name:
            base = os.path.join(dirpart, name.rsplit(infix, 1)[0])
            break
    else:
        return "removed"  # not a commit-protocol dir; caller filtered wrong
    if not os.path.exists(base):
        try:
            verify_checkpoint(path)
        except CheckpointIntegrityError:
            pass
        else:
            os.rename(path, base)
            _fsync_dir(dirpart)
            if log is not None:
                log(f"Promoted orphaned-but-complete checkpoint {path} "
                    f"back to {base} (save was killed mid-commit)")
            return "promoted"
    shutil.rmtree(path, ignore_errors=True)
    return "removed"


def verify_checkpoint(model_path: str, check_content: bool = False) -> dict:
    """Probe an artifact against its manifest; returns the parsed meta on
    success, raises CheckpointIntegrityError naming the first offending
    file otherwise. Cheap by design (stat per file, hash only the small
    sidecars), so resume can probe a fallback chain and rotation can
    re-check candidates without meaningful cost.

    `check_content=True` additionally re-hashes every file carrying a
    post-commit `content_sha256` (written when the save ran with
    `checkpoint_hash_content`) — the resume path's deep probe; the
    rotation/fallback walks keep the cheap default.

    Pre-manifest (legacy) artifacts get a structural probe instead:
    required files present, meta parseable, Orbax state dir non-empty —
    enough to reject the blatant half-writes the old layout could leave.
    """
    with obs.span("checkpoint_verify",
                  hist=obs.histogram("checkpoint_verify_seconds",
                                     "manifest probe of one artifact")):
        try:
            return _verify_checkpoint_inner(model_path, check_content)
        except CheckpointIntegrityError:
            obs.counter("checkpoint_verify_failures_total",
                        "artifacts that failed their integrity check "
                        "(resume fallback walked past them)").inc()
            raise


def _verify_checkpoint_inner(model_path: str,
                             check_content: bool = False) -> dict:
    base = _abs(model_path)
    if not os.path.isdir(base):
        raise CheckpointIntegrityError(f"{base}: not a directory")
    manifest_path = os.path.join(base, MANIFEST_NAME)
    if not os.path.isfile(manifest_path):
        return _verify_legacy(base)
    try:
        with open(manifest_path) as f:
            manifest = json.load(f)
    except (OSError, ValueError) as e:
        raise CheckpointIntegrityError(
            f"{manifest_path}: unreadable or corrupt manifest ({e})")
    if not isinstance(manifest, dict) or not isinstance(
            manifest.get("files"), dict):
        raise CheckpointIntegrityError(
            f"{manifest_path}: malformed manifest (no file table)")
    if not manifest.get("orbax_complete"):
        raise CheckpointIntegrityError(
            f"{manifest_path}: Orbax completion marker missing — the save "
            f"was interrupted before wait_until_finished")
    if "process_count" in manifest:
        # Manifest format 2+: the save recorded its participant set. An
        # incomplete ack set means a host died between the commit
        # barrier and the manifest (or the manifest was hand-edited);
        # its shards may be missing from the artifact, so reject it.
        # The check is against the manifest's OWN process_count — never
        # the restore-time one — so a COMPLETE commit made at a
        # different topology verifies fine (classify_restore routes it
        # to the resharded-restore path); only INCOMPLETE commits are
        # rejected.
        want = int(manifest["process_count"])
        acks = manifest.get("commit_acks")
        try:
            got = (sorted({int(a) for a in acks})
                   if isinstance(acks, list) else None)
        except (TypeError, ValueError):
            got = None
        if got != list(range(want)):
            raise CheckpointIntegrityError(
                f"{manifest_path}: commit-ack participant set {got} is "
                f"not the full {want}-process set — a host did not "
                f"survive to the commit barrier; its shards cannot be "
                f"trusted to be in this artifact")
    for rel, entry in manifest["files"].items():
        p = os.path.join(base, rel)
        if not os.path.isfile(p):
            raise CheckpointIntegrityError(f"{p}: listed in manifest but missing")
        try:
            size = os.path.getsize(p)
            if size != entry.get("size"):
                raise CheckpointIntegrityError(
                    f"{p}: size {size} != manifest size {entry.get('size')} "
                    f"(truncated or partially written)")
            want_hash = entry.get("sha256")
            content_hash = (entry.get("content_sha256") if check_content
                            else None)
            if want_hash or content_hash:
                digest = _sha256_file(p)  # one pass serves both checks
                if want_hash and digest != want_hash:
                    raise CheckpointIntegrityError(
                        f"{p}: sha256 mismatch against manifest (corrupt)")
                if content_hash and digest != content_hash:
                    raise CheckpointIntegrityError(
                        f"{p}: content sha256 mismatch against manifest "
                        f"(bit-rot or size-preserving corruption)")
        except OSError as e:
            # A file that vanishes BETWEEN the isfile() probe and the
            # stat/hash is an artifact being swapped underneath us — on a
            # multi-host pod every host runs the same commit (staging
            # rename + backup swap) on the same final path, so a peer's
            # commit window can briefly empty the directory a rotation
            # probe is walking (the cross-host save barrier is a known
            # ROADMAP item). Degrade to the integrity error the callers
            # are built to tolerate (fallback walks skip the candidate;
            # resume retries older) instead of crashing the trainer.
            raise CheckpointIntegrityError(
                f"{p}: vanished or became unreadable mid-probe ({e}) — "
                f"concurrent commit/rotation by another process")
    return _load_meta_checked(base)


def _verify_legacy(base: str) -> dict:
    for rel in ("dictionaries.bin", _META_NAME):
        if not os.path.isfile(os.path.join(base, rel)):
            raise CheckpointIntegrityError(
                f"{os.path.join(base, rel)}: required file missing "
                f"(no manifest to consult; pre-manifest artifact)")
    meta = _load_meta_checked(base)
    state_dir = os.path.join(base, _STATE_DIR)
    if not os.path.isdir(state_dir) or not os.listdir(state_dir):
        raise CheckpointIntegrityError(
            f"{state_dir}: Orbax state directory missing or empty")
    return meta


def _load_meta_checked(base: str) -> dict:
    meta_path = os.path.join(base, _META_NAME)
    try:
        with open(meta_path) as f:
            return json.load(f)
    except (OSError, ValueError) as e:
        raise CheckpointIntegrityError(
            f"{meta_path}: unreadable or corrupt meta ({e})")


def _candidate_key(parsed) -> int:
    """Encode (epoch, is_preempt) as one integer preserving the resume
    preference order (newer epoch wins; at equal epoch the preemption
    artifact wins — see latest_valid_checkpoint)."""
    epoch, preempt = parsed
    return epoch * 2 + (1 if preempt else 0)


def _candidate_path(save_base: str, key: int) -> str:
    epoch, preempt = key // 2, bool(key % 2)
    return f"{save_base}_iter{epoch}" + ("_preempt" if preempt else "")


def _local_latest_valid(save_base: str, excluded,
                        log: Optional[Callable[[str], None]] = None,
                        trail: Optional[list] = None):
    """This host's newest verifying candidate (key, path), skipping any
    key in `excluded`; (None, None) if nothing verifies. `trail`, when
    given, collects one record per candidate CONSIDERED — the resume
    path surfaces it so a run that fell back past rejected artifacts
    says so loudly instead of silently starting older (or fresh)."""
    import glob
    candidates = []  # ((epoch, is_preempt), path)
    for p in glob.glob(save_base + "_iter*"):
        parsed = parse_iter_name(p)
        if parsed is None or _candidate_key(parsed) in excluded:
            continue
        candidates.append((parsed, p))
    for parsed, path in sorted(candidates, reverse=True):
        try:
            verify_checkpoint(path)
            if trail is not None:
                trail.append({"path": path, "outcome": "selected",
                              "reason": "passes verification"})
            return _candidate_key(parsed), path
        except CheckpointIntegrityError as e:
            obs.counter(
                "resume_artifacts_rejected_total",
                "resume candidates the fallback walk rejected").inc()
            if trail is not None:
                trail.append({"path": path, "outcome": "rejected",
                              "reason": str(e)})
            if log is not None:
                log(f"Skipping corrupt/partial checkpoint {path}: {e}")
    return None, None


def latest_valid_checkpoint(save_base: str,
                            log: Optional[Callable[[str], None]] = None,
                            collective: Optional[bool] = None,
                            trail: Optional[list] = None):
    """Newest `<save_base>_iter<N>[_preempt]` artifact that PASSES its
    integrity check (None if no candidate does). Walks newest -> oldest
    past corrupt/partial artifacts, logging each skip, so a save killed
    mid-write (or a disk that ate a file) costs at most the epochs since
    the last valid artifact instead of the whole run.

    At equal N the preemption artifact wins: it was written mid-epoch
    N+1, so its params are strictly more trained than the clean
    end-of-epoch-N save.

    On a multi-host pod (`collective=None` auto-detects; pass False to
    force a host-local walk, e.g. post-mortem tooling) the walk is a
    COLLECTIVE agreement: each host proposes its local best, the pod
    takes the minimum (the newest artifact every host accepts can only
    be <= each local best), every host re-verifies that candidate, and
    the vote repeats with the candidate excluded until unanimous — all
    hosts return the SAME path (or all None). Without this, hosts whose
    independent backward walks diverge restore different steps and
    deadlock the pod's first collective. The agreement covers the
    RESHARD decision too: once a path is unanimous, every host
    classifies it against the current topology and a divergence (e.g.
    one host reading a stale manifest copy) raises the loud desync
    error instead of letting the pod split between an exact and a
    resharded restore. Runs host collectives: main thread only."""
    if collective is None:
        collective = distributed.process_count() > 1
    if not collective or distributed.process_count() == 1:
        return _local_latest_valid(save_base, excluded=(), log=log,
                                   trail=trail)[1]
    excluded = set()
    while True:
        local_key, _local_path = _local_latest_valid(save_base, excluded,
                                                     log, trail=trail)
        proposal = -1 if local_key is None else local_key
        agreed = distributed.agree_scalar(proposal, "min")
        if agreed < 0:
            # At least one host verifies NOTHING (it also vetoes every
            # newer candidate its peers hold): resuming a subset would
            # desync the pod, so all hosts consistently start fresh.
            return None
        path = _candidate_path(save_base, agreed)
        try:
            verify_checkpoint(path)
            ok = 1.0
        except CheckpointIntegrityError as e:
            ok = 0.0
            if log is not None:
                log(f"Pod-agreed candidate {path} fails verification on "
                    f"this host: {e}")
        votes = distributed.allreduce_host_scalars(np.array([ok]))[0]
        if int(votes) == distributed.process_count():
            if log is not None and excluded:
                log(f"Pod agreed on fallback checkpoint {path} after "
                    f"excluding {len(excluded)} candidate(s)")
            # The reshard decision is part of the agreement: every host
            # must read the same manifest the same way, or the pod's
            # restore would mix exact and resharded templates.
            decision = (0 if classify_restore(load_manifest(path)) == "exact"
                        else 1)
            distributed.assert_host_agreement(
                decision, f"reshard decision for {os.path.basename(path)}")
            return path
        excluded.add(agreed)


# Back-compat name: the pre-manifest API returned the newest artifact by
# name alone; every caller now gets the verified walk.
latest_checkpoint = latest_valid_checkpoint


def resolve_load_path(model_load_path: str,
                      log: Optional[Callable[[str], None]] = None,
                      trail: Optional[list] = None) -> str:
    """Resolve a `--load` argument: a concrete artifact directory is
    returned as-is; anything else is treated as a save base and resolved
    to its newest VALID `_iter<N>` artifact, so resuming after a crash
    never requires the operator to guess which directory survived.
    `trail` collects the candidates considered/rejected along the way so
    the caller can report a degraded resume loudly."""
    base = _abs(model_load_path)
    if os.path.isdir(base) and (
            os.path.isfile(os.path.join(base, _META_NAME))
            or os.path.isfile(os.path.join(base, MANIFEST_NAME))):
        return base
    found = latest_valid_checkpoint(base, log=log, trail=trail)
    return found if found is not None else base


class AsyncCommitter:
    """Bounded background pipeline for the deferred half of a save.

    `save_model(..., committer=...)` stages the artifact and dispatches
    the Orbax write synchronously, then hands the rest — Orbax
    wait_until_finished, the cross-host commit barrier, acks, manifest,
    atomic rename, content-hash pass — to this single commit thread.
    The step loop's save stall shrinks to staging + dispatch.

    Guarantees kept from the synchronous protocol:
    - bounded in-flight depth with BACK-PRESSURE: `submit` blocks once
      `max_in_flight` commits are pending, so a slow filesystem can
      never queue unbounded half-finished saves;
    - commit failures are never silent: the first error re-raises on
      the next `submit` or `drain` (the trainer drains in its
      `finally`, so a failed commit fails the run);
    - `drain()` completes every pending commit deterministically —
      the preemption path drains BEFORE writing its own artifact, so
      exit always leaves a fully committed, resumable state."""

    def __init__(self, max_in_flight: int = 2,
                 log: Optional[Callable[[str], None]] = None):
        from concurrent.futures import ThreadPoolExecutor
        self._executor = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="c2v-ckpt-commit")
        self._slots = threading.Semaphore(max(1, int(max_in_flight)))
        self._lock = threading.Lock()
        self._futures = []
        self._errors = []
        self._depth = 0
        self._log = log
        self._g_depth = obs.gauge(
            "checkpoint_async_inflight",
            "async checkpoint commits currently pending")

    @property
    def in_flight(self) -> int:
        with self._lock:
            return self._depth

    def raise_pending(self) -> None:
        """Re-raise the first recorded commit failure (original
        exception object, so fault-injection drills see their own
        types). Clears it: the caller owns the error once raised."""
        with self._lock:
            if not self._errors:
                return
            label, err = self._errors.pop(0)
        raise err

    def submit(self, job: Callable[[], object], label: str) -> None:
        self.raise_pending()
        with obs.span("checkpoint_async_backpressure",
                      hist=obs.histogram(
                          "checkpoint_async_backpressure_seconds",
                          "save stalled waiting for an in-flight async "
                          "commit slot")):
            self._slots.acquire()  # back-pressure at max_in_flight

        def run():
            try:
                with obs.span("checkpoint_async_commit",
                              hist=obs.histogram(
                                  "checkpoint_async_commit_seconds",
                                  "deferred commit: orbax wait + barrier "
                                  "+ manifest + rename")):
                    job()
            except BaseException as e:  # noqa: BLE001 — surfaced on drain
                with self._lock:
                    self._errors.append((label, e))
                obs.counter("checkpoint_async_errors_total",
                            "async checkpoint commits that failed").inc()
                if self._log is not None:
                    self._log(f"Async checkpoint commit {label} FAILED: "
                              f"{type(e).__name__}: {e}")
            finally:
                with self._lock:
                    self._depth -= 1
                    self._g_depth.set(self._depth)
                self._slots.release()

        with self._lock:
            self._futures = [f for f in self._futures if not f.done()]
            self._futures.append(self._executor.submit(run))
            self._depth += 1
            self._g_depth.set(self._depth)

    def drain(self) -> None:
        """Block until every pending commit finished; re-raise the first
        failure. Idempotent and safe to call with nothing in flight."""
        from concurrent.futures import wait
        with self._lock:
            pending = list(self._futures)
        if pending:
            wait(pending)
        self.raise_pending()

    def close(self) -> None:
        """Drain (surfacing errors) and stop the commit thread."""
        try:
            self.drain()
        finally:
            self._executor.shutdown(wait=True)


def save_model(model_save_path: str, state: TrainState, vocabs, config,
               epoch: int = 0, released: bool = False,
               committer: Optional[AsyncCommitter] = None,
               on_committed: Optional[Callable[[], None]] = None,
               data_cursor: Optional[dict] = None) -> str:
    """Save a standalone model artifact at `<model_save_path>` (a directory
    is created): Orbax state + `dictionaries.bin` + config meta. Mirrors
    `Code2VecModelBase.save` (model_base.py:102-109).

    Crash-atomic: everything lands in a staging dir, the manifest is
    recorded last, and the staging dir is renamed into place (see the
    commit protocol in the module docstring). Multi-host pods add the
    commit-barrier protocol; the save is a COLLECTIVE call there. The
    `save` fault points between the steps are inert in production and
    let tests/test_chaos.py kill the save at every interesting boundary.

    With `committer` (async mode) the call returns after staging +
    Orbax dispatch; flush/barrier/manifest/rename run on the commit
    thread and `on_committed` (e.g. checkpoint rotation) fires there
    after a successful commit. The returned path is where the artifact
    WILL commit; callers needing it durable must drain the committer.

    `data_cursor` ({"epoch", "global_row_ordinal", ...}) is recorded
    verbatim into the format-3 manifest — the input-pipeline position
    this state corresponds to, which an elastic resume remaps to the new
    host count so no row is skipped or double-read."""
    with obs.span("checkpoint_save",
                  hist=obs.histogram(
                      "checkpoint_save_seconds",
                      "step-loop save stall: stage + flush + commit "
                      "(sync) or stage + dispatch (async)")):
        return _save_model_inner(model_save_path, state, vocabs, config,
                                 epoch, released, committer, on_committed,
                                 data_cursor)


def _barrier_timeout_s(config) -> float:
    return float(getattr(config, "save_barrier_timeout_s", 0)
                 or DEFAULT_BARRIER_TIMEOUT_S)


def _save_model_inner(model_save_path: str, state: TrainState, vocabs,
                      config, epoch: int, released: bool,
                      committer: Optional[AsyncCommitter] = None,
                      on_committed: Optional[Callable[[], None]] = None,
                      data_cursor: Optional[dict] = None) -> str:
    base = _abs(model_save_path) + (RELEASED_SUFFIX if released else "")
    nprocs = distributed.process_count()
    multi = nprocs > 1
    ordinal = next(_save_ordinal)  # lockstep: save_model is collective
    timeout_s = _barrier_timeout_s(config)
    if multi:
        # ONE shared staging dir for the whole pod (Orbax's collective
        # save interleaves every host's shards into the same tree), its
        # name chosen by process 0 and spread over the coordination KV
        # store. Process 0 prepares it; the `stage` barrier keeps peers
        # from writing into a directory that does not exist yet.
        proposal = (f"{base}{STAGING_INFIX}{_SHARED_STAGING_TAG}"
                    f"{os.getpid()}" if distributed.process_index() == 0
                    else None)
        staging = distributed.broadcast_from_primary(
            f"c2v:staging:{ordinal}:{os.path.basename(base)}", proposal,
            timeout_s)
        if distributed.process_index() == 0:
            if os.path.isdir(staging):
                shutil.rmtree(staging)  # leftover from a failed save
            os.makedirs(staging)
        distributed.commit_barrier(f"c2v:stage:{ordinal}", timeout_s)
    else:
        staging = f"{base}{STAGING_INFIX}{os.getpid()}"
        if os.path.isdir(staging):
            shutil.rmtree(staging)  # leftover from a failed save by this pid
        os.makedirs(staging)
    committing_host = not multi or distributed.process_index() == 0
    fault_point("save")   # 1: staging created, nothing written
    if committing_host:
        vocabs.save(os.path.join(staging, "dictionaries.bin"))
    fault_point("save")   # 2: vocab written, meta missing
    if committing_host:
        with open(os.path.join(staging, _META_NAME), "w") as f:
            json.dump({
                "released": released,
                "epoch": epoch,
                "step": int(np.asarray(state.step)),
                "token_vocab_size": vocabs.token_vocab.size,
                "path_vocab_size": vocabs.path_vocab.size,
                "target_vocab_size": vocabs.target_vocab.size,
                "token_embeddings_size": config.token_embeddings_size,
                "path_embeddings_size": config.path_embeddings_size,
                "separate_oov_and_pad": config.separate_oov_and_pad,
                # opt_state pytree structure depends on the update mode;
                # recorded so a mode mismatch fails with a clear error at
                # restore time instead of an opaque Orbax structure
                # mismatch.
                "use_sparse_embedding_update": bool(
                    getattr(config, "use_sparse_embedding_update", False)),
                # Adam moment dtypes shape the opt_state arrays; a restore
                # into a template with different dtypes can error or
                # silently cast depending on the Orbax version, so they're
                # recorded and checked like the sparse-mode flag above.
                "adam_mu_dtype": str(
                    getattr(config, "adam_mu_dtype", "float32")),
                "adam_nu_dtype": str(
                    getattr(config, "adam_nu_dtype", "float32")),
            }, f, indent=2)
    fault_point("save")   # 3: meta written, Orbax state missing
    # Orbax dispatch is synchronous in BOTH modes (it snapshots the
    # arrays); the flush wait is what async mode defers.
    ckptr = ocp.StandardCheckpointer()
    target = {"params": state.params, "step": state.step}
    if not released:
        target["opt_state"] = state.opt_state
    state_dir = os.path.join(staging, _STATE_DIR)
    ckptr.save(state_dir, target, force=True)

    # Format-3 topology record, captured host-side before the deferred
    # commit: the save-time mesh plan, the GLOBAL tree structure (a
    # sharded jax.Array's .shape is global), and the data cursor — what
    # an elastic restore needs to reshard onto any topology and resume
    # the input pipeline without skipping or double-reading rows.
    topology = {
        "mesh_plan": _config_mesh_plan(config).to_dict(),
        "param_tree": tree_summary(target),
    }
    if data_cursor is not None:
        topology["data_cursor"] = dict(data_cursor)

    def commit_job():
        try:
            with obs.span("checkpoint_orbax_flush",
                          hist=obs.histogram(
                              "checkpoint_orbax_flush_seconds",
                              "Orbax wait_until_finished (the bulk "
                              "bytes reaching disk)")):
                ckptr.wait_until_finished()
        finally:
            ckptr.close()
        fault_point("save")   # 4: Orbax flushed, manifest missing
        fault_point("async_commit")  # deferred commit work begins
        if multi:
            fault_point("barrier_enter")
            with obs.span("checkpoint_commit_barrier",
                          hist=obs.histogram(
                              "checkpoint_barrier_wait_seconds",
                              "wait at the cross-host post-flush commit "
                              "barrier")):
                distributed.commit_barrier(f"c2v:commit:{ordinal}",
                                           timeout_s)
            # every host survived the flush: ack, then wait for all acks
            write_commit_ack(staging, distributed.process_index())
            distributed.commit_barrier(f"c2v:acks:{ordinal}", timeout_s)
        if committing_host:
            _write_manifest(staging, epoch, released, process_count=nprocs,
                            topology=topology)
            fault_point("save")   # 5: fully staged, not yet committed
            _commit_staging(staging, base)
        fault_point("callback_crash")  # committed, completion pending
        if multi:
            # peers return only once the artifact is liftable at `base`
            distributed.commit_barrier(f"c2v:committed:{ordinal}",
                                       timeout_s)
        if committing_host and getattr(config, "checkpoint_hash_content",
                                       False):
            # Post-commit by design: the artifact is already durable, so
            # hashing the multi-GB shards never widens the crash window —
            # a kill mid-hash just leaves a valid artifact without
            # content hashes (which resume then simply doesn't check).
            try:
                hash_artifact_content(base)
            except OSError:
                # a peer's commit swapped the artifact mid-hash (the same
                # race verify_checkpoint degrades gracefully); the
                # surviving copy is covered by its own writer's hash pass
                obs.counter(
                    "checkpoint_content_hash_races_total",
                    "post-commit hash passes abandoned because a peer "
                    "swapped the artifact underneath them").inc()
        obs.counter("checkpoint_saves_total",
                    "committed checkpoint artifacts").inc()
        obs.gauge("checkpoint_last_save_unixtime",
                  "wall clock of the last committed save"
                  ).set_to_current_time()
        obs.gauge("checkpoint_last_save_epoch",
                  "epoch recorded in the last committed save").set(epoch)
        if on_committed is not None:
            on_committed()
        return base

    if committer is None:
        commit_job()
    else:
        try:
            committer.submit(commit_job,
                             label=f"{os.path.basename(base)}@{ordinal}")
        except BaseException:
            # submit resurfaced an EARLIER commit's failure before
            # accepting this job — but this save's Orbax write is
            # already dispatched and still streaming into the staging
            # dir. Settle it before re-raising, or a retry's staging
            # cleanup races the orphaned background write.
            try:
                ckptr.wait_until_finished()
            except Exception:
                pass
            finally:
                ckptr.close()
            raise
    return base


def load_model_meta(model_load_path: str) -> dict:
    base = _abs(model_load_path)
    with open(os.path.join(base, _META_NAME)) as f:
        return json.load(f)


def _abstract_restore_template(tree):
    """Restore targets built from the CURRENT state's abstract-array
    metadata: every live jax.Array leaf becomes a ShapeDtypeStruct
    carrying its (current-mesh) sharding, so Orbax lays the restored
    arrays out for the topology the run HAS, not the one the artifact
    was saved under — the mechanism behind elastic N->M restore. Host
    (numpy) leaves stay concrete and restore host-side as before."""
    def leaf(x):
        if isinstance(x, jax.Array):
            return jax.ShapeDtypeStruct(x.shape, x.dtype,
                                        sharding=x.sharding)
        return x
    return jax.tree.map(leaf, tree)


def load_model(model_load_path: str, state_like: TrainState,
               config=None, params_only: bool = False,
               report: Optional[dict] = None) -> TrainState:
    """Restore a standalone artifact saved by `save_model`. `state_like`
    provides structure/shardings; released artifacts keep `state_like`'s
    (fresh) optimizer state. `params_only` restores just params+step and
    never touches the saved optimizer state — the `--release` path, which
    must load artifacts regardless of their optimizer layout/dtypes (it
    is the advertised escape hatch for every optimizer-mismatch error
    below, so it cannot itself run those checks).

    The artifact is manifest-verified FIRST, so a truncated or
    half-written directory fails fast with the offending file named
    instead of surfacing as an opaque Orbax pytree error mid-restore.
    Resume is the deep probe: post-commit content hashes (saves made
    with `checkpoint_hash_content`) are re-checked here when present.

    Topology is ELASTIC: a complete commit made at a different host
    count or mesh shape restores fine — targets are abstract arrays
    built from `state_like`'s current shardings, the manifest's recorded
    global tree is checked against them first (mismatches name the
    offending leaf), and `report` (optional out-param) receives
    `resume_mode` ("exact" | "resharded"), the saved topology and the
    restored step for the caller's heartbeat/metrics."""
    base = _abs(model_load_path)
    meta = verify_checkpoint(base, check_content=True)
    manifest = load_manifest(base)
    mode = classify_restore(manifest, config)
    if report is not None:
        report["resume_mode"] = mode
        report["path"] = base
        if manifest:
            report["saved_process_count"] = manifest.get("process_count")
            report["saved_mesh_plan"] = manifest.get("mesh_plan")
            report["data_cursor"] = manifest.get("data_cursor")
    if mode == "resharded":
        # Read-only by design: a kill anywhere in the reshard restore
        # must leave the artifact untouched and re-restorable (the
        # chaos matrix arms this point to prove it).
        fault_point("reshard_restore")
        obs.counter("resume_resharded_restores_total",
                    "restores that rebuilt the arrays for a topology "
                    "other than the save-time one").inc()
    if params_only:
        template = {"params": state_like.params, "step": state_like.step}
        _check_param_tree(manifest, template, base)
        restore_args = ocp.checkpoint_utils.construct_restore_args(template)
        try:
            restore = ocp.args.PyTreeRestore(item=template,
                                             restore_args=restore_args,
                                             partial_restore=True)
        except TypeError:
            # orbax < 0.6 has no partial_restore kwarg; empty `transforms`
            # is that vintage's way to restore a subtree of the saved item
            # (drop the artifact's opt_state, keep params+step).
            restore = ocp.args.PyTreeRestore(item=template,
                                             restore_args=restore_args,
                                             transforms={})
        with ocp.PyTreeCheckpointer() as ckptr:
            restored = ckptr.restore(os.path.join(base, _STATE_DIR),
                                     args=restore)
        if report is not None:
            report["restored_step"] = int(np.asarray(restored["step"]))
        return TrainState(step=restored["step"], params=restored["params"],
                          opt_state=state_like.opt_state)
    if config is not None and not meta.get("released", False):
        saved_sparse = bool(meta.get("use_sparse_embedding_update", False))
        want_sparse = bool(getattr(config, "use_sparse_embedding_update",
                                   False))
        if saved_sparse != want_sparse:
            raise ValueError(
                f"{base} was saved with use_sparse_embedding_update="
                f"{saved_sparse} but this run has "
                f"use_sparse_embedding_update={want_sparse}; the optimizer "
                f"state layouts are incompatible. Either set the flag to "
                f"match, or `--release` the artifact first (a released "
                f"model carries no optimizer state and loads under either "
                f"mode).")
        for knob in ("adam_mu_dtype", "adam_nu_dtype"):
            saved = meta.get(knob)
            want = str(getattr(config, knob, "float32"))
            # artifacts predating this meta entry carry no record (the
            # default changed over time) — nothing to check against
            if saved is not None and saved != want:
                raise ValueError(
                    f"{base} was saved with {knob}={saved} but this run "
                    f"has {knob}={want}; the optimizer-moment dtypes "
                    f"differ and a restore would corrupt or miscast the "
                    f"moments. Pass --{knob} {saved} to resume this "
                    f"artifact, or `--release` it first (released models "
                    f"carry no optimizer state).")
    template = {"params": state_like.params, "step": state_like.step}
    if not meta.get("released", False):
        template["opt_state"] = state_like.opt_state
    _check_param_tree(manifest, template, base)
    ckptr = ocp.StandardCheckpointer()
    restored = ckptr.restore(os.path.join(base, _STATE_DIR),
                             _abstract_restore_template(template))
    ckptr.close()
    if report is not None:
        report["restored_step"] = int(np.asarray(restored["step"]))
    return TrainState(
        step=restored["step"],
        params=restored["params"],
        opt_state=restored.get("opt_state", state_like.opt_state))


def release_model(model_load_path: str, model_save_path: Optional[str],
                  state_like: TrainState, vocabs, config) -> str:
    """Load a trainable artifact and re-save it weights-only
    (reference: tensorflow_model.py:131-135 saves `<load>.release`).
    Loads params-only: releasing discards the optimizer state, so a
    saved-vs-current optimizer layout/dtype mismatch must not block it."""
    state = load_model(model_load_path, state_like, params_only=True)
    out = model_save_path or model_load_path
    return save_model(out, state, vocabs, config, released=True)
