from code2vec_tpu.training.state import (  # noqa: F401
    TrainState, make_optimizer, init_params, create_train_state,
)
from code2vec_tpu.training.step import TrainStepBuilder  # noqa: F401
